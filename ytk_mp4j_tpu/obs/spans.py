"""Bounded span ring + Chrome-trace export.

Every phase event the always-on :class:`~ytk_mp4j_tpu.utils.stats.
CommStats` books (wire/reduce/serialize, at chunk granularity) and
every outermost collective call the ``trace.traced`` wrapper times is
also appended here as a *span*: ``(name, category, start, duration,
rank, thread, args)``. The ring is bounded (``MP4J_SPAN_RING`` entries,
default 65536; 0 disables) so a long job keeps a sliding window of the
most recent activity at a fixed memory cost, and appending is one
O(1) ``deque.append`` — cheap enough to stay default-on.

:func:`export_chrome_trace` renders the ring as trace-event JSON
(``{"traceEvents": [...]}``, complete-event ``"ph": "X"`` records with
``ts``/``dur`` in microseconds, ``pid`` = mp4j rank, ``tid`` = a small
per-process thread id), loadable in ``chrome://tracing`` or Perfetto.
Multi-process jobs export one file per rank; ``mp4j-scope merge``
combines them into a single timeline (ranks keep distinct pids).
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any

from ytk_mp4j_tpu.utils import tuning

_lock = threading.Lock()
# Trace timebase: spans are recorded in perf_counter time (cheap,
# monotone) but EXPORTED anchored to the wall clock — perf_counter
# epochs are per-process, so independently launched ranks would
# otherwise shift by their launch skew in a merged timeline. Residual
# cross-host skew is whatever NTP leaves (ms-scale), fine for eyeballs.
_epoch = time.perf_counter()
_epoch_wall = time.time()
_capacity = tuning.span_ring_capacity()
_ring: collections.deque = collections.deque(maxlen=max(_capacity, 1))
_enabled = _capacity > 0
_tids: dict[int, int] = {}        # thread ident -> small stable tid


def enabled() -> bool:
    return _enabled


def configure(capacity: int) -> None:
    """Resize (and clear) the ring; 0 disables recording. Mainly for
    tests and embedding applications — jobs configure via
    ``MP4J_SPAN_RING``."""
    global _ring, _capacity, _enabled
    with _lock:
        _capacity = capacity
        _enabled = capacity > 0
        _ring = collections.deque(maxlen=max(capacity, 1))


def clear() -> None:
    with _lock:
        _ring.clear()


def _tid() -> int:
    ident = threading.get_ident()
    tid = _tids.get(ident)
    if tid is None:
        with _lock:
            tid = _tids.setdefault(ident, len(_tids))
    return tid


def record(name: str, cat: str, t0: float, dur: float,
           pid: int | None, args: dict[str, Any] | None = None) -> None:
    """Append one complete span (``t0`` in ``time.perf_counter``
    seconds). Bounded ring: the oldest span falls off when full."""
    if not _enabled:
        return
    _ring.append((name, cat, t0, dur, pid or 0, _tid(), args))


def phase(name: str, seconds: float, pid: int | None, collective: str,
          seq: int, **extra) -> None:
    """A phase span (wire/reduce/serialize) booked after the fact: the
    caller measured ``seconds`` ending now, so the span's start is
    reconstructed as ``now - seconds``."""
    if not _enabled:
        return
    end = time.perf_counter()
    args: dict[str, Any] = {"collective": collective, "seq": seq}
    for k, v in extra.items():
        if v is not None:
            args[k] = v
    _ring.append((name, "phase", end - seconds, seconds, pid or 0,
                  _tid(), args))


def mark(name: str, pid: int | None, **args: Any) -> None:
    """A zero-duration recovery event (abort announced, retry started,
    terminal abort) — renders as an instant tick on the rank's
    timeline, so ``mp4j-scope`` traces show exactly where a job
    recovered (ISSUE 5)."""
    if not _enabled:
        return
    _ring.append((name, "recovery", time.perf_counter(), 0.0, pid or 0,
                  _tid(), {k: v for k, v in args.items()
                           if v is not None} or None))


def collective(name: str, t0: float, dur: float, pid: int | None,
               seq: int) -> None:
    """The outermost collective-call span (emitted by trace.traced)."""
    if not _enabled:
        return
    _ring.append((name, "collective", t0, dur, pid or 0, _tid(),
                  {"seq": seq}))


def snapshot() -> list[tuple]:
    with _lock:
        return list(_ring)


def export_chrome_trace(path: str) -> int:
    """Write the ring as trace-event JSON; returns the event count.

    Events are globally sorted by start time, so ``ts`` is monotone
    non-decreasing on every (pid, tid) track — the invariant the tier-1
    schema test asserts and Perfetto's importer expects.
    """
    events = []
    for name, cat, t0, dur, pid, tid, args in sorted(
            snapshot(), key=lambda s: s[2]):
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": round((t0 - _epoch + _epoch_wall) * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "pid": pid,
            "tid": tid,
        }
        if args:
            ev["args"] = args
        events.append(ev)
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return len(events)


def merge_chrome_traces(out_path: str, in_paths: list[str]) -> int:
    """Merge per-rank Chrome-trace files into one timeline (ranks keep
    their pids; events re-sorted by ``ts`` so every track stays
    monotone). Accepts both the object form (``{"traceEvents": [...]}``)
    and the bare-array form of the trace-event format."""
    merged: list[dict] = []
    for p in in_paths:
        with open(p, encoding="utf-8") as fh:
            doc = json.load(fh)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        merged.extend(events)
    merged.sort(key=lambda e: (e.get("ts", 0)))
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, fh)
    return len(merged)
