"""mp4j-audit — collective correctness auditing (ISSUE 8).

The third observability plane: mp4j-scope (ISSUE 3) sees *time*, the
metrics plane (ISSUE 6) sees *volume*; this plane sees *content*. Every
outermost collective on the socket backend gets a **digest record**
``(seq, family, operand sig, input digest, output digest)`` appended to
a bounded per-rank ring; in ``verify`` mode records also carry
**per-frame wire digests** (composable ``zlib.crc32`` over the exact
bytes the wire sees, folded at the Channel SPI so tcp and shm get them
for free, with transport attribution) and ship to the master as
heartbeat deltas, where :class:`ClusterAuditor` folds them and flags
any collective ordinal where ranks disagree — naming the ordinal, the
family and the minority ranks. ``capture`` mode additionally stores the
input payloads so ``mp4j-scope replay`` can re-execute the captured
schedule in-process on the thread backend and diff digests
record-by-record: offline reproduction of a divergence with no cluster.

Two digest algorithms, chosen for what each audits:

- **payload digests** (collective inputs/outputs) use a block-
  positional u64 xor hash over the canonicalized bytes
  (``ascontiguousarray`` + native byte order — the false-divergence
  hazard mp4j-lint R13 guards): the payload's u64 words split into 16
  contiguous blocks, each xor-reduced in one vectorized pass, and the
  16 block values combine with odd per-block weights. Measured 21-35
  GB/s on the bench host vs ~11 for a u64 ``np.dot`` polynomial and
  ~1 for ``zlib.crc32`` — the difference between a default-on
  ``digest`` mode and one nobody would leave enabled. Detection
  power matches the threat model (corruption, not adversaries): any
  flipped BIT changes exactly one block's xor and therefore the
  digest, always; transpositions across blocks change two weighted
  terms; only a reorder of equal-width words WITHIN one 1/16th block
  — not a shape wire corruption can take — escapes.
- **wire digests** (verify mode) use composable ``zlib.crc32`` folds —
  ``crc32(b, crc32(a)) == crc32(a + b)`` — over the exact bytes each
  channel/raw exchange moves, keyed per (peer, direction, transport).
  Folding is boundary-invariant, so the sender's per-buffer folds and
  the receiver's chunked receive folds agree whenever the byte STREAM
  agrees; a flipped bit anywhere in flight makes the pair's folds
  disagree, which the master reports as a wire divergence naming both
  ranks and the transport. Crucially this catches *consistent-wrong*
  corruption too: a corrupted contribution folded into a reduce makes
  every rank's output equal-but-wrong (output digests agree!), but the
  sender's clean send-fold vs the receiver's corrupted recv-fold still
  disagree.

Digest semantics per payload kind (job-wide canonical, see
:func:`digest_payload`): arrays digest their canonical bytes mixed with
dtype token and element count; maps digest as an ORDER-INSENSITIVE sum
of per-item (key, value) mixes, so dict iteration order — which
legitimately differs across ranks — can never cause a false
divergence; lists digest positionally; everything else digests its
pickle (deterministic for the plain keys/values that ride the wire).

Which families are cross-rank comparable: the replicated-output
collectives (:data:`REPLICATED`) — allreduce/broadcast/allgather for
arrays and maps, including the columnar map plane and the two-level
schedules, whose outputs are bitwise identical on every rank by
contract. Rooted/scattered families still record (and replay, and
family-compare: a rank running a DIFFERENT collective at the same
ordinal is flagged as schedule divergence), but their outputs
legitimately differ per rank and are never digest-compared.

This module deliberately imports nothing from ``comm`` at module scope
(the obs discipline); the replay driver imports the thread backend
lazily inside the function.
"""

from __future__ import annotations

import base64
import collections
import json
import os
import pickle
import threading
import time
import zlib

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.obs import spans as spans_mod
from ytk_mp4j_tpu.utils import tuning

_MASK = (1 << 64) - 1
_PRIME = 0x9E3779B97F4A7C15       # odd -> per-position injectivity
_PRIME2 = 0xBF58476D1CE4E5B9

# collectives whose OUTPUT is replicated bitwise on every rank — the
# set the master digest-compares (ISSUE 8 tentpole). Rooted families
# record but only family-compare.
REPLICATED = frozenset({
    "allreduce_array", "broadcast_array", "allgather_array",
    "allreduce_map", "broadcast_map", "allgather_map",
})

# capture-mode payloads above this size are not captured (the record
# keeps digests + a "capskip" flag); bounds per-record memory like the
# ring bounds record count
CAPTURE_MAX_BYTES = 8 * 1024 * 1024


# ----------------------------------------------------------------------
# payload digests (u64 polynomial hash, vectorized)
# ----------------------------------------------------------------------
_BLOCKS = 16
# odd per-block weights: position across blocks is load-bearing
_BLOCK_W = ((np.arange(1, _BLOCKS + 1, dtype=np.uint64)
             * np.uint64(_PRIME)) | np.uint64(1))


def _mix(h: int) -> int:
    """splitmix64-style finalizer: diffuses low-entropy inputs so
    combined digests (sums, xors) don't cancel structurally."""
    h &= _MASK
    h = ((h ^ (h >> 30)) * _PRIME2) & _MASK
    h = ((h ^ (h >> 27)) * 0x94D049BB133111EB) & _MASK
    return h ^ (h >> 31)


def digest_bytes(buf) -> int:
    """Block-positional u64 digest of a contiguous bytes-like (see
    the module docstring for the detection-power argument).

    The u64 main body splits into 16 CONTIGUOUS blocks, each
    xor-reduced in one vectorized pass (contiguous rows keep numpy at
    memory bandwidth — a strided 16-lane layout measured 4x slower),
    then combines with odd per-block weights; the division remainder
    words, the sub-8-byte tail and the total length fold in
    afterwards, so ``b"a" + b"\\0"`` and ``b"a"`` differ.
    """
    u8 = np.frombuffer(buf, dtype=np.uint8)
    n = u8.size
    n8 = n >> 3
    h = 0
    if n8:
        words = u8[:n8 * 8].view(np.uint64)
        m = (n8 // _BLOCKS) * _BLOCKS
        if m:
            blocks = np.bitwise_xor.reduce(
                words[:m].reshape(_BLOCKS, -1), axis=1)
            h = int((blocks * _BLOCK_W).sum())
        for t in words[m:]:
            h = (h * _PRIME + int(t)) & _MASK
    tail = u8[n8 * 8:]
    if tail.size:
        h = (h * _PRIME + int.from_bytes(tail.tobytes(), "little")) & _MASK
    return _mix(h ^ ((n * _PRIME2) & _MASK))


def _dtype_token(dt: np.dtype) -> str:
    # wire name, mirroring transport.channel: extension float dtypes
    # (kind 'V') go by NAME because their .str decodes as raw void
    return dt.name if dt.kind == "V" else dt.str


def canon_array(a: np.ndarray) -> np.ndarray:
    """Canonical digest form of an array: contiguous, native byte
    order. Two ranks holding the SAME values in different memory
    layouts (a strided view; a big-endian wire relic) must digest
    identically — the false-divergence hazard mp4j-lint R13 exists
    for."""
    if a.dtype.byteorder == ">":
        a = a.astype(a.dtype.newbyteorder("="))
    return np.ascontiguousarray(a)


def digest_array(a: np.ndarray) -> int:
    a = canon_array(a)
    try:
        body = a.view(np.uint8).reshape(-1)
    except (TypeError, ValueError):
        # object / unviewable dtypes digest their pickle
        return digest_obj(a.tolist())
    h = digest_bytes(body)
    return _mix(h ^ zlib.crc32(_dtype_token(a.dtype).encode())
                ^ ((a.size * _PRIME) & _MASK))


def digest_obj(x) -> int:
    """Pickle-based digest for scalars/keys/odd values (deterministic
    for the plain ints/strings/tuples that ride the wire; an
    unpicklable object digests as a fixed sentinel — same on every
    rank, so it can never false-diverge, it just audits as opaque)."""
    try:
        return digest_bytes(pickle.dumps(x, protocol=4))
    except Exception:
        return _mix(0xDEAD)


def digest_payload(x) -> tuple[int, str]:
    """``(digest, operand signature)`` of one collective payload.

    The signature is a human/replay hint (``<f8[120000]``,
    ``map[800]``), not part of the digest; cross-rank comparison uses
    the digest only (map sizes legitimately differ pre-merge)."""
    if isinstance(x, np.ndarray):
        return digest_array(x), f"{_dtype_token(x.dtype)}[{x.size}]"
    if isinstance(x, dict):
        # order-insensitive combine: sum of per-item mixes mod 2^64 —
        # dict iteration order differs across ranks by construction
        h = 0
        for k, v in x.items():
            vh = (digest_array(v) if isinstance(v, np.ndarray)
                  else digest_obj(v))
            h = (h + _mix(digest_obj(k)
                          ^ ((vh * _PRIME) & _MASK))) & _MASK
        return _mix(h ^ ((len(x) * _PRIME2) & _MASK)), f"map[{len(x)}]"
    if isinstance(x, (list, tuple)):
        h = 0
        for i, v in enumerate(x):
            vh = (digest_array(v) if isinstance(v, np.ndarray)
                  else digest_obj(v))
            h = (h * _PRIME + _mix(vh ^ i)) & _MASK
        return _mix(h), f"list[{len(x)}]"
    if x is None:
        return _mix(1), "none"
    return digest_obj(x), type(x).__name__


def _payload_nbytes_floor(x) -> int:
    """A LOWER bound on a payload's serialized size, one cheap walk:
    array buffers only (pickle can never be smaller than the raw
    bytes). Used to skip capture-mode pickling of payloads that are
    certainly over the cap; an underestimate only costs the (bounded)
    pickle-then-discard pass it exists to avoid."""
    if isinstance(x, np.ndarray):
        return x.nbytes
    if isinstance(x, dict):
        return sum(v.nbytes for v in x.values()
                   if isinstance(v, np.ndarray))
    if isinstance(x, (list, tuple)):
        return sum(v.nbytes for v in x if isinstance(v, np.ndarray))
    return 0


def fold_wire(crc: int, buf) -> int:
    """One composable wire-digest fold (zlib.crc32). Boundary-
    invariant: folding a stream in any chunking yields the same value,
    so sender-side per-buffer folds match receiver-side chunked-receive
    folds whenever the bytes match."""
    return zlib.crc32(buf, crc)


# ----------------------------------------------------------------------
# the per-rank audit ring
# ----------------------------------------------------------------------
class AuditRing:
    """Per-slave audit state: the bounded record ring, the current
    collective's wire-digest accumulators, and the heartbeat delta
    cursor.

    Modes (``MP4J_AUDIT``): ``digest`` records in/out digests only
    (record-only — nothing ships); ``verify`` adds the per-frame wire
    folds and ships records on the heartbeat; ``capture`` adds input
    payload capture for offline replay. ``off`` is represented by NOT
    constructing a ring at all (the slave keeps ``_audit = None``), so
    the disabled hot path is one attribute check.

    Thread-safety: ``on_wire`` may run on the send-helper thread
    concurrently with the collective thread's hooks; the ring lock
    serializes both. Exactly one collective is in flight per slave
    (the socket backend's contract), so the wire accumulators need no
    seq key — ``begin`` clears them, ``commit``/``abandon`` collects.
    """

    def __init__(self, mode: str | None = None, rank: int | None = None,
                 capacity: int | None = None):
        self.mode = tuning.audit_mode(mode)
        if self.mode == "off":
            raise Mp4jError("AuditRing(mode='off'): keep audit=None "
                            "instead of an off ring")
        self.rank = rank
        # set by the owning slave after rendezvous: the dump carries it
        # so replay knows the TRUE job size even when the highest
        # rank(s) died without leaving a bundle
        self.slave_num: int | None = None
        # rank replacement (ISSUE 10): a joining spare inherits the
        # last cross-rank-verified ordinal from the adoption manifest,
        # so its ring starts ALIGNED — every record it ever writes has
        # seq > watermark, and postmortem/replay readers know ordinals
        # at or below it were verified before this rank even existed
        self.watermark = 0
        self.wire_on = self.mode in ("verify", "capture")
        self.ships = self.mode in ("verify", "capture")
        self.captures = self.mode == "capture"
        cap = tuning.audit_ring() if capacity is None else int(capacity)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=cap)
        self._shipped = 0       # records already taken as a delta
        self._dropped = 0       # records that fell off unshipped
        self._appended = 0      # records ever appended (sink cursor)
        # current-collective wire folds: (peer, dir) -> [crc, bytes,
        # transport]
        self._wire: dict = {}

    # -- recording (collective thread + send helper) --------------------
    def begin(self, seq: int, family: str, payload, meta: dict) -> dict:
        """Open the record for outermost collective ``seq``: digest the
        input, optionally capture it, clear the wire accumulators."""
        h, sig = digest_payload(payload)
        rec = {"seq": int(seq), "fam": family, "sig": sig, "in": h,
               "out": None, **meta}
        if self.captures:
            cap = self._capture(payload)
            if cap is not None:
                rec["cap"] = cap
            else:
                rec["capskip"] = True
        with self._lock:
            self._wire.clear()
        return rec

    @staticmethod
    def _capture(payload) -> str | None:
        # cheap LOWER bound on the pickle size first: a 2 GB buffer
        # must not pay a full serialize pass (and a transient 2x
        # allocation) on the collective thread just to be discarded
        # as oversized — pickle of an ndarray is >= its nbytes
        if _payload_nbytes_floor(payload) > CAPTURE_MAX_BYTES:
            return None
        try:
            raw = pickle.dumps(payload, protocol=4)
        except Exception:
            return None
        if len(raw) > CAPTURE_MAX_BYTES:
            return None
        return base64.b64encode(zlib.compress(raw, 1)).decode("ascii")

    def on_wire(self, peer, direction: str, bufs, transport: str) -> None:
        """Fold wire bytes into the current collective's (peer,
        direction) accumulator — called from the Channel SPI
        (framed/columnar frames) and from the raw exchange (the native
        poll loop and the shm rings move bytes below the Python
        channel primitives, so the raw plane folds whole segments at
        exchange granularity; crc composability makes the two
        bookkeeping units comparable)."""
        if peer is None:
            return
        key = (int(peer), direction)
        with self._lock:
            ent = self._wire.get(key)
            if ent is None:
                ent = self._wire[key] = [0, 0, transport]
            for b in bufs:
                ent[0] = fold_wire(ent[0], b)
                # mp4j-lint: disable=R13 (length read, not a byte serialization)
                ent[1] += memoryview(b).nbytes

    def put_wire(self, folds: dict) -> None:
        """Install precomputed per-collective wire folds for the
        record about to :meth:`commit` (ISSUE 11): the nonblocking
        engine interleaves several collectives on the wire, so it
        folds each collective's legs into its OWN accumulator —
        ``{(peer, direction): [crc, nbytes, transport]}`` — and
        installs them here one record at a time, keeping the
        cross-rank pairwise wire comparison exact whatever the local
        interleaving was."""
        with self._lock:
            self._wire.clear()
            self._wire.update({k: list(v) for k, v in folds.items()})

    def reset_wire(self) -> None:
        """Drop the in-flight attempt's wire folds — called from the
        recovery restore path: a retried collective's failed attempt
        put bytes on a torn epoch's wire that the peer never folded
        (they died in the drain), so carrying them into the record
        would false-diverge every recovered seq."""
        with self._lock:
            self._wire.clear()

    def _collect_wire(self) -> dict | None:
        with self._lock:
            if not self._wire:
                return None
            out: dict = {}
            for (peer, direction), (crc, nbytes, transport) in \
                    self._wire.items():
                e = out.setdefault(str(peer), {"t": transport})
                e["s" if direction == "send" else "r"] = [crc, nbytes]
            self._wire.clear()
            return out

    def commit(self, rec: dict, payload) -> dict:
        """Close the record: digest the output, attach the wire folds,
        append to the ring."""
        h, sig = digest_payload(payload)
        rec["out"] = h
        rec["osig"] = sig
        if self.wire_on:
            w = self._collect_wire()
            if w:
                rec["wire"] = w
        self._append(rec)
        return rec

    def abandon(self, rec: dict, error: BaseException) -> None:
        """The collective raised terminally: record the attempt with
        the error instead of an output digest (the master skips digest
        comparison for errored records; postmortem/replay still see
        where the schedule stopped)."""
        rec["err"] = repr(error)[:200]
        rec.pop("cap", None)    # a failed record cannot replay
        with self._lock:
            self._wire.clear()
        self._append(rec)

    def _append(self, rec: dict) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                # the oldest record falls off: a shipped one just
                # advances the cursor; an UNSHIPPED one is a reportable
                # loss (the heartbeat delta carries the drop count)
                if self._shipped > 0:
                    self._shipped -= 1
                elif self.ships:
                    self._dropped += 1
            self._ring.append(rec)
            self._appended += 1

    # -- reading / shipping ---------------------------------------------
    def records(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def read_since(self, cursor: int) -> tuple[int, list[dict], int]:
        """``(new_cursor, records, dropped)`` — records appended since
        ``cursor`` that are still in the ring, plus the count that
        already fell off. Non-destructive and independent of the
        heartbeat's ``take_delta`` cursor: the durable sink (ISSUE 9)
        reads here without stealing records from the master's
        verification stream. Capture payloads stay out — the sink is a
        telemetry stream, the bundle carries bytes."""
        with self._lock:
            total, recs, dropped = spans_mod.ring_delta(
                self._ring, self._appended, cursor)
            return (total,
                    [{k: v for k, v in r.items() if k != "cap"}
                     for r in recs],
                    dropped)

    def take_delta(self) -> dict | None:
        """Records appended since the last take — the heartbeat
        payload (verify/capture modes; bounded by the ring: records
        that fell off unshipped are reported as a drop count, never
        silently lost). Capture payloads do NOT ride the heartbeat —
        the control plane carries digests, the bundle carries bytes."""
        if not self.ships:
            return None
        with self._lock:
            fresh = len(self._ring) - self._shipped
            if fresh <= 0 and not self._dropped:
                return None
            recs = list(self._ring)[-fresh:] if fresh > 0 else []
            self._shipped = len(self._ring)
            dropped, self._dropped = self._dropped, 0
        out = {"records": [{k: v for k, v in r.items() if k != "cap"}
                           for r in recs]}
        if dropped:
            out["dropped"] = dropped
        return out

    def dump(self) -> dict:
        """The postmortem-bundle / replay-bundle document
        (``audit.json``). ``watermark`` is nonzero only for an adopted
        joiner (ISSUE 10): the verified ordinal it inherited."""
        return {"rank": self.rank, "mode": self.mode,
                "slave_num": self.slave_num,
                "watermark": self.watermark,
                "records": self.records()}


# ----------------------------------------------------------------------
# master-side verification (pure state machine; comm/master.py owns it)
# ----------------------------------------------------------------------
_PENDING_CAP = 512


class ClusterAuditor:
    """Folds per-rank digest records and verifies each collective
    ordinal once every live rank has reported it.

    Checks per complete seq:

    - **schedule**: every rank must be running the same collective
      family at the same ordinal (a cheap mismatched-schedule
      detector that works even for rooted families);
    - **output digests** for :data:`REPLICATED` families: all ranks
      must agree bitwise; a disagreement names the minority ranks;
    - **wire digests** (when present): for every ordered pair, rank
      a's send-fold to b must equal b's recv-fold from a — the check
      that catches consistent-wrong corruption (a flipped byte folded
      into a reduce gives every rank the same wrong output) and
      attributes it to a transport;
    - **retry snapshots** are checked rank-locally at restore time
      (see ``comm/process_comm.py``), not here.

    NOT thread-safe: the owner (the master, under its lock)
    serializes folds. Log lines for NEW divergences are returned so
    the owner can emit them outside its lock.
    """

    def __init__(self, slave_num: int):
        self.slave_num = slave_num
        self._pending: dict[int, dict[int, dict]] = {}
        self.verified_seq = 0       # highest seq verified clean
        self.verified_total = 0     # seqs verified clean, lifetime
        self.divergence_total = 0
        self.divergences: collections.deque = collections.deque(maxlen=64)
        self.dropped_records = 0    # slaves' rings overflowed unshipped
        self.unverified_dropped = 0  # pending seqs pruned incomplete
        self.rank_seq: dict[int, int] = {}   # highest audited seq/rank

    def fold(self, rank: int, delta: dict | None,
             live: set[int]) -> list[str]:
        """Fold one heartbeat's audit delta; returns log lines for
        newly detected divergences."""
        if not delta:
            return []
        self.dropped_records += int(delta.get("dropped", 0))
        lines: list[str] = []
        for rec in delta.get("records", ()):
            try:
                seq = int(rec["seq"])
            except (KeyError, TypeError, ValueError):
                continue
            self.rank_seq[rank] = max(self.rank_seq.get(rank, 0), seq)
            self._pending.setdefault(seq, {})[rank] = rec
            lines.extend(self._maybe_verify(seq, live))
        # bound the pending table: a rank that stops shipping (died,
        # ring overflow) must not grow it forever
        while len(self._pending) > _PENDING_CAP:
            oldest = min(self._pending)
            del self._pending[oldest]
            self.unverified_dropped += 1
        return lines

    def _maybe_verify(self, seq: int, live: set[int]) -> list[str]:
        got = self._pending.get(seq)
        if got is None or not live <= set(got):
            return []
        del self._pending[seq]
        lines: list[str] = []
        # compare EVERY rank that reported the seq, not just the
        # still-live set: close flushes race rank departures, and a
        # cleanly-closed rank's records are exactly as comparable —
        # live-only comparison would shrink to one rank at job end
        # and wave corrupted seqs through as "verified"
        recs = {r: got[r] for r in sorted(got)}
        fams = {r: rec.get("fam") for r, rec in recs.items()}
        if len(set(fams.values())) > 1:
            # the minority schedule's ranks are the implicated ones
            fam_groups: dict = {}
            for r, f in fams.items():
                fam_groups.setdefault(f, []).append(r)
            fam_major = max(fam_groups.values(), key=len)
            dissent = [r for f, rs in fam_groups.items()
                       if rs is not fam_major for r in rs]
            lines.append(self._flag(
                seq, "schedule",
                f"ranks disagree about collective #{seq}: "
                + ", ".join(f"rank {r} ran {f!r}"
                            for r, f in fams.items()),
                ranks=dissent))
            return lines
        fam = next(iter(fams.values()))
        errs = [r for r, rec in recs.items() if "err" in rec]
        if errs:
            return lines    # failed collective: recovery owns this
        lines.extend(self._check_wire(seq, fam, recs))
        # nonstd calls (explicit from_/to/ranges/partitioner) digest
        # the WHOLE payload while the collective only replicates part
        # of it — bytes outside the range legitimately differ per
        # rank, so output comparison would false-alarm on healthy
        # jobs (checkprocess's ranged allreduce is the canonical
        # case); the wire check above still covers them
        nonstd = any(rec.get("nonstd") for rec in recs.values())
        if fam in REPLICATED and not nonstd:
            groups: dict[int, list[int]] = {}
            for r, rec in recs.items():
                groups.setdefault(rec.get("out"), []).append(r)
            if len(groups) > 1:
                majority = max(groups.values(), key=len)
                minority = sorted(r for d, rs in groups.items()
                                  if rs is not majority for r in rs)
                lines.append(self._flag(
                    seq, "output",
                    f"collective #{seq} ({fam}): replicated outputs "
                    f"DIVERGE — minority rank(s) {minority} disagree "
                    f"with ranks {sorted(majority)} "
                    f"({len(groups)} distinct digests)",
                    ranks=minority))
        if not lines:
            self.verified_total += 1
            if seq > self.verified_seq:
                self.verified_seq = seq
        return lines

    def _check_wire(self, seq: int, fam: str,
                    recs: dict[int, dict]) -> list[str]:
        lines = []
        for a, rec in recs.items():
            for peer_s, ent in (rec.get("wire") or {}).items():
                b = int(peer_s)
                back = (recs.get(b, {}).get("wire") or {}).get(str(a))
                if back is None:
                    continue
                sent, rcvd = ent.get("s"), back.get("r")
                if sent and rcvd and sent != rcvd:
                    lines.append(self._flag(
                        seq, "wire",
                        f"collective #{seq} ({fam}): wire digest "
                        f"mismatch rank {a} -> rank {b} over "
                        f"{ent.get('t', '?')}: sent "
                        f"crc={sent[0]:#010x}/{sent[1]}B but received "
                        f"crc={rcvd[0]:#010x}/{rcvd[1]}B — bytes "
                        "corrupted in flight",
                        ranks=[a, b]))
        return lines

    def _flag(self, seq: int, kind: str, msg: str,
              ranks: list[int] | tuple = ()) -> str:
        """Record one divergence. ``ranks`` names the implicated
        ranks structurally (minority / wire endpoints / schedule
        dissenters) so the health plane (ISSUE 12) can escalate them
        without parsing the human-readable message."""
        self.divergence_total += 1
        self.divergences.append({"seq": seq, "kind": kind, "msg": msg,
                                 "ranks": sorted(int(r) for r in ranks)})
        return f"audit: DIVERGENCE ({kind}) {msg}"

    # -- elastic membership (ISSUE 10) ----------------------------------
    def note_replacement(self, rank: int, resume_seq: int) -> list[str]:
        """Rank ``rank`` was re-populated from a spare resuming at
        ``resume_seq``: ordinals at or below it can never receive a
        record from the NEW occupant, so settle every pending seq in
        that range against whoever did report it (the dead occupant's
        pre-death records included — they are honest and comparable)
        instead of letting those seqs jam the pending table until the
        cap prunes them as silently unverified."""
        lines: list[str] = []
        for seq in sorted(s for s in self._pending if s <= resume_seq):
            # live=∅ forces completeness: verify among the reporters
            lines.extend(self._maybe_verify(seq, set()))
        return lines

    def note_grow(self, slave_num: int, resume_seq: int) -> list[str]:
        """The roster GREW (ISSUE 13): ordinals at or below the
        joiners' resume position can never receive their records —
        settle those pending seqs against whoever did report (the
        ``note_replacement`` rule), then widen the expected rank
        count for everything after."""
        lines: list[str] = []
        for seq in sorted(s for s in self._pending if s <= resume_seq):
            # live=∅ forces completeness among the actual reporters
            lines.extend(self._maybe_verify(seq, set()))
        self.slave_num = slave_num
        return lines

    def note_shrink(self, slave_num: int,
                    mapping: dict[int, int]) -> None:
        """The roster renumbered (shrink): remap the per-rank audit
        positions and drop pending seqs — their records are keyed by
        OLD ranks, and the retried ordinal's fresh records arrive
        under the new numbering (comparing across the rename would
        false-diverge every survivor against itself)."""
        self.slave_num = slave_num
        self.rank_seq = {mapping[r]: s for r, s in self.rank_seq.items()
                        if r in mapping}
        self.unverified_dropped += len(self._pending)
        self._pending.clear()

    def status(self) -> dict:
        """The cluster audit document (metrics endpoint, live view,
        postmortem manifest)."""
        return {
            "verified_seq": self.verified_seq,
            "verified_total": self.verified_total,
            "divergences": self.divergence_total,
            "last_divergences": list(self.divergences)[-8:],
            "dropped_records": self.dropped_records,
            "unverified_dropped": self.unverified_dropped,
            "rank_seq": {str(r): s for r, s in
                         sorted(self.rank_seq.items())},
        }


# ----------------------------------------------------------------------
# record/replay (the ``mp4j-scope replay`` command)
# ----------------------------------------------------------------------
def write_rank_audit(root: str, rank: int, dump: dict) -> str:
    """Write one rank's ``audit.json`` under ``root/rank_NNNN/`` —
    the same layout the postmortem flight recorder uses, so a clean
    capture run and a crash bundle replay identically."""
    d = os.path.join(root, f"rank_{rank:04d}")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "audit.json")
    # tmp + replace (mp4j-lint R14): replay must never decode a dump
    # torn by a dying process as a short-but-valid schedule
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(dump, fh)
    os.replace(tmp, path)
    return path


def load_audit_bundles(root: str) -> dict[int, dict]:
    """``{rank: audit document}`` from every ``rank_*/audit.json``
    under ``root`` (postmortem bundles and clean capture dumps alike);
    each document carries ``records``, ``mode`` and — since it is
    load-bearing for replay's dead-rank detection — ``slave_num``."""
    out: dict[int, dict] = {}
    for name in sorted(os.listdir(root)):
        if not name.startswith("rank_"):
            continue
        p = os.path.join(root, name, "audit.json")
        if not os.path.exists(p):
            continue
        try:
            rank = int(name[len("rank_"):])
        except ValueError:
            continue
        with open(p, encoding="utf-8") as fh:
            out[rank] = json.load(fh)
    return out


def _decode_capture(cap: str):
    return pickle.loads(zlib.decompress(base64.b64decode(cap)))


_REPLAY_FAMILIES = frozenset({
    "allreduce_array", "reduce_array", "broadcast_array",
    "allgather_array", "gather_array", "scatter_array",
    "reduce_scatter_array", "allreduce_map", "reduce_map",
    "broadcast_map", "gather_map", "allgather_map", "scatter_map",
    "reduce_scatter_map",
})


def _resolve(rec):
    """(method kwargs, reason) — replay call arguments resolved from a
    record's operand/operator/root names, or (None, why-not)."""
    from ytk_mp4j_tpu.operands import Operands
    from ytk_mp4j_tpu.operators import Operators

    if rec.get("fam") not in _REPLAY_FAMILIES:
        return None, f"family {rec.get('fam')!r} not replayable"
    if rec.get("nonstd"):
        return None, "call used non-default args (ranges/from_/to)"
    kwargs: dict = {}
    opn = rec.get("operand")
    if opn:
        byname = {o.name: o for o in Operands.NUMERIC}
        byname["STRING"] = Operands.STRING
        byname["OBJECT"] = Operands.OBJECT_OPERAND()
        if opn not in byname:
            return None, f"unknown operand {opn!r}"
        kwargs["operand"] = byname[opn]
    orn = rec.get("operator")
    if orn:
        try:
            kwargs["operator"] = Operators.by_name(orn)
        except Mp4jError:
            return None, f"operator {orn!r} not replayable (custom?)"
    if rec.get("root") is not None:
        kwargs["root"] = int(rec["root"])
    return kwargs, None


def replay_bundle(root: str) -> tuple[str, int]:
    """Re-execute a captured schedule on the thread backend and diff
    digests record-by-record; returns ``(report text, diverged
    count)``.

    Every rank's captured INPUT payloads for record k are handed to a
    standalone ``ThreadCommSlave`` group (one thread per rank, no
    master, no sockets) which runs the recorded collective; the
    replayed input/output digests are then compared with the recorded
    ones. A recorded output digest that disagrees with the clean
    replay reproduces the live divergence offline — down to which
    ranks and which digests.

    Parity note: the thread backend's merge association differs from
    some socket schedules (rhd/ring vs pairwise tree), so genuinely
    order-sensitive float reductions can differ in low bits; for the
    order-insensitive operator/value combinations the cross-backend
    property grids pin, replay is bit-exact. Records without captured
    payloads (digest/verify mode, oversized, custom operators) are
    reported as skipped, never silently dropped.
    """
    # lazy import: comm imports obs.audit; importing the thread
    # backend at module scope would cycle
    from ytk_mp4j_tpu.comm.thread_comm import ThreadCommSlave

    bundles = load_audit_bundles(root)
    if not bundles:
        raise ValueError(f"{root}: no rank_*/audit.json bundles")
    ranks = sorted(bundles)
    # the TRUE job size comes from the bundles themselves (a dump
    # records slave_num): a dead HIGHEST rank leaves a contiguous
    # 0..n-2 bundle set that rank-contiguity alone cannot distinguish
    # from a healthy (n-1)-rank job — re-executing with the wrong
    # group size would flag every record of a run whose only fault
    # was the kill
    n = max([max(ranks) + 1]
            + [int(doc["slave_num"]) for doc in bundles.values()
               if doc.get("slave_num")])
    by_seq: dict[int, dict[int, dict]] = {}
    for r, doc in bundles.items():
        for rec in doc.get("records") or []:
            by_seq.setdefault(int(rec.get("seq", 0)), {})[r] = rec
    lines = [f"replay: {root} — {len(ranks)}/{n} rank(s), "
             f"{len(by_seq)} recorded collective(s)"]
    if ranks != list(range(n)):
        # a dead rank left no bundle: its inputs are gone, so the
        # schedule cannot be re-executed — degrade to the recorded
        # cross-rank comparison below, don't pretend to replay
        missing = sorted(set(range(n)) - set(ranks))
        lines.append(f"  cannot re-execute: rank(s) {missing} left no "
                     "audit bundle; comparing recorded digests only")
        slaves = None
    else:
        slaves = ThreadCommSlave.spawn_group(n)
    diverged = 0

    for seq in sorted(by_seq):
        recs = by_seq[seq]
        if set(recs) != set(ranks):
            lines.append(f"  #{seq}: SKIP — only ranks "
                         f"{sorted(recs)} recorded it")
            continue
        fams = {rec["fam"] for rec in recs.values()}
        if len(fams) > 1:
            diverged += 1
            lines.append(f"  #{seq}: SCHEDULE DIVERGENCE — "
                         + ", ".join(f"rank {r}: {rec['fam']}"
                                     for r, rec in sorted(recs.items())))
            continue
        fam = next(iter(fams))
        if any("err" in rec for rec in recs.values()):
            lines.append(f"  #{seq} {fam}: SKIP — recorded error "
                         "(schedule stopped here)")
            continue
        if slaves is None:
            nonstd = any(rec.get("nonstd") for rec in recs.values())
            if fam in REPLICATED and not nonstd:
                outs = {rec.get("out") for rec in recs.values()}
                if len(outs) > 1:
                    diverged += 1
                    lines.append(f"  #{seq} {fam}: DIVERGED "
                                 "(recorded digests disagree)")
                else:
                    lines.append(f"  #{seq} {fam}: ok (recorded)")
            else:
                lines.append(f"  #{seq} {fam}: SKIP — "
                             + ("non-default args"
                                if nonstd else "rooted family")
                             + ", recorded-only comparison")
            continue
        kwargs, why = _resolve(recs[ranks[0]])
        caps = {r: rec.get("cap") for r, rec in recs.items()}
        if kwargs is None or any(c is None for c in caps.values()):
            why = why or "no captured payload (run MP4J_AUDIT=capture)"
            lines.append(f"  #{seq} {fam}: SKIP — {why}")
            continue
        try:
            payloads = {r: _decode_capture(caps[r]) for r in ranks}
        except Exception as e:      # torn/corrupt capture bytes — the
            # exact artifact replay exists to diagnose, never a crash
            diverged += 1
            lines.append(f"  #{seq} {fam}: CAPTURE CORRUPT — payload "
                         f"decode failed ({e!r})")
            continue
        # replayed input digests must reproduce the recorded ones —
        # a mismatch means the capture itself is corrupt
        bad_in = [r for r in ranks
                  if digest_payload(payloads[r])[0] != recs[r]["in"]]
        if bad_in:
            diverged += 1
            lines.append(f"  #{seq} {fam}: CAPTURE CORRUPT — replayed "
                         f"input digest differs on rank(s) {bad_in}")
            continue
        out_digests, errs = _replay_one(slaves, fam, kwargs, payloads)
        if errs:
            # a replay-side execution error is its own diagnosis, not
            # a digest divergence — report the exception text. The
            # error may have stranded peer threads INSIDE the
            # collective, wedging the group's barriers: abandon it
            # (stuck daemon threads die with the process) and respawn
            # fresh slaves so the remaining records replay cleanly
            diverged += 1
            det = ", ".join(f"rank {r}: {e!r}"
                            for r, e in sorted(errs.items()))
            lines.append(f"  #{seq} {fam}: REPLAY ERROR — {det}")
            slaves = ThreadCommSlave.spawn_group(n)
            continue
        bad = [r for r in ranks
               if out_digests[r] != recs[r].get("out")]
        if bad:
            diverged += 1

            def hx(v):
                return f"{v:#018x}" if isinstance(v, int) else repr(v)

            det = ", ".join(
                f"rank {r}: recorded {hx(recs[r].get('out'))} != "
                f"replayed {hx(out_digests[r])}" for r in bad)
            lines.append(f"  #{seq} {fam}: DIVERGED — {det}")
        else:
            lines.append(f"  #{seq} {fam}: ok")
    if slaves is not None:
        for s in slaves:
            s.close(0)
    lines.append(f"replay: {diverged} diverged record(s)"
                 if diverged else "replay: all records clean")
    return "\n".join(lines), diverged


def _replay_one(slaves, fam: str, kwargs: dict,
                payloads: dict) -> tuple[dict[int, int],
                                         dict[int, BaseException]]:
    """Run one recorded collective across the thread group; returns
    (per-rank output digests, per-rank exceptions). An execution error
    surfaces as the record's REPLAY ERROR diagnosis rather than
    killing replay or masquerading as a digest divergence."""
    out: dict[int, int] = {}
    errs: dict[int, BaseException] = {}

    def run(slave):
        # no barrier here: the caller joins every thread before the
        # next record, and a barrier would wedge the erroring thread
        # behind peers stranded inside the failed collective
        r = slave.rank
        payload = payloads[r]
        try:
            getattr(slave, fam)(payload, **kwargs)
            out[r] = digest_payload(payload)[0]
        except Exception as e:       # noqa: BLE001 - reported per record
            errs[r] = e

    threads = [threading.Thread(target=run, args=(s,), daemon=True)
               for s in slaves]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 30.0
    for t in threads:
        t.join(max(0.1, deadline - time.monotonic()))
    for r in payloads:
        if r not in out and r not in errs:
            errs[r] = TimeoutError(
                "replay thread never completed (one rank's error can "
                "strand its peers mid-collective)")
    return out, errs
