"""mp4j-trail — durable streaming telemetry sink (ISSUE 9).

Every observability plane built so far is a bounded in-memory ring:
the span ring (ISSUE 3), the metrics registry (ISSUE 6), the audit
record ring (ISSUE 8) and the recovery event log (ISSUE 5) all keep
only a sliding tail, so a multi-day job's history dies with the
process. This module drains those rings to disk continuously:

- :class:`SinkWriter` runs a background thread per rank that, every
  ``MP4J_SINK_FLUSH_SECS``, takes the DELTA of each source ring
  (non-destructive cursors — ``spans.take_since``,
  ``AuditRing.read_since``, ``RecoveryManager.events_since``, and
  stats/metrics snapshot diffs) and appends it as crc-framed records
  to an append-only **segment file** under
  ``MP4J_SINK_DIR/rank_NNNN/``. The drain never runs on the
  collective hot path; the hot path's only cost is the ring appends
  it already pays.
- Segments rotate at a size derived from the PER-RANK disk budget
  ``MP4J_SINK_BYTES``; when the rank's directory would exceed the
  budget the OLDEST whole segment is evicted, so the job's footprint
  is bounded at ``slave_num * MP4J_SINK_BYTES`` no matter how long it
  runs.
- Torn-tail tolerance: each record is framed ``MAGIC | payload_len |
  crc32(payload) | payload`` and appended frame-wise with unbuffered
  ``write`` calls (rotation/eviction run between frames, so any size
  of backlog streams through under the budget); a ``kill -9``
  mid-write can only tear the frame being written, which the reader
  detects (short read or crc mismatch) and reports as exactly one
  torn tail — every prior record stays readable. No fsync per
  record: the OS page cache survives process death, and only a
  machine crash loses the final interval.

Record framing (little-endian)::

    +------+-------------+--------------+---------------------+
    | b"MJ"| len: uint32 | crc32: uint32| payload (JSON utf-8)|
    +------+-------------+--------------+---------------------+

Record payloads (``{"t": kind, ...}``):

- ``meta``    — first record of every segment: rank, slave_num,
  segment ordinal, wall time (readers learn identity from any
  surviving segment, even after eviction removed the first);
- ``spans``   — a batch of span tuples with ``t0`` converted to WALL
  time (``spans.to_wall``), so cross-rank timelines reconstruct from
  independently launched processes;
- ``stats``   — a ``comm.stats()`` delta since the previous record;
- ``metrics`` — a metrics-registry delta (``metrics.diff_snapshot``);
- ``audit``   — a batch of audit digest records (capture payloads
  excluded — the sink is telemetry, the bundle carries bytes);
- ``recovery``— a batch of recovery events, plus this rank's epoch;
- ``alerts``  — a batch of health-plane alert events (ISSUE 12):
  per-rank verdict transitions and straggler onsets the master pushed
  to this rank, each with an id/wall/detector/from/to — the durable
  half of the ``mp4j-scope health`` timeline.

The offline half — :func:`iter_segment`, :func:`read_rank`,
:func:`load_job` — feeds :mod:`ytk_mp4j_tpu.obs.critpath` (the
``mp4j-scope analyze`` / ``tail`` commands). Deliberately imports
nothing from ``comm`` (the obs discipline); the writer receives its
sources as objects.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib

from ytk_mp4j_tpu.obs import metrics as metrics_mod
from ytk_mp4j_tpu.obs import spans
from ytk_mp4j_tpu.utils import stats as stats_mod
from ytk_mp4j_tpu.utils import tuning

MAGIC = b"MJ"
_HEADER = struct.Struct("<2sII")          # magic, payload len, crc32
# one record's payload can never legitimately exceed this — a larger
# length field in a segment means the header itself is corrupt, and
# the reader must not allocate gigabytes chasing it
MAX_RECORD_BYTES = 16 * 1024 * 1024
# spans per "spans" record: the one unbounded batch a drain can form
# (a full default ring is 65536 entries; everything else is bounded
# by its own ring/table size). 4096 spans x ~300 B JSON each keeps
# every frame far below MAX_RECORD_BYTES — a frame the writer emits
# must NEVER look like a corrupt header to the reader, which would
# discard the rest of the segment, not one record
_SPAN_BATCH = 4096
_SEG_FMT = "seg_{:08d}.mp4j"
_SEG_MIN = 64 * 1024


def rank_dir(root: str, rank: int) -> str:
    return os.path.join(root, f"rank_{rank:04d}")


def encode_record(obj: dict) -> bytes:
    """One crc-framed record. JSON payload: self-describing, and torn
    bytes can never masquerade as a record (the crc covers every
    payload byte, the magic pins the frame start). ``default=repr``:
    an exotic object that leaked into span args or an audit record
    must degrade to its repr, never kill the drain thread with a
    TypeError."""
    payload = json.dumps(obj, separators=(",", ":"),
                         default=repr).encode("utf-8")
    return _HEADER.pack(MAGIC, len(payload),
                        zlib.crc32(payload)) + payload


def _write_all(fh, buf: bytes) -> None:
    """Write every byte or raise. An unbuffered FileIO's ``write``
    issues ONE ``write(2)`` and may return short (nearly-full disk,
    RLIMIT_FSIZE) WITHOUT raising — booking a short write as durable
    would count torn records as safe and let later frames land after
    the corrupt bytes, where the reader discards them at the tear."""
    # mp4j-lint: disable=R13 (callers pass plain bytes frames — contiguous by construction)
    view = memoryview(buf)
    while view:
        n = fh.write(view)
        if not n:
            raise OSError("short write: 0 bytes accepted")
        view = view[n:]


def _record_count(rec: dict) -> int:
    """How many underlying telemetry records one frame carries — the
    unit drop accounting uses everywhere (a spans frame batches
    thousands; counting frames would under-report losses by orders of
    magnitude)."""
    kind = rec.get("t")
    if kind == "spans":
        return len(rec.get("spans") or ()) or 1
    if kind == "audit":
        return len(rec.get("records") or ()) or 1
    if kind == "recovery":
        return len(rec.get("events") or ()) or 1
    if kind == "alerts":
        return len(rec.get("alerts") or ()) or 1
    return 1


def iter_segment(path: str, offset: int = 0):
    """Yield ``(record, next_offset)`` from a segment file starting at
    ``offset``; stops at EOF or at a torn tail. Returns via
    StopIteration value — use :func:`read_segment` for the plain
    ``(records, end_offset, torn)`` shape."""
    with open(path, "rb") as fh:
        fh.seek(offset)
        while True:
            start = fh.tell()
            head = fh.read(_HEADER.size)
            if not head:
                return (start, False)        # clean end
            if len(head) < _HEADER.size:
                return (start, True)         # torn header
            magic, length, crc = _HEADER.unpack(head)
            if magic != MAGIC or length > MAX_RECORD_BYTES:
                return (start, True)         # torn/corrupt header
            payload = fh.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return (start, True)         # torn payload
            try:
                rec = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, ValueError):
                return (start, True)         # crc passed, JSON didn't:
                # treat as torn rather than crash the reader
            yield rec, fh.tell()


def read_segment(path: str, offset: int = 0
                 ) -> tuple[list[dict], int, bool]:
    """``(records, end_offset, torn)`` — every intact record from
    ``offset`` on; ``torn`` is True when the file ends inside a frame
    (exactly one torn tail by construction: the reader stops there).
    ``end_offset`` is where the LAST intact record ended — a follow-
    mode reader resumes from it, so a tail torn only because the
    writer is mid-append completes on the next poll."""
    records: list[dict] = []
    it = iter_segment(path, offset)
    end = offset
    while True:
        try:
            rec, end = next(it)
        except StopIteration as stop:
            pos, torn = stop.value
            if not torn:
                end = pos        # clean EOF; torn keeps the last
                # intact record's end so follow mode re-reads the
                # (possibly still-growing) tail next poll
            return records, end, torn
        records.append(rec)


def list_segments(rdir: str) -> list[str]:
    """Segment paths in a rank dir, oldest first (eviction may have
    removed a prefix — gaps are normal)."""
    try:
        names = sorted(n for n in os.listdir(rdir)
                       if n.startswith("seg_") and n.endswith(".mp4j"))
    except OSError:
        return []
    return [os.path.join(rdir, n) for n in names]


def read_dir(rdir: str) -> dict:
    """Every intact record across ONE directory of crc-framed
    segments, oldest first: ``{"records": [...], "segments": int,
    "torn": int, "bytes": int}``. A torn tail in a NON-final segment
    (the writer crashed, restarted and rotated) is counted too — each
    segment is independent. The generic reader: per-rank sink dirs
    (:func:`read_rank`) and the fleet history dir
    (:mod:`ytk_mp4j_tpu.obs.fleet`) are both plain segment
    directories under this framing."""
    records: list[dict] = []
    torn = 0
    nbytes = 0
    segs = list_segments(rdir)
    for p in segs:
        try:
            recs, end, t = read_segment(p)
        except OSError:
            continue        # evicted under the reader (follow mode)
        # already-parsed records are kept even if the file vanishes
        # (eviction racing a follow-mode reader) before the size
        # stat — megabytes of intact telemetry must not disappear
        # from one analysis pass over a stat on a gone path
        records.extend(recs)
        torn += bool(t)
        try:
            nbytes += os.path.getsize(p)
        except OSError:
            nbytes += end
    return {"records": records, "segments": len(segs), "torn": torn,
            "bytes": nbytes}


def read_rank(rdir: str) -> dict:
    """One rank's sink history — a rank dir IS a plain segment dir
    (kept as its own name: every analyzer call site reads as
    per-rank, and the fleet reader must not look like it reads
    ranks)."""
    return read_dir(rdir)


def load_job(root: str) -> dict[int, dict]:
    """``{rank: read_rank(...)}`` for every ``rank_*/`` under the sink
    root — the analyzer's input."""
    out: dict[int, dict] = {}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for name in names:
        if not name.startswith("rank_"):
            continue
        try:
            rank = int(name[len("rank_"):])
        except ValueError:
            continue
        d = os.path.join(root, name)
        if os.path.isdir(d):
            out[rank] = read_rank(d)
    return out


class SinkWriter:
    """Per-rank durable sink: background drain of the telemetry rings
    into rotating crc-framed segments (module docstring).

    ``stats`` is the slave's ``CommStats`` (spans are read from the
    process-global ring filtered by this rank — thread-backed
    multi-slave processes share it); ``audit`` / ``recovery`` may be
    None. ``metrics`` defaults to ``stats.metrics``: the sink books
    its own counters (``sink/bytes``, ``sink/records``,
    ``sink/dropped_records``) and the ``sink/lag_secs`` gauge there,
    so sink health rides the existing heartbeat to Prometheus.

    Thread-safety: ``flush()`` may be called from the collective
    thread (close, terminal hook) concurrently with the drain thread;
    ``_io_lock`` serializes whole drains. Everything is best-effort:
    a full disk degrades to dropped telemetry (counted), never to a
    failed collective.
    """

    def __init__(self, root: str, rank: int, *, slave_num: int = 0,
                 stats=None, audit=None, recovery=None, metrics=None,
                 alerts=None, budget_bytes: int | None = None,
                 flush_secs: float | None = None):
        self.root = str(root)
        self.rank = int(rank)
        self.slave_num = int(slave_num)
        self.dir = rank_dir(self.root, self.rank)
        self._stats = stats
        self._audit = audit
        self._recovery = recovery
        # health-alert log (ISSUE 12): same cursor-delta contract as
        # the audit ring and recovery event log
        self._alerts = alerts
        self._metrics = metrics if metrics is not None else (
            stats.metrics if stats is not None else None)
        self.budget = (tuning.sink_bytes() if budget_bytes is None
                       else int(budget_bytes))
        # segment size: budget/8 keeps eviction granularity fine
        # enough that the budget overshoot is bounded by one segment
        self.seg_bytes = max(_SEG_MIN, self.budget // 8)
        self.flush_secs = (tuning.sink_flush_secs() if flush_secs is None
                           else float(flush_secs))
        self._io_lock = threading.Lock()
        self._fh = None
        self._seg_index = 0
        self._seg_size = 0
        self._seg_records: dict[str, int] = {}   # basename -> size
        # delta cursors into the source rings. The span ring is
        # process-global (thread-backed multi-slave processes share
        # it): start at its oldest still-served cursor so history
        # that predates this writer is neither replayed nor reported
        # as dropped
        self._span_cur = spans.oldest_cursor()
        self._audit_cur = 0
        self._rec_cur = 0
        self._alert_cur = 0
        self._last_stats: dict = {}
        self._last_metrics: dict = {}
        self._last_drain = time.monotonic()
        # lifetime counters (mirrored into the metrics registry)
        self.bytes_written = 0
        self.records_written = 0
        self.dropped_records = 0       # ring overflow before a drain
        self.evicted_segments = 0
        self.last_error: str | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._failed = False

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "SinkWriter":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mp4j-sink-r{self.rank}")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_secs):
            self.flush()

    def flush(self) -> None:
        """One synchronous drain of every source ring (the fatal-path
        and close-path entry point; also the drain thread's body).
        Never raises: an unexpected exception (not just OSError) is
        counted and remembered instead of killing the drain thread —
        a silently dead sink whose counters freeze at plausible
        values is exactly the healthy-looking-dead state this plane
        exists to prevent."""
        try:
            with self._io_lock:
                self._drain_locked()
        except Exception as e:          # noqa: BLE001 - see docstring
            with self._io_lock:
                self.dropped_records += 1
                self.last_error = repr(e)
            if self._metrics is not None and self._metrics.enabled:
                self._metrics.inc("sink/dropped_records", 1)

    def abort(self) -> None:
        """Stop draining WITHOUT a final flush — the fault-injected
        ``kill`` path: a crashed process flushes nothing, and the
        simulation must not keep writing segments a real corpse
        couldn't."""
        self._stop.set()
        with self._io_lock:
            self._failed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def close(self) -> None:
        """Stop the drain thread, final flush, release the segment.
        The final drain rides :meth:`flush` so its catch-all applies —
        a poison record in the last interval must not turn a clean
        job shutdown into an uncaught exception."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
        self.flush()
        with self._io_lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # -- the drain ------------------------------------------------------
    def _drain_locked(self) -> None:
        if self._failed:
            return
        now = time.monotonic()
        lag = now - self._last_drain
        recs: list[dict] = []
        dropped = 0

        self._span_cur, items, d = spans.take_since(self._span_cur)
        dropped += d
        mine = [s for s in items if s[4] == self.rank]
        for i in range(0, len(mine), _SPAN_BATCH):
            recs.append({"t": "spans", "spans": [
                [s[0], s[1], round(spans.to_wall(s[2]), 6),
                 round(s[3], 9), s[4], s[5], s[6]]
                for s in mine[i:i + _SPAN_BATCH]]})

        if self._stats is not None:
            snap = self._stats.snapshot()
            sd = stats_mod.diff_snapshots(snap, self._last_stats)
            self._last_stats = snap
            if sd:
                recs.append({"t": "stats", "delta": sd})
        if self._metrics is not None:
            msnap = self._metrics.snapshot()
            md = metrics_mod.diff_snapshot(msnap, self._last_metrics)
            self._last_metrics = msnap
            # the sink's OWN accounting (sink/*) is excluded from the
            # stream: writing it would change the counters, making the
            # next delta non-empty forever — an idle job would churn
            # one self-accounting frame per flush interval and evict
            # its real collective history to store sink noise. Sink
            # health reaches Prometheus via the heartbeat and the
            # postmortem via sink.json; segments carry the job.
            counters = {k: v for k, v in md.get("counters", {}).items()
                        if not k.startswith("sink/")}
            gauges = {k: v for k, v in md.get("gauges", {}).items()
                      if not k.startswith("sink/")}
            if counters or md.get("histograms"):
                recs.append({"t": "metrics", "delta": {
                    "counters": counters, "gauges": gauges,
                    "histograms": md.get("histograms", {})}})

        if self._audit is not None:
            self._audit_cur, arecs, d = self._audit.read_since(
                self._audit_cur)
            dropped += d
            if arecs:
                recs.append({"t": "audit", "records": arecs})
        if self._recovery is not None:
            self._rec_cur, events, d = self._recovery.events_since(
                self._rec_cur)
            dropped += d
            if events:
                recs.append({"t": "recovery",
                             "epoch": self._recovery.epoch,
                             "events": [[round(ts, 6), kind, detail]
                                        for ts, kind, detail in events]})
        if self._alerts is not None:
            self._alert_cur, evs, d = self._alerts.events_since(
                self._alert_cur)
            dropped += d
            if evs:
                recs.append({"t": "alerts", "alerts": evs})
        if recs:
            try:
                dropped += self._write_records(recs)
            except Exception as e:      # noqa: BLE001 - encode-side
                # poison (e.g. a CYCLIC structure in span args —
                # default=repr only saves acyclic oddities). Encoding
                # happens before any write, so the whole delta is
                # lost: count it in RECORD units, remember the error,
                # never let telemetry fail the job
                dropped += sum(_record_count(r) for r in recs)
                self.last_error = repr(e)
                try:
                    if self._fh is not None:
                        self._fh.close()
                except OSError:
                    pass
                self._fh = None
        if dropped:
            self.dropped_records += dropped
            if self._metrics is not None and self._metrics.enabled:
                self._metrics.inc("sink/dropped_records", dropped)
        self._note_metrics(lag)
        self._last_drain = now

    def _note_metrics(self, lag: float) -> None:
        m = self._metrics
        if m is None or not m.enabled:
            return
        m.set_gauge("sink/lag_secs", round(lag, 3))
        m.set_gauge("sink/dir_bytes", float(sum(
            self._seg_records.values())))

    def _write_records(self, recs: list[dict]) -> int:
        """Append the drain's records FRAME BY FRAME: rotation and
        eviction run between frames, so an arbitrarily large backlog
        (a stalled drain thread, a burst of collectives) streams
        through many segments under the budget instead of landing as
        one oversized write that blows past it — "the directory never
        exceeds MP4J_SINK_BYTES" must hold for any drain size. Span
        records too big for half a segment split recursively first. A
        kill -9 still tears at most the single frame being written.

        Returns the RECORD count lost (unsplittable-oversized frames
        plus everything after a write failure). A full/unwritable
        disk must never fail the job — and must never double-count:
        frames durably written before the failing one stay counted as
        written, only the unwritten remainder reports as dropped."""
        frames: list[tuple[bytes, int]] = []
        half_seg = max(4096, self.seg_bytes // 2)
        lost = 0
        for rec in recs:
            lost += self._encode_bounded(rec, half_seg, frames)
        for i, (frame, count) in enumerate(frames):
            try:
                fh = self._ensure_segment(len(frame))
                _write_all(fh, frame)
            except OSError as e:
                self.last_error = repr(e)
                try:
                    if self._fh is not None:
                        self._fh.close()
                except OSError:
                    pass
                self._fh = None
                return lost + sum(c for _, c in frames[i:])
            self._seg_size += len(frame)
            self._seg_records[os.path.basename(self._seg_path())] = \
                self._seg_size
            self.bytes_written += len(frame)
            self.records_written += count
            m = self._metrics
            if m is not None and m.enabled:
                m.inc("sink/bytes", len(frame))
                m.inc("sink/records", count)
        return lost

    # which key holds each batching record kind's splittable list —
    # audit records and recovery events are exactly as splittable as
    # span batches, and an unsplit oversized batch would defeat the
    # budget bound for small MP4J_SINK_BYTES just the same
    _SPLIT_KEYS = {"spans": "spans", "audit": "records",
                   "recovery": "events", "alerts": "alerts"}

    def _encode_bounded(self, rec: dict, cap: int,
                        out: list[tuple[bytes, int]]) -> int:
        """Encode ``rec``, splitting batch records (spans/audit/
        recovery lists) in half until each frame fits ``cap``; returns
        the record count DROPPED (an unsplittable oversized record —
        one giant span, a huge metrics table: a frame above the
        reader's limits would read as a corrupt header and take the
        rest of its segment along). The caller folds the return into
        the drain's drop accounting so the metric and the ``!`` live
        marker see it like every other loss."""
        frame = encode_record(rec)
        if len(frame) <= min(cap, MAX_RECORD_BYTES):
            out.append((frame, _record_count(rec)))
            return 0
        key = self._SPLIT_KEYS.get(rec.get("t"))
        items = rec.get(key) if key else None
        if items and len(items) > 1:
            mid = len(items) // 2
            lo = {**rec, key: items[:mid]}
            hi = {**rec, key: items[mid:]}
            return (self._encode_bounded(lo, cap, out)
                    + self._encode_bounded(hi, cap, out))
        if len(frame) <= MAX_RECORD_BYTES:
            out.append((frame, _record_count(rec)))   # over the soft
            # cap but still readable: better a fat segment than loss
            return 0
        return _record_count(rec)

    def _seg_path(self) -> str:
        return os.path.join(self.dir, _SEG_FMT.format(self._seg_index))

    def _ensure_segment(self, incoming: int):
        """The open segment file, rotating + evicting as needed."""
        if self._fh is not None and self._seg_size + incoming \
                > self.seg_bytes:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        if self._fh is None:
            os.makedirs(self.dir, exist_ok=True)
            # resume after restart/rotation: next index past anything
            # already on disk (scanned once, then tracked in memory)
            if not self._seg_records:
                for p in list_segments(self.dir):
                    base = os.path.basename(p)
                    try:
                        self._seg_records[base] = os.path.getsize(p)
                        idx = int(base[len("seg_"):-len(".mp4j")])
                        self._seg_index = max(self._seg_index, idx + 1)
                    except (OSError, ValueError):
                        continue
            else:
                self._seg_index += 1
            self._evict(incoming)
            # unbuffered append-only segment write — the ONE sanctioned
            # non-atomic write path (mp4j-lint R14 baseline): frames
            # are crc-delimited and the reader tolerates a torn tail
            self._fh = open(self._seg_path(), "ab", buffering=0)
            self._seg_size = 0
            head = encode_record({
                "t": "meta", "rank": self.rank,
                "slave_num": self.slave_num, "seg": self._seg_index,
                # wall clock: segment identity must be human-meaningful
                # across hosts, like the postmortem bundle's timestamp
                # mp4j-lint: disable=R11 (artifact timestamp, not a duration)
                "wall": time.time(),
                "budget": self.budget, "seg_bytes": self.seg_bytes})
            _write_all(self._fh, head)
            self._seg_size += len(head)
            self._seg_records[os.path.basename(self._seg_path())] = \
                self._seg_size
        return self._fh

    def _evict(self, incoming: int) -> None:
        """Drop oldest whole segments until the budget holds (never
        the active one — the writer is about to append there). A full
        segment of headroom stays reserved for the active file's
        growth, so the directory never exceeds the budget even
        BETWEEN rotations — the acceptance bound is "disk never
        exceeds MP4J_SINK_BYTES", not "returns under it each
        rotation"."""
        target = max(self.seg_bytes, self.budget - self.seg_bytes)
        total = sum(self._seg_records.values()) + incoming
        active = os.path.basename(self._seg_path())
        for base in sorted(self._seg_records):
            if total <= target:
                break
            if base == active:
                break
            try:
                os.remove(os.path.join(self.dir, base))
            except OSError:
                # the file is still on disk: keep it in the
                # accounting (forgetting it would undercount every
                # later budget check and silently break the bound
                # forever) and stop — if the oldest can't go, newer
                # ones likely can't either; retry next rotation
                break
            total -= self._seg_records.pop(base)
            self.evicted_segments += 1

    def status(self) -> dict:
        """One sink-health record (postmortem bundle's ``sink.json``,
        the master's manifest). Counters are written by the drain
        thread under ``_io_lock`` — snapshot under the same lock so a
        status render never shows a half-applied flush."""
        with self._io_lock:
            return {"dir": self.dir, "root": self.root,
                    "bytes_written": self.bytes_written,
                    "records_written": self.records_written,
                    "dropped_records": self.dropped_records,
                    "evicted_segments": self.evicted_segments,
                    "last_error": self.last_error,
                    "budget_bytes": self.budget,
                    "segment_bytes": self.seg_bytes}
