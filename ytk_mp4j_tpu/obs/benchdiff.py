"""``mp4j-scope bench-diff`` — perf regression gating over BENCH files.

Compares the headline figures of two ``bench.py`` JSON outputs (round
A vs round B) against per-metric regression thresholds and reports a
verdict per metric — the seed of perf regression gating for every
future PR: drop two BENCH files in, get a nonzero exit when a tracked
figure regressed past its budget.

Accepted input shapes (both appear in the repo):

- the raw one-line bench output: ``{"metric", "value", "extra": {...}}``;
- the driver wrapper: ``{"n", "cmd", "rc", "tail", "parsed": {...}}``
  (``parsed`` holds the raw form).

Thresholds are PER METRIC because the noise floor is: pure-device
figures repeat within a few percent, while the loopback socket legs on
a shared 1-core bench host swing 10-20% run to run. Every tracked
metric is higher-is-better; a metric missing from either file is
skipped (rounds grow new figures), never an error.
"""

from __future__ import annotations

import json

# metric -> max tolerated fractional drop (new >= old * (1 - thr)).
# Grounded in BENCH_r01..r05 run-to-run spread; tighten as the bench
# host stabilizes. "value" is the headline GB/s/chip figure.
THRESHOLDS: dict[str, float] = {
    "value": 0.10,
    "trees_per_sec": 0.10,
    "socket_baseline_gbs": 0.25,
    "socket_collective_gbs": 0.20,
    "socket_native_collective_gbs": 0.20,
    # ISSUE 7: the intra-host shared-memory plane and the forced
    # two-level schedule over it; same loopback-leg noise floor as the
    # other socket figures on the shared 1-core bench host
    "socket_shm_collective_gbs": 0.25,
    "socket_twolevel_gbs": 0.25,
    # ISSUE 8: the audit plane's default (digest) mode on the headline
    # leg — gated so the always-on digest tax cannot silently creep;
    # same loopback noise floor as the other socket figures
    "socket_collective_gbs_audit_digest": 0.25,
    # ISSUE 9: the durable sink armed on the headline leg — gated so
    # the background-drain tax cannot silently creep; same noise floor
    "socket_collective_gbs_sink_on": 0.25,
    # ISSUE 12: the streaming health plane armed (slave span-cell
    # folds + master detector set) on the headline leg — gated so the
    # verdict engine's tax cannot silently creep; same noise floor
    "socket_collective_gbs_health_on": 0.25,
    # ISSUE 11 (mp4j-async): k outstanding iallreduces on the
    # scheduler (overlap leg) and the tiny-map coalescing figure —
    # gated so neither the scheduler's dense cost nor the fused map
    # plane regresses silently; same loopback noise floor as the
    # other socket figures. The frozen legs pin async off, so every
    # historical figure stays comparable.
    "socket_async_overlap_gbs": 0.25,
    "socket_async_sequential_gbs": 0.25,
    "socket_coalesce_keys_per_sec": 0.25,
    "socket_coalesce_off_keys_per_sec": 0.25,
    # ISSUE 17 (mp4j-overlap): the dense small-array fused plane (the
    # array twin of the map coalescing rows above) and the
    # trainer-overlap epoch ratio. The ratio row only appears in BENCH
    # files produced on a multi-core host (1-core rigs record a
    # skipped_1core marker instead of a figure), and as an on/off
    # ratio it is already normalized against host speed — the budget
    # bounds erosion of the overlap win itself, not wall-clock drift
    "socket_coalesce_array_elems_per_sec": 0.25,
    "socket_coalesce_array_off_elems_per_sec": 0.25,
    "socket_trainer_overlap_ratio": 0.25,
    "socket_framed_collective_gbs": 0.20,
    "socket_collective_in_workload_gbs": 0.25,
    # ISSUE 15 (mp4j-tuner): the framed/columnar-map planes over the
    # shm rings (frame-level ring routing) and the tuner act leg —
    # gated so neither the routing fast path nor the adaptive win
    # regresses silently; same loopback noise floor. The act leg's
    # win over socket_tuner_off_gbs is the acceptance evidence.
    "socket_framed_shm_gbs": 0.25,
    "socket_map_shm_keys_s": 0.25,
    "socket_tuner_act_gbs": 0.25,
    "socket_tuner_off_gbs": 0.25,
    "ffm_sparse_steps_per_sec": 0.10,
    "ffm_stream_rows_per_sec": 0.20,
    "ffm_stream_rows_per_sec_serialized": 0.20,
    "ffm_stream_text_rows_per_sec": 0.20,
    "libsvm_reader_rows_per_sec": 0.20,
    "socket_map_allreduce_keys_per_sec": 0.20,
    "socket_map_int_allreduce_keys_per_sec": 0.20,
    "socket_map_pickle_keys_per_sec": 0.25,
    "socket_map_int_pickle_keys_per_sec": 0.25,
    "device_map_int_allreduce_keys_per_sec": 0.20,
    "device_map_chained_keys_per_sec": 0.20,
    "gbdt_hist_mxu_tflops_per_sec_per_chip": 0.10,
    # ISSUE 10: recovery/membership latencies (LOWER is better — see
    # LOWER_IS_BETTER below). Wide budgets: these are single-event
    # wall-clock deltas on a shared 1-core host whose scheduler tails
    # swing them run to run; the gate exists to catch a protocol
    # regression (an extra round trip, a lost deadline), which shows
    # as a multiple, not a percent
    "socket_recovery_latency_ms": 1.0,
    "socket_replacement_latency_ms": 1.0,
    "socket_shrink_latency_ms": 1.0,
    # ISSUE 13: autoscaler actuation latencies, same single-event
    # wall-clock caveat and wide budget as the membership rows above
    "socket_planned_evict_ms": 1.0,
    "socket_grow_latency_ms": 1.0,
    # ISSUE 18 (mp4j-fleet): one full FleetPoller sweep against a
    # live 4-rank job (both endpoint fetches + summary fold + model
    # rebuild + contention detection), p99 over the sweep loop —
    # LOWER is better. Wide budget: the tail rides loopback-HTTP
    # scheduler wakeups on the shared 1-core bench host; the gate
    # exists to catch a fold/detector complexity regression, which
    # shows as a multiple, not a percent
    "fleet_scrape_p99_ms": 1.0,
    # ISSUE 19 (mp4j-serve): the inference plane. The QPS rows gate
    # the micro-batched and unbatched throughputs (loopback noise
    # floor, like the other socket figures) and the speedup row gates
    # the batching win itself — a RATIO, already normalized against
    # host speed. The latency rows (LOWER is better, see below) carry
    # the membership-row caveat: single-digit-ms tails on a shared
    # 1-core host swing run to run, so the gate exists to catch a
    # protocol regression (an extra collective per batch, a lost
    # deadline), which shows as a multiple, not a percent
    "serve_batched_qps": 0.25,
    "serve_unbatched_qps": 0.25,
    "serve_speedup": 0.25,
    "serve_p50_ms": 1.0,
    "serve_p99_ms": 1.0,
    "serve_chaos_p99_ms": 1.0,
    # ISSUE 16: mp4j-lint v3 (R23-R25 lockset/resource whole-program
    # passes) over v2 (R19-R21) — a RATIO, so already normalized
    # against host speed; the budget bounds growth of the marginal
    # analysis cost (v3 <= 1.5x v2 absolute is asserted in tier-1,
    # this row gates drift between bench rounds)
    "lint_v3_over_v2_ratio": 0.5,
}

# metrics where SMALLER is the good direction (latencies): the budget
# bounds GROWTH — new <= old * (1 + thr) — instead of shrinkage
LOWER_IS_BETTER = frozenset({
    "socket_recovery_latency_ms",
    "socket_replacement_latency_ms",
    "socket_shrink_latency_ms",
    "socket_planned_evict_ms",
    "socket_grow_latency_ms",
    "fleet_scrape_p99_ms",
    "lint_v3_over_v2_ratio",
    "serve_p50_ms",
    "serve_p99_ms",
    "serve_chaos_p99_ms",
})


def load_bench(path: str) -> dict[str, float]:
    """Flat ``{metric: value}`` from a BENCH file (either shape);
    raises ``ValueError`` on anything that is not a bench document."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict) or "value" not in doc:
        raise ValueError(f"{path}: not a bench.py output "
                         "(no 'value' headline)")
    out: dict[str, float] = {}
    if isinstance(doc.get("value"), (int, float)):
        out["value"] = float(doc["value"])
    for k, v in (doc.get("extra") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = float(v)
    return out


def compare(old: dict[str, float], new: dict[str, float],
            threshold: float | None = None) -> list[dict]:
    """Row per tracked metric present in BOTH files: ``{metric, old,
    new, ratio, threshold, verdict}`` with verdict ``"REGRESSED"`` /
    ``"ok"`` / ``"improved"`` (improved = past the same margin in the
    good direction). ``threshold`` overrides every per-metric value."""
    rows = []
    for metric, thr in THRESHOLDS.items():
        if metric not in old or metric not in new:
            continue
        if threshold is not None:
            thr = threshold
        a, b = old[metric], new[metric]
        ratio = b / a if a else float("inf")
        lower = metric in LOWER_IS_BETTER
        if lower:
            # latency: growth past budget regresses, shrinkage improves
            if b > a * (1.0 + thr):
                verdict = "REGRESSED"
            elif b < a * (1.0 - thr):
                verdict = "improved"
            else:
                verdict = "ok"
        elif b < a * (1.0 - thr):
            verdict = "REGRESSED"
        elif b > a * (1.0 + thr):
            verdict = "improved"
        else:
            verdict = "ok"
        rows.append({"metric": metric, "old": a, "new": b,
                     "ratio": ratio, "threshold": thr,
                     "lower_is_better": lower,
                     "verdict": verdict})
    return rows


def format_table(rows: list[dict]) -> str:
    if not rows:
        return "(no tracked metrics common to both files)"
    w = max(len(r["metric"]) for r in rows)
    lines = [f"{'metric':<{w}}  {'old':>12}  {'new':>12}  "
             f"{'ratio':>6}  {'budget':>6}  verdict"]
    for r in rows:
        sign = "+" if r.get("lower_is_better") else "-"
        lines.append(
            f"{r['metric']:<{w}}  {r['old']:>12.4f}  {r['new']:>12.4f}  "
            f"{r['ratio']:>6.2f}  {sign}{r['threshold'] * 100:>4.0f}%  "
            f"{r['verdict']}")
    regressed = [r["metric"] for r in rows
                 if r["verdict"] == "REGRESSED"]
    if regressed:
        lines.append(f"REGRESSION: {', '.join(regressed)} dropped past "
                     "budget")
    else:
        lines.append(f"ok: {len(rows)} tracked metric(s) within budget")
    return "\n".join(lines)


def run(old_path: str, new_path: str,
        threshold: float | None = None) -> tuple[str, bool]:
    """(report text, regressed?) — the CLI's whole job."""
    rows = compare(load_bench(old_path), load_bench(new_path),
                   threshold)
    return (format_table(rows),
            any(r["verdict"] == "REGRESSED" for r in rows))
