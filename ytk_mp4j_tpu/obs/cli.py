"""``mp4j-scope`` — cluster telemetry CLI.

Usage::

    mp4j-scope merge -o merged.json rank0.json rank1.json ...
    mp4j-scope report [--json] stats0.json stats1.json ...
    mp4j-scope live http://master-host:PORT [--interval 1.0] [--once]
    mp4j-scope postmortem /path/to/MP4J_POSTMORTEM_DIR
    mp4j-scope replay /path/to/BUNDLE_DIR
    mp4j-scope analyze /path/to/MP4J_SINK_DIR [--json]
    mp4j-scope health /path/to/MP4J_SINK_DIR | http://master:PORT
    mp4j-scope tuner /path/to/MP4J_SINK_DIR | http://master:PORT
    mp4j-scope tail /path/to/MP4J_SINK_DIR [--interval 1.0] [--once]
    mp4j-scope fleet URL [URL ...] [--interval 2.0] [--once] [--sink DIR]
    mp4j-scope fleet-report /path/to/FLEET_SINK_DIR
    mp4j-scope bench-diff BENCH_rA.json BENCH_rB.json [--threshold PCT]
    python -m ytk_mp4j_tpu.obs report ...

``merge`` combines per-rank Chrome-trace exports
(``trace.export_chrome_trace`` output, one file per rank) into a single
timeline loadable in ``chrome://tracing`` / Perfetto — ranks keep
distinct ``pid`` tracks.

``report`` renders the cross-rank skew table (per-collective
min/median/max busy time, bytes, straggler ranks) from per-rank
``comm.stats()`` JSON dumps. Each input file holds either one rank's
snapshot (``{collective: {...}}``, rank taken from the argument order)
or an explicit ``{"rank": N, "stats": {...}}`` wrapper.

``live`` polls the master's metrics endpoint (``MP4J_METRICS_PORT``)
and renders the per-rank throughput / current collective / sequence
lag / retry table with straggler highlighting; ``--once`` prints a
single frame (scripts, tests).

``postmortem`` merges a flight-recorder directory (per-rank bundles +
the master manifest, ``MP4J_POSTMORTEM_DIR``) into one report naming
the dead and lagging ranks, plus the audit plane's known-good
watermark (the last cross-rank-verified collective before the fatal).

``replay`` (ISSUE 8) re-executes a captured schedule
(``MP4J_AUDIT=capture`` bundles: postmortem dirs or
``ProcessCommSlave.dump_audit`` dumps) in-process on the thread
backend and diffs digests record-by-record — offline reproduction of
a divergence with no cluster. Exit 1 when any record diverges.

``analyze`` (ISSUE 9) reads a durable sink directory
(``MP4J_SINK_DIR``: crc-framed per-rank segments) and prints the
job-lifetime critical-path report — per-collective dominators,
per-phase wait decomposition, straggler-onset timestamps, torn-tail
counts. ``tail`` follows the same directory live, printing each
collective's timeline line as all ranks' records land (``--once``
prints the current backlog and exits).

``health`` (ISSUE 12) renders per-rank health verdicts: given a
durable sink DIRECTORY it reconstructs the full verdict history from
the ``alerts`` records (every transition, the first-degradation
timeline, final verdicts); given a master URL it shows the live
health document (current states, detector-pressure evidence,
dominator window, recent alerts).

``tuner`` (ISSUE 15) renders the self-tuning data plane: given a
durable sink DIRECTORY it prints the decision history (every
per-link decision the ranks noted, plus fenced leader updates and
audit trips from the alert stream); given a master URL it shows the
live tuner document (mode, leader overrides, per-rank applied
decisions, trip state).

``fleet`` (ISSUE 18) scrapes N job masters' ``/metrics.json`` +
``/health.json`` endpoints on a cadence and renders the cross-job
fleet table: one row per job (staleness state ``LIVE``/``STALE``/
``GONE``, ranks, rates, retries, health-ladder tally), shared-host
blocks with per-job byte attribution on each co-resident host
fingerprint, and cross-job ``CONTENTION`` rows. ``--sink DIR`` (or
``MP4J_FLEET_SINK_DIR``) additionally lands the fleet history
durably as crc-framed segments; ``fleet-report`` reconstructs the
merged fleet event timeline (job up/stale/gone/restart, health
transitions, autoscaler actions, contention episodes) offline from
such a directory.

``bench-diff`` compares two ``bench.py`` JSON outputs against
per-metric regression budgets (``obs.benchdiff``); exit 1 on a
regression — the perf gate.

Exit codes: 0 ok, 1 bench-diff regression / replay divergence, 2 bad
invocation / unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

from ytk_mp4j_tpu.obs import (audit, benchdiff, critpath,
                              fleet as fleet_mod,
                              health as health_mod, postmortem,
                              sink as sink_mod, spans, telemetry)
from ytk_mp4j_tpu.utils import tuning


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mp4j-scope",
        description="cluster-wide mp4j telemetry: timeline merge, "
                    "cross-rank skew report, live metrics view, "
                    "postmortem merge, bench regression gate")
    sub = ap.add_subparsers(dest="cmd", required=True)

    mg = sub.add_parser("merge", help="merge per-rank Chrome-trace "
                                      "files into one timeline")
    mg.add_argument("-o", "--out", required=True,
                    help="output trace-event JSON path")
    mg.add_argument("traces", nargs="+", help="per-rank trace files")

    rp = sub.add_parser("report", help="cross-rank skew table from "
                                       "per-rank comm.stats() dumps")
    rp.add_argument("--json", action="store_true",
                    help="emit the skew as JSON instead of a table")
    rp.add_argument("stats", nargs="+", help="per-rank stats JSON files")

    lv = sub.add_parser("live", help="poll a running master's metrics "
                                     "endpoint (MP4J_METRICS_PORT)")
    lv.add_argument("url", help="endpoint base, e.g. "
                                "http://127.0.0.1:9090 (scheme optional)")
    lv.add_argument("--interval", type=float, default=1.0,
                    help="poll period in seconds (default 1.0)")
    lv.add_argument("--once", action="store_true",
                    help="print one frame and exit (no screen clears)")

    pm = sub.add_parser("postmortem",
                        help="merge a flight-recorder directory into "
                             "one report naming the dead/lagging rank")
    pm.add_argument("dir", help="the job's MP4J_POSTMORTEM_DIR")

    rp2 = sub.add_parser("replay",
                         help="re-execute a captured audit bundle on "
                              "the thread backend and diff digests "
                              "record-by-record (MP4J_AUDIT=capture)")
    rp2.add_argument("dir", help="bundle dir (rank_*/audit.json)")

    an = sub.add_parser("analyze",
                        help="job-lifetime critical-path report from "
                             "a durable sink directory "
                             "(MP4J_SINK_DIR)")
    an.add_argument("dir", help="sink dir (rank_*/seg_*.mp4j)")
    an.add_argument("--json", action="store_true",
                    help="emit the structured analysis as JSON")

    hp = sub.add_parser("health",
                        help="per-rank health verdicts: history from "
                             "a sink dir, or live from a master URL")
    hp.add_argument("target",
                    help="a MP4J_SINK_DIR (verdict history) or a "
                         "master metrics URL (current verdicts)")
    hp.add_argument("--json", action="store_true",
                    help="emit the raw health document/alert list")

    tn = sub.add_parser("tuner",
                        help="self-tuning data-plane decisions: "
                             "history from a sink dir, or live "
                             "per-link decisions from a master URL")
    tn.add_argument("target",
                    help="a MP4J_SINK_DIR (decision history) or a "
                         "master metrics URL (live tuner document)")
    tn.add_argument("--json", action="store_true",
                    help="emit the raw tuner document/event list")

    tl = sub.add_parser("tail",
                        help="follow a durable sink directory live, "
                             "one line per completed collective")
    tl.add_argument("dir", help="sink dir (rank_*/seg_*.mp4j)")
    tl.add_argument("--interval", type=float, default=1.0,
                    help="poll period in seconds (default 1.0)")
    tl.add_argument("--once", action="store_true",
                    help="print the current backlog and exit")

    fl = sub.add_parser("fleet",
                        help="scrape N job masters and render the "
                             "cross-job fleet table (shared hosts, "
                             "contention, per-job health)")
    fl.add_argument("urls", nargs="+", metavar="URL",
                    help="master endpoint bases, e.g. "
                         "http://127.0.0.1:9090 (scheme optional)")
    fl.add_argument("--interval", type=float, default=None,
                    help="poll period in seconds (default "
                         "MP4J_FLEET_POLL_SECS, 2.0)")
    fl.add_argument("--once", action="store_true",
                    help="one scrape sweep + one frame, then exit")
    fl.add_argument("--sink", default=None, metavar="DIR",
                    help="land fleet history durably in DIR as "
                         "crc-framed segments (default "
                         "MP4J_FLEET_SINK_DIR; empty = no sink)")

    fr = sub.add_parser("fleet-report",
                        help="merged fleet event timeline + "
                             "contention episodes from a fleet sink "
                             "directory, offline")
    fr.add_argument("dir", help="fleet sink dir (seg_*.mp4j)")
    fr.add_argument("--json", action="store_true",
                    help="emit the raw reconstruction as JSON")

    bd = sub.add_parser("bench-diff",
                        help="compare two bench.py JSON outputs "
                             "against per-metric regression budgets")
    bd.add_argument("old", help="baseline BENCH file")
    bd.add_argument("new", help="candidate BENCH file")
    bd.add_argument("--threshold", type=float, default=None,
                    metavar="PCT",
                    help="override every per-metric budget with this "
                         "max tolerated drop, in percent (e.g. 10)")
    return ap


def _load_rank_stats(paths: list[str]) -> dict[int, dict]:
    per_rank: dict[int, dict] = {}
    for i, p in enumerate(paths):
        with open(p, encoding="utf-8") as fh:
            doc = json.load(fh)
        if isinstance(doc, dict) and "stats" in doc and "rank" in doc:
            per_rank[int(doc["rank"])] = doc["stats"]
        elif isinstance(doc, dict):
            per_rank[i] = doc
        else:
            raise ValueError(f"{p}: not a stats snapshot")
    return per_rank


def _fetch_doc(base: str) -> dict:
    if "://" not in base:
        base = "http://" + base
    with urllib.request.urlopen(base.rstrip("/") + "/metrics.json",
                                timeout=5.0) as resp:
        return json.load(resp)


def _analyze(args) -> int:
    analysis = critpath.analyze(sink_mod.load_job(args.dir))
    if args.json:
        print(json.dumps(analysis, sort_keys=True, default=str))
    else:
        print(critpath.format_report(analysis, args.dir))
    return 0


def _tail(args) -> int:
    """Follow mode: each poll re-reads the sink and prints every
    collective whose cross-rank attribution is COMPLETE and new
    since the last poll, plus recovery events as they land. An
    ordinal is held back until every rank's spans have landed —
    ranks flush on independent cadences, and attributing from the
    ranks that happened to flush first would systematically
    misattribute exactly the ordinals a slow-flushing straggler
    gates. An ordinal older than the newest fully-covered one can
    never complete (a rank died mid-job) and prints with what
    survived. Full re-reads keep the loop simple and robust against
    rotation/eviction under the tailer; a sink directory is at most
    slave_num * MP4J_SINK_BYTES."""
    seen: set[int] = set()
    pending: dict[int, int] = {}    # seq -> polls waited incomplete
    seen_recovery: dict[int, int] = {}
    while True:
        analysis = critpath.analyze(sink_mod.load_job(args.dir))
        n = max((int(m.get("slave_num") or 0)
                 for m in analysis["meta"].values()), default=0) \
            or len(analysis["ranks"])
        horizon = max((r["seq"] for r in analysis["rows"]
                       if len(r["waits"]) >= n), default=0)
        for row in analysis["rows"]:
            seq = row["seq"]
            if seq in seen:
                continue
            # emit once coverage is complete, once a NEWER ordinal is
            # fully covered (every rank already flushed past this
            # one), after 3 incomplete polls (a dead rank's spans are
            # never coming — the ordinals around a crash must not be
            # withheld forever), or on --once (final state)
            stale = pending.get(seq, 0) >= 3
            if len(row["waits"]) >= n or seq < horizon or stale \
                    or args.once:
                seen.add(seq)
                pending.pop(seq, None)
                print(critpath.format_row(row), flush=True)
            else:
                pending[seq] = pending.get(seq, 0) + 1
        for rank, events in sorted(analysis["recovery"].items()):
            start = seen_recovery.get(rank, 0)
            for _, kind, detail in events[start:]:
                print(f"rank {rank} recovery: {kind}"
                      + (f" ({detail})" if detail else ""), flush=True)
            seen_recovery[rank] = len(events)
        if args.once:
            return 0
        try:
            time.sleep(max(args.interval, 0.1))
        except KeyboardInterrupt:
            return 0


def _health(args) -> int:
    """Verdict history from a sink dir, or current verdicts from a
    live master (the ISSUE 12 operator view)."""
    if os.path.isdir(args.target):
        analysis = critpath.analyze(sink_mod.load_job(args.target))
        alerts = analysis.get("health_alerts") or []
        if args.json:
            print(json.dumps(alerts, sort_keys=True, default=str))
        else:
            print(health_mod.format_history(alerts,
                                            analysis["ranks"]))
        return 0
    doc = _fetch_doc(args.target)
    hl = (doc.get("cluster") or {}).get("health")
    if args.json:
        print(json.dumps(hl, sort_keys=True, default=str))
    else:
        print(health_mod.format_status(hl or {}))
    return 0


def _format_tuner_doc(doc: dict | None) -> str:
    """The live tuner view (ISSUE 15): mode/trip head line, leader
    overrides, then one line per rank with its applied per-link
    decisions."""
    if not doc:
        return "tuner: off (MP4J_TUNER=off — static knobs only)"
    lines = [f"tuner: mode={doc.get('mode')} "
             f"demotions={doc.get('demotions', 0)} "
             f"version={doc.get('version', 0)}"
             + (f"  TRIPPED: {doc['tripped']}"
                if doc.get("tripped") else "")]
    if doc.get("overrides"):
        lines.append(f"  leader overrides (host group -> leader): "
                     f"{doc['overrides']}")
    for r in sorted(doc.get("ranks") or {}, key=int):
        t = doc["ranks"][r] or {}
        applied = t.get("applied") or {}
        dec = ", ".join(
            f"->{p}: chunk={d.get('chunk_bytes') or 'static'} "
            f"compress={'static' if d.get('compress') is None else d['compress']}"
            for p, d in sorted(applied.items(), key=lambda kv: int(kv[0])))
        lines.append(
            f"  rank {r}: decisions={t.get('decisions_total', 0)}"
            + (f"  TRIPPED: {t['tripped']}" if t.get("tripped") else "")
            + (f"  [{dec}]" if dec else "  [all links static]"))
    for ev in (doc.get("events") or [])[-6:]:
        lines.append("  " + health_mod.format_alert(ev))
    return "\n".join(lines)


def _tuner(args) -> int:
    """Decision history from a sink dir, or the live tuner document
    from a master URL (the ISSUE 15 operator view)."""
    if os.path.isdir(args.target):
        analysis = critpath.analyze(sink_mod.load_job(args.target))
        events = analysis.get("tuner_events") or []
        alerts = [a for a in (analysis.get("health_alerts") or ())
                  if a.get("kind") == "tuner"]
        if args.json:
            print(json.dumps({"events": events, "alerts": alerts},
                             sort_keys=True, default=str))
            return 0
        if not events and not alerts:
            print("no tuner events in this sink directory "
                  "(MP4J_TUNER=off, or the job made no decisions)")
            return 0
        for ev in events:
            print(f"rank {ev['rank']}: {ev['msg']}")
        for a in alerts:
            print(health_mod.format_alert(a))
        return 0
    doc = _fetch_doc(args.target)
    tun = (doc.get("cluster") or {}).get("tuner")
    if args.json:
        print(json.dumps(tun, sort_keys=True, default=str))
    else:
        print(_format_tuner_doc(tun))
    return 0


def _live(args) -> int:
    last_frame: str | None = None
    last_ok: float | None = None
    while True:
        try:
            last_frame = telemetry.format_live(_fetch_doc(args.url))
            last_ok = time.monotonic()
            frame = last_frame
        except (OSError, ValueError, json.JSONDecodeError) as e:
            # mid-watch endpoint death is a FACT to render, not a
            # traceback to die with (ISSUE 18 satellite) — but an
            # endpoint that never answered once is a usage error and
            # keeps the exit-2 path
            if args.once or last_ok is None:
                raise
            frame = (last_frame + "\n" if last_frame else "") + (
                f"STALE (last seen "
                f"{time.monotonic() - last_ok:.0f}s ago) — "
                f"{args.url}: {e}")
        if args.once:
            print(frame)
            return 0
        # ANSI clear + home: a poor man's top(1); the frame is small
        print("\x1b[2J\x1b[H" + frame, flush=True)
        try:
            time.sleep(max(args.interval, 0.1))
        except KeyboardInterrupt:
            return 0


def _fleet(args) -> int:
    """The cross-job fleet watch (ISSUE 18): one FleetPoller sweep
    per interval, rendered via ``telemetry.format_fleet``. Staleness
    handling lives in the poller — a dead master degrades its own
    row (LIVE -> STALE -> GONE), never this loop."""
    sink_dir = args.sink if args.sink is not None \
        else tuning.fleet_sink_dir()
    fs = fleet_mod.FleetSink(sink_dir) if sink_dir else None
    poller = fleet_mod.FleetPoller(args.urls, poll_secs=args.interval,
                                   sink=fs)
    try:
        while True:
            frame = telemetry.format_fleet(poller.poll_once())
            if args.once:
                print(frame)
                return 0
            print("\x1b[2J\x1b[H" + frame, flush=True)
            try:
                time.sleep(max(poller.poll_secs, 0.1))
            except KeyboardInterrupt:
                return 0
    finally:
        if fs is not None:
            fs.close()


def _fleet_report(args) -> int:
    report = fleet_mod.fleet_report(args.dir)
    if args.json:
        print(json.dumps(report, sort_keys=True, default=str))
    else:
        print(telemetry.format_fleet_report(report))
    return 0


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    try:
        if args.cmd == "merge":
            n = spans.merge_chrome_traces(args.out, args.traces)
            print(f"mp4j-scope: merged {n} events from "
                  f"{len(args.traces)} file(s) into {args.out}")
            return 0
        if args.cmd == "live":
            return _live(args)
        if args.cmd == "postmortem":
            print(postmortem.merge_report(args.dir))
            return 0
        if args.cmd == "replay":
            text, diverged = audit.replay_bundle(args.dir)
            print(text)
            return 1 if diverged else 0
        if args.cmd == "analyze":
            return _analyze(args)
        if args.cmd == "health":
            return _health(args)
        if args.cmd == "tuner":
            return _tuner(args)
        if args.cmd == "tail":
            return _tail(args)
        if args.cmd == "fleet":
            return _fleet(args)
        if args.cmd == "fleet-report":
            return _fleet_report(args)
        if args.cmd == "bench-diff":
            thr = (None if args.threshold is None
                   else args.threshold / 100.0)
            text, regressed = benchdiff.run(args.old, args.new, thr)
            print(text)
            return 1 if regressed else 0
        skew = telemetry.cluster_skew(_load_rank_stats(args.stats))
        if args.json:
            print(json.dumps(skew, sort_keys=True))
        else:
            print(telemetry.format_skew(skew))
        return 0
    except (OSError, ValueError, KeyError, json.JSONDecodeError,
            urllib.error.URLError) as e:
        print(f"mp4j-scope: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
