"""``mp4j-scope`` — cluster telemetry CLI.

Usage::

    mp4j-scope merge -o merged.json rank0.json rank1.json ...
    mp4j-scope report [--json] stats0.json stats1.json ...
    python -m ytk_mp4j_tpu.obs report ...

``merge`` combines per-rank Chrome-trace exports
(``trace.export_chrome_trace`` output, one file per rank) into a single
timeline loadable in ``chrome://tracing`` / Perfetto — ranks keep
distinct ``pid`` tracks.

``report`` renders the cross-rank skew table (per-collective
min/median/max busy time, bytes, straggler ranks) from per-rank
``comm.stats()`` JSON dumps. Each input file holds either one rank's
snapshot (``{collective: {...}}``, rank taken from the argument order)
or an explicit ``{"rank": N, "stats": {...}}`` wrapper.

Exit codes: 0 ok, 2 bad invocation / unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys

from ytk_mp4j_tpu.obs import spans, telemetry


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mp4j-scope",
        description="cluster-wide mp4j telemetry: timeline merge + "
                    "cross-rank skew report")
    sub = ap.add_subparsers(dest="cmd", required=True)

    mg = sub.add_parser("merge", help="merge per-rank Chrome-trace "
                                      "files into one timeline")
    mg.add_argument("-o", "--out", required=True,
                    help="output trace-event JSON path")
    mg.add_argument("traces", nargs="+", help="per-rank trace files")

    rp = sub.add_parser("report", help="cross-rank skew table from "
                                       "per-rank comm.stats() dumps")
    rp.add_argument("--json", action="store_true",
                    help="emit the skew as JSON instead of a table")
    rp.add_argument("stats", nargs="+", help="per-rank stats JSON files")
    return ap


def _load_rank_stats(paths: list[str]) -> dict[int, dict]:
    per_rank: dict[int, dict] = {}
    for i, p in enumerate(paths):
        with open(p, encoding="utf-8") as fh:
            doc = json.load(fh)
        if isinstance(doc, dict) and "stats" in doc and "rank" in doc:
            per_rank[int(doc["rank"])] = doc["stats"]
        elif isinstance(doc, dict):
            per_rank[i] = doc
        else:
            raise ValueError(f"{p}: not a stats snapshot")
    return per_rank


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    try:
        if args.cmd == "merge":
            n = spans.merge_chrome_traces(args.out, args.traces)
            print(f"mp4j-scope: merged {n} events from "
                  f"{len(args.traces)} file(s) into {args.out}")
            return 0
        skew = telemetry.cluster_skew(_load_rank_stats(args.stats))
        if args.json:
            print(json.dumps(skew, sort_keys=True))
        else:
            print(telemetry.format_skew(skew))
        return 0
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"mp4j-scope: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
