"""mp4j-fleet — cross-job fleet observability (ISSUE 18).

Every observability plane below this one ends at ONE master and ONE
job. Production traffic is many concurrent jobs sharing hosts and
links — and before any federation broker can *arbitrate* spares and
links between jobs, something has to *see* across them. This module is
that read-only fleet plane:

- :class:`FleetPoller` scrapes N job masters' ``/metrics.json`` +
  ``/health.json`` control surfaces (the PR 13 endpoints built "for
  EXTERNAL orchestrators") on a cadence, with a bounded timeout on
  every request and a per-job staleness/backoff state machine —
  ``LIVE -> STALE -> GONE`` — so a hung or dead master degrades its
  OWN row and never wedges or crashes the poller. A master restart is
  detected as a ``job_id`` change at the same URL (the ISSUE 18
  identity stamp), never guessed from heuristics.
- :func:`job_summary` / :func:`fold_fleet` fold the per-job documents
  into a **host- and link-centric fleet model** keyed on the roster
  host fingerprints (ISSUE 7): which jobs co-reside on which host,
  each job's wire bytes and live byte rate on that host, its per-link
  tuner decisions there, a health-ladder tally, and the cluster
  aggregate rates.
- :func:`detect_contention` flags the single-tenant blind spot the
  ROADMAP names: two jobs sharing a host both see "the link is slow"
  and neither yields. Detected as **overlapping busy windows** (both
  jobs moving bytes on the same host fingerprint in the same poll)
  plus **simultaneous slow-link verdicts** (each job's tuner applied
  per-link decisions there — the verdict a single-tenant tuner
  reaches when its link underperforms).
- :class:`FleetSink` lands fleet history durably using the crc-framed
  segment format of :mod:`ytk_mp4j_tpu.obs.sink` (same torn-tail
  recovery guarantees, same rotation/eviction budget discipline), and
  :func:`fleet_report` reconstructs the merged **fleet event
  timeline** — job up/stale/gone/restart, per-rank health
  transitions, autoscaler actions, contention onsets — offline from a
  fleet sink directory (``mp4j-scope fleet-report``).

Obs discipline: imports nothing from ``comm`` — the poller observes
jobs strictly through their public HTTP control surfaces, exactly like
an external orchestrator would.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

from ytk_mp4j_tpu.obs import sink as sink_mod
from ytk_mp4j_tpu.utils import tuning

LIVE = "LIVE"
STALE = "STALE"
GONE = "GONE"
# GONE follows STALE at this multiple of MP4J_FLEET_STALE_SECS: one
# missed scrape window is a blip, three is a corpse
GONE_FACTOR = 3.0
# consecutive-failure backoff cap, in poll periods: a dead master is
# re-probed often enough to catch a restart, rarely enough not to
# burn the sweep budget on connection timeouts
_BACKOFF_CAP_POLLS = 8.0
# bounded in-memory event ring (the durable copy rides FleetSink)
_EVENT_CAP = 4096


def normalize_url(base: str) -> str:
    """Scheme-optional like ``mp4j-scope live``: ``host:port`` means
    ``http://host:port``."""
    if "://" not in base:
        base = "http://" + base
    return base.rstrip("/")


# ---------------------------------------------------------------------
# pure folds: per-job documents -> fleet model
# ---------------------------------------------------------------------
def _rank_wire_bytes(info: dict) -> int:
    return int(sum(e.get("bytes_sent", 0) + e.get("bytes_recv", 0)
                   for e in (info.get("stats") or {}).values()))


def _slow_links(tuner_doc: dict | None, rank: str) -> list[str]:
    """The tuner's applied per-link decisions for one rank, as
    ``"rank->peer"`` tokens. An applied decision (a non-static chunk
    size or an explicit compress verdict) IS the single-tenant
    "this link is slow/underperforming" verdict the contention
    detector cross-references between jobs."""
    t = (tuner_doc or {}).get("ranks", {}).get(rank) or {}
    out = []
    for peer, dec in sorted((t.get("applied") or {}).items(),
                            key=lambda kv: str(kv[0])):
        if dec and (dec.get("chunk_bytes") is not None
                    or dec.get("compress") is not None):
            out.append(f"{rank}->{peer}")
    return out


def job_summary(metrics_doc: dict, health_doc: dict | None = None
                ) -> dict:
    """Fold ONE job's control documents into its fleet row: identity,
    aggregate rates, retry total, health-ladder tally, and the
    host-centric view (ranks / wire bytes / live byte rate / slow
    links per roster host fingerprint). Pure — the poller and the
    synthetic-document tests share it."""
    ranks = metrics_doc.get("ranks") or {}
    cl = metrics_doc.get("cluster") or {}
    rates = cl.get("rates") or {}
    tuner = cl.get("tuner")
    hosts: dict[str, dict] = {}
    retries = 0
    wire_bytes = 0
    for r, info in ranks.items():
        fp = str(info.get("host_fp") or "")
        h = hosts.setdefault(fp, {"ranks": [], "wire_bytes": 0,
                                  "bytes_per_sec": 0.0,
                                  "slow_links": []})
        h["ranks"].append(int(r))
        rb = _rank_wire_bytes(info)
        h["wire_bytes"] += rb
        wire_bytes += rb
        h["bytes_per_sec"] += float(
            (info.get("rates") or {}).get("bytes_per_sec", 0.0))
        h["slow_links"].extend(_slow_links(tuner, str(r)))
        retries += int(sum(e.get("retries", 0)
                           for e in (info.get("stats") or {}).values()))
    for h in hosts.values():
        h["ranks"].sort()
    # health-ladder tally from /health.json (falls back to the metrics
    # doc's cluster.health section — same schema — when the health
    # endpoint was unreachable but metrics was not)
    hdoc = health_doc if health_doc is not None else cl.get("health")
    hstates = {str(r): e.get("state", "HEALTHY")
               for r, e in ((hdoc or {}).get("ranks") or {}).items()}
    ladder: dict[str, int] = {}
    for s in hstates.values():
        ladder[s] = ladder.get(s, 0) + 1
    asc = cl.get("autoscale") or {}
    # serve summary (ISSUE 19): carried whole so the fleet view can
    # render serve jobs distinctly (QPS cell); None for batch jobs
    serve = cl.get("serve") if (cl.get("serve") or {}).get("active") \
        else None
    return {
        "job_id": str(metrics_doc.get("job_id") or ""),
        "started_wall": metrics_doc.get("started_wall"),
        "roster_gen": int(metrics_doc.get("roster_gen") or 0),
        "slave_num": int(metrics_doc.get("slave_num") or 0),
        "ranks_reporting": len(ranks),
        "bytes_per_sec": float(rates.get("bytes_per_sec", 0.0)),
        "collectives_per_sec": float(
            rates.get("collectives_per_sec", 0.0)),
        "keys_per_sec": float(rates.get("keys_per_sec", 0.0)),
        "wire_bytes": wire_bytes,
        "retries": retries,
        "hosts": hosts,
        "health": {
            "states": ladder,
            "by_rank": hstates,
            "alerts_total": int((hdoc or {}).get("alerts_total") or 0),
            "evict_recommended": list(
                (hdoc or {}).get("evict_recommended") or ()),
        },
        "autoscale_actions": int(
            sum((asc.get("actions") or {}).values())
            + sum((asc.get("observed") or {}).values())),
        "serve": serve,
    }


def detect_contention(hosts: dict[str, dict],
                      busy_bytes_per_sec: float = 0.0) -> list[dict]:
    """Cross-job contention rows from a folded host map
    (``fold_fleet``'s ``hosts``): a host fingerprint where at least
    two jobs show **overlapping busy windows** (live byte rate above
    ``busy_bytes_per_sec`` in the same poll) and at least two of
    those busy jobs **simultaneously hold slow-link verdicts** there
    (tuner applied decisions). That conjunction is the single-tenant
    blind spot: each job's tuner correctly concluded its own link is
    slow, and none of them can see that the *other tenant* is why."""
    out = []
    for fp in sorted(hosts):
        if not fp:
            continue        # "" = fingerprint opt-out, not a host
        jobs = hosts[fp].get("jobs") or {}
        busy = {jid: j for jid, j in jobs.items()
                if float(j.get("bytes_per_sec", 0.0))
                > busy_bytes_per_sec}
        slow = {jid: j["slow_links"] for jid, j in busy.items()
                if j.get("slow_links")}
        if len(busy) >= 2 and len(slow) >= 2:
            out.append({"host_fp": fp,
                        "jobs": sorted(busy),
                        "slow": {jid: list(v)
                                 for jid, v in sorted(slow.items())}})
    return out


def fold_fleet(jobstates: dict[str, dict],
               busy_bytes_per_sec: float = 0.0) -> dict:
    """The fleet model: fold per-URL poll states (``{"url", "state",
    "age", "summary"|None}``) into per-job rows, the host-centric
    co-residency map, contention rows and the aggregate. Pure — the
    poller feeds it live states, tests feed it synthetic ones.

    A STALE job's last summary still participates in the host map
    (its ranks have not provably left the host — that is what STALE
    means), but only LIVE jobs count toward the aggregate rates and
    the busy side of contention: a frozen byte rate from a wedged
    master must not manufacture phantom load."""
    hosts: dict[str, dict] = {}
    agg = {"jobs": len(jobstates), "live": 0, "ranks": 0,
           "bytes_per_sec": 0.0, "collectives_per_sec": 0.0}
    for key in sorted(jobstates):
        st = jobstates[key]
        s = st.get("summary")
        if s is None:
            continue
        live = st.get("state") == LIVE
        if live:
            agg["live"] += 1
            agg["ranks"] += s["ranks_reporting"]
            agg["bytes_per_sec"] += s["bytes_per_sec"]
            agg["collectives_per_sec"] += s["collectives_per_sec"]
        jid = s["job_id"] or st.get("url") or key
        for fp, h in (s.get("hosts") or {}).items():
            row = hosts.setdefault(str(fp), {"jobs": {}})
            row["jobs"][jid] = {
                "url": st.get("url", key),
                "state": st.get("state"),
                "ranks": list(h["ranks"]),
                "wire_bytes": int(h["wire_bytes"]),
                # a non-LIVE job's rate is history, not load (above)
                "bytes_per_sec": (float(h["bytes_per_sec"])
                                  if live else 0.0),
                "slow_links": list(h["slow_links"]),
            }
    shared = sorted(fp for fp, row in hosts.items()
                    if fp and len(row["jobs"]) >= 2)
    return {
        "jobs": {key: {"url": st.get("url", key),
                       "state": st.get("state"),
                       "age": float(st.get("age", 0.0)),
                       "summary": st.get("summary")}
                 for key, st in jobstates.items()},
        "hosts": hosts,
        "shared_hosts": shared,
        "contention": detect_contention(hosts, busy_bytes_per_sec),
        "aggregate": agg,
    }


# ---------------------------------------------------------------------
# the poller
# ---------------------------------------------------------------------
class FleetPoller:
    """Scrape N job masters on a cadence and maintain the fleet model.

    Never crashes, never hangs: every fetch carries an explicit
    bounded ``timeout`` (mp4j-lint R27 territory), every per-job
    failure is absorbed into that job's ``LIVE -> STALE -> GONE``
    state machine with capped exponential backoff, and
    :meth:`poll_once` is exception-free by construction (scrape-side
    surprises are counted in ``scrape_errors``, fold-side code is
    pure). A master that comes back under the SAME URL with a NEW
    ``job_id`` is a restart (``job_restart`` event), not a
    continuation.

    ``fetch`` is the injection seam for deterministic tests: a
    callable ``(url) -> (metrics_doc, health_doc)`` raising on
    failure. The default fetches both documents over HTTP. ``now``
    likewise injects the monotonic clock.
    """

    def __init__(self, urls, *, poll_secs: float | None = None,
                 stale_secs: float | None = None,
                 timeout: float | None = None,
                 sink: "FleetSink | None" = None,
                 fetch=None, now=time.monotonic):
        self.urls = [normalize_url(u) for u in urls]
        self.poll_secs = (tuning.fleet_poll_secs()
                          if poll_secs is None else float(poll_secs))
        self.stale_secs = (tuning.fleet_stale_secs()
                           if stale_secs is None else float(stale_secs))
        # per-request bound: never longer than the staleness budget
        # (a scrape still in flight when its job goes STALE is the
        # wedge this plane exists to avoid), never degenerate
        self.timeout = (max(0.1, min(self.poll_secs, 5.0,
                                     self.stale_secs / 2))
                        if timeout is None else float(timeout))
        self.sink = sink
        self._fetch = fetch if fetch is not None else self._http_fetch
        self._now = now
        self.scrape_errors = 0          # absorbed per-job failures
        self._lock = threading.Lock()
        t0 = self._now()
        self._jobs: dict[str, dict] = {
            u: {"url": u, "state": STALE, "job_id": None,
                "summary": None, "last_ok": None, "born": t0,
                "failures": 0, "next_try": t0, "last_error": None}
            for u in self.urls}
        self._events: list[dict] = []
        self._contended: set[str] = set()
        self._model: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- scraping ------------------------------------------------------
    def _http_fetch(self, url: str) -> tuple[dict, dict | None]:
        with urllib.request.urlopen(url + "/metrics.json",
                                    timeout=self.timeout) as resp:
            mdoc = json.load(resp)
        try:
            with urllib.request.urlopen(url + "/health.json",
                                        timeout=self.timeout) as resp:
                hdoc = json.load(resp)
        except Exception:       # noqa: BLE001 - metrics alone suffices
            # (an old master without the health endpoint, a scrape
            # racing shutdown): the fold falls back to the metrics
            # doc's cluster.health section
            hdoc = None
        return mdoc, hdoc if isinstance(hdoc, dict) else None

    def _event(self, kind: str, job: dict, msg: str,
               events_out: list[dict]) -> None:
        ev = {
            # wall stamp: fleet timelines merge across machines, like
            # every sink artifact
            # mp4j-lint: disable=R11 (event timestamp, not a duration)
            "wall": time.time(),
            "kind": kind, "url": job["url"],
            "job_id": job.get("job_id"), "msg": msg}
        self._events.append(ev)
        del self._events[:-_EVENT_CAP]
        events_out.append(ev)

    def _note_success(self, job: dict, mdoc: dict, hdoc,
                      events_out: list[dict]) -> None:
        summary = job_summary(mdoc, hdoc)
        jid = summary["job_id"] or None
        prev = job.get("job_id")
        prev_summary = job.get("summary")
        if prev is None and jid is not None and prev_summary is None:
            self._event("job_up", {**job, "job_id": jid},
                        f"job {jid} up at {job['url']} "
                        f"({summary['slave_num']} ranks)", events_out)
        elif prev is not None and jid is not None and jid != prev:
            self._event("job_restart", {**job, "job_id": jid},
                        f"{job['url']}: job id {prev} -> {jid} "
                        "(master restarted)", events_out)
        elif job["state"] != LIVE:
            self._event("job_back", {**job, "job_id": jid},
                        f"job {jid} reachable again "
                        f"(was {job['state']})", events_out)
        # per-rank health transitions between consecutive scrapes of
        # the SAME job incarnation
        if prev_summary is not None and jid == prev:
            old = prev_summary["health"]["by_rank"]
            for r, s in sorted(summary["health"]["by_rank"].items(),
                               key=lambda kv: kv[0]):
                o = old.get(r)
                if o is not None and o != s:
                    self._event("health", job,
                                f"job {jid}: rank {r} {o}->{s}",
                                events_out)
            if (summary["autoscale_actions"]
                    > prev_summary["autoscale_actions"]):
                self._event("autoscale", job,
                            f"job {jid}: autoscaler acted "
                            f"({summary['autoscale_actions']} total)",
                            events_out)
        job.update(state=LIVE, job_id=jid, summary=summary,
                   last_ok=self._now(), failures=0, last_error=None,
                   next_try=self._now())

    def _note_failure(self, job: dict, err: Exception,
                      events_out: list[dict]) -> None:
        self.scrape_errors += 1
        job["failures"] += 1
        job["last_error"] = repr(err)
        # capped exponential backoff: a dead master costs one bounded
        # connect attempt per backoff window, not per sweep
        delay = min(self.poll_secs * (2.0 ** (job["failures"] - 1)),
                    self.poll_secs * _BACKOFF_CAP_POLLS)
        job["next_try"] = self._now() + delay

    def _age(self, job: dict) -> float:
        ref = job["last_ok"] if job["last_ok"] is not None \
            else job["born"]
        return max(0.0, self._now() - ref)

    def _degrade(self, job: dict, events_out: list[dict]) -> None:
        """Advance the staleness ladder from the age of the last
        successful scrape — runs every sweep, backoff or not, so a
        job in deep backoff still degrades on schedule."""
        age = self._age(job)
        if age > self.stale_secs * GONE_FACTOR:
            if job["state"] != GONE:
                self._event("job_gone", job,
                            f"job {job.get('job_id') or job['url']} "
                            f"GONE (no scrape for {age:.1f}s)",
                            events_out)
                job["state"] = GONE
        elif age > self.stale_secs:
            if job["state"] == LIVE:
                self._event("job_stale", job,
                            f"job {job.get('job_id') or job['url']} "
                            f"STALE (no scrape for {age:.1f}s)",
                            events_out)
                job["state"] = STALE

    # -- one sweep -----------------------------------------------------
    def poll_once(self) -> dict:
        """One scrape sweep over every URL + fold + event detection +
        durable append. Returns the fresh fleet model. Never raises —
        the chaos contract: SIGKILL of an entire job mid-poll shows
        up as that job's STALE->GONE walk, zero exceptions here."""
        events_out: list[dict] = []
        with self._lock:
            for url in self.urls:
                job = self._jobs[url]
                if self._now() >= job["next_try"]:
                    try:
                        mdoc, hdoc = self._fetch(url)
                        if not isinstance(mdoc, dict):
                            raise ValueError(
                                f"{url}: non-object metrics document")
                        self._note_success(job, mdoc, hdoc, events_out)
                    except Exception as e:  # noqa: BLE001 - absorbed
                        # into the state machine; ANY scrape-side
                        # surprise (refused, reset, timeout, torn
                        # JSON, schema garbage) is a staleness fact
                        # about that job, not a poller fatal
                        self._note_failure(job, e, events_out)
                self._degrade(job, events_out)
            model = fold_fleet(
                {u: {"url": j["url"], "state": j["state"],
                     "age": self._age(j), "summary": j["summary"]}
                 for u, j in self._jobs.items()})
            now_contended = {c["host_fp"] for c in model["contention"]}
            for fp in sorted(now_contended - self._contended):
                row = next(c for c in model["contention"]
                           if c["host_fp"] == fp)
                self._event(
                    "contention_on", {"url": "", "job_id": None},
                    f"host {fp}: cross-job contention between "
                    f"{', '.join(row['jobs'])} (slow links: "
                    + "; ".join(f"{j}: {','.join(v)}"
                                for j, v in row["slow"].items())
                    + ")", events_out)
            for fp in sorted(self._contended - now_contended):
                self._event("contention_off", {"url": "",
                                               "job_id": None},
                            f"host {fp}: contention cleared",
                            events_out)
            self._contended = now_contended
            self._model = model
        if self.sink is not None:
            for ev in events_out:
                self.sink.append({"t": "fleet_event", **ev})
            self.sink.append({
                "t": "fleet",
                # mp4j-lint: disable=R11 (snapshot timestamp)
                "wall": time.time(),
                "jobs": {k: {"url": v["url"], "state": v["state"],
                             "age": round(v["age"], 3),
                             "summary": v["summary"]}
                         for k, v in model["jobs"].items()},
                "shared_hosts": model["shared_hosts"],
                "contention": model["contention"],
                "aggregate": model["aggregate"]})
        return model

    def model(self) -> dict | None:
        """The last folded fleet model (None before the first sweep)."""
        with self._lock:
            return self._model

    def events(self) -> list[dict]:
        """The bounded in-memory event tail, oldest first."""
        with self._lock:
            return list(self._events)

    def states(self) -> dict[str, str]:
        """``{url: LIVE|STALE|GONE}`` right now."""
        with self._lock:
            return {u: j["state"] for u, j in self._jobs.items()}

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FleetPoller":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="mp4j-fleet-poller")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_secs):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if self.sink is not None:
            self.sink.close()


# ---------------------------------------------------------------------
# durable fleet history
# ---------------------------------------------------------------------
class FleetSink:
    """Durable fleet history: the poller's snapshots and events as
    crc-framed records in rotating segment files under ONE directory
    (the :mod:`ytk_mp4j_tpu.obs.sink` framing — same torn-tail
    recovery: a ``kill -9`` mid-append tears at most the single frame
    being written, and :func:`read_fleet` recovers every prior
    record). Oldest-segment eviction bounds the directory at
    ``budget_bytes`` no matter how long the fleet is watched.

    Best-effort like the per-rank sink: a full disk degrades to
    dropped records (counted in ``dropped_records``), never to a
    poller failure."""

    def __init__(self, root: str, *, budget_bytes: int | None = None):
        self.root = str(root)
        self.budget = (tuning.sink_bytes() if budget_bytes is None
                       else int(budget_bytes))
        self.seg_bytes = max(64 * 1024, self.budget // 8)
        self._lock = threading.Lock()
        self._fh = None
        self._seg_index = 0
        self._seg_size = 0
        self._seg_sizes: dict[str, int] = {}     # basename -> bytes
        self.records_written = 0
        self.bytes_written = 0
        self.dropped_records = 0
        self.last_error: str | None = None

    def append(self, rec: dict) -> None:
        """Append one record frame; never raises (the poller must
        survive a full disk the way a rank's drain thread does)."""
        try:
            frame = sink_mod.encode_record({
                **rec, "v": 1})
            with self._lock:
                fh = self._ensure_segment(len(frame))
                sink_mod._write_all(fh, frame)
                self._seg_size += len(frame)
                self._seg_sizes[os.path.basename(self._seg_path())] = \
                    self._seg_size
                self.bytes_written += len(frame)
                self.records_written += 1
        except Exception as e:      # noqa: BLE001 - telemetry must
            # never fail the observer; see SinkWriter.flush
            with self._lock:
                self.dropped_records += 1
                self.last_error = repr(e)
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None

    def _seg_path(self) -> str:
        return os.path.join(self.root,
                            f"seg_{self._seg_index:08d}.mp4j")

    def _ensure_segment(self, incoming: int):
        """Open segment, rotating + evicting under the budget (the
        SinkWriter discipline, single-directory edition). Caller
        holds the lock."""
        if self._fh is not None and self._seg_size + incoming \
                > self.seg_bytes:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        if self._fh is None:
            os.makedirs(self.root, exist_ok=True)
            if not self._seg_sizes:
                # resume past anything already on disk
                for p in sink_mod.list_segments(self.root):
                    base = os.path.basename(p)
                    try:
                        self._seg_sizes[base] = os.path.getsize(p)
                        idx = int(base[len("seg_"):-len(".mp4j")])
                        self._seg_index = max(self._seg_index, idx + 1)
                    except (OSError, ValueError):
                        continue
            else:
                self._seg_index += 1
            self._evict(incoming)
            # unbuffered append-only segment write — crc-delimited
            # frames, reader tolerates a torn tail (sink precedent)
            # mp4j-lint: disable=R14 (sanctioned segment append path)
            self._fh = open(self._seg_path(), "ab", buffering=0)
            self._seg_size = 0
        return self._fh

    def _evict(self, incoming: int) -> None:
        target = max(self.seg_bytes, self.budget - self.seg_bytes)
        total = sum(self._seg_sizes.values()) + incoming
        active = os.path.basename(self._seg_path())
        for base in sorted(self._seg_sizes):
            if total <= target or base == active:
                break
            try:
                os.remove(os.path.join(self.root, base))
            except OSError:
                break       # can't evict the oldest -> newer ones
                # likely can't go either; keep the accounting honest
            total -= self._seg_sizes.pop(base)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_fleet(root: str) -> dict:
    """Every intact fleet record from a fleet sink directory
    (:func:`ytk_mp4j_tpu.obs.sink.read_dir` — the shared crc-framed
    reader, shared torn-tail guarantees)."""
    return sink_mod.read_dir(root)


def fleet_report(root: str) -> dict:
    """Offline reconstruction from a fleet sink dir: the merged event
    timeline (job up/stale/gone/restart, health transitions,
    autoscaler actions, contention on/off), the jobs ever seen with
    their last-known state, and contention EPISODES (onset..clear
    windows, open-ended when the history ends contended)."""
    doc = read_fleet(root)
    events = [r for r in doc["records"] if r.get("t") == "fleet_event"]
    events.sort(key=lambda e: e.get("wall", 0.0))
    snaps = [r for r in doc["records"] if r.get("t") == "fleet"]
    jobs: dict[str, dict] = {}
    for snap in snaps:          # oldest first: last write wins
        for key, st in (snap.get("jobs") or {}).items():
            s = st.get("summary") or {}
            jobs[key] = {
                "url": st.get("url", key),
                "state": st.get("state"),
                "job_id": s.get("job_id"),
                "slave_num": s.get("slave_num"),
                "roster_gen": s.get("roster_gen"),
                "last_wall": snap.get("wall"),
            }
    episodes: list[dict] = []
    open_eps: dict[str, dict] = {}
    for ev in events:
        host = None
        if ev.get("kind") in ("contention_on", "contention_off"):
            # host fp is the token after "host " in the message
            msg = str(ev.get("msg") or "")
            host = msg.split(":", 1)[0].removeprefix("host ").strip() \
                if msg.startswith("host ") else msg
        if ev.get("kind") == "contention_on" and host is not None:
            open_eps[host] = {"host_fp": host,
                              "onset_wall": ev.get("wall"),
                              "clear_wall": None,
                              "msg": ev.get("msg")}
            episodes.append(open_eps[host])
        elif ev.get("kind") == "contention_off" and host is not None:
            ep = open_eps.pop(host, None)
            if ep is not None:
                ep["clear_wall"] = ev.get("wall")
    return {"events": events, "jobs": jobs, "episodes": episodes,
            "snapshots": len(snaps), "torn": doc["torn"],
            "segments": doc["segments"]}
