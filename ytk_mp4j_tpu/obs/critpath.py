"""Cross-rank critical-path attribution over durable sink segments.

The skew tables (ISSUE 3/6) say *which* rank lags; this module says
*why* and *since when*. Input is the per-rank record stream the
durable sink (:mod:`ytk_mp4j_tpu.obs.sink`) wrote — collective and
phase spans with WALL timestamps, plus audit/recovery records — and
the output is, for every collective ordinal the job ran:

- a reconstructed **cross-rank timeline** (per-rank start/end, phase
  busy decomposition: wire / reduce / serialize / other-wait);
- the **critical-path dominator**: the (rank, cause) that gated the
  ordinal's completion, where cause is either ``late-arrival`` (the
  rank entered the collective far behind the others — upstream
  compute skew), a dominant local phase (``wire``/``reduce``/
  ``serialize``), or a **peer link** (``link->K over tcp|shm``) when
  the blame votes of the OTHER ranks' wire waits point at one rank;
- aggregation into a **dominator table** (per rank: ordinals gated,
  share, cumulative gated seconds, dominant cause) and **straggler
  onset** detection: sliding windows over the ordinal axis flag the
  first window where one rank's dominance share crosses the
  threshold, with the onset ordinal and wall timestamp — "rank 3
  started gating everything at 14:02:31", not just "rank 3 is slow".

Dominator rule (per ordinal, given per-rank collective spans and
phase spans):

1. every rank *votes*: its wire seconds per peer are blame on that
   peer (time spent on the link INCLUDES waiting for the peer's
   bytes), and its own reduce/serialize busy is self-blame;
2. a rank's **score** is its own busy plus the blame it received
   from everyone else's wire votes — an injected-slow rank wins both
   terms (its own slowed I/O books wire on every link it touches, and
   every peer's wait books blame on it);
3. unless the **late-arrival** signal dominates first: when the
   latest-entering rank's start lags the median start by more than
   half the median duration (and by an absolute floor), upstream
   skew, not in-collective behavior, gated the ordinal.

Everything here is a pure function of the loaded records —
``mp4j-scope analyze`` renders the report offline, ``mp4j-scope
tail`` follows a live directory. Imports nothing from ``comm``.
"""

from __future__ import annotations

import time

_PHASES = ("wire", "reduce", "serialize")
# late-arrival detection: start skew must exceed BOTH a fraction of
# the median span duration and an absolute floor (scheduler jitter on
# microsecond collectives must not read as a straggler)
_LATE_FRAC = 0.5
_LATE_FLOOR = 1e-4
# straggler-onset windows over the ordinal axis
ONSET_WINDOW = 32
ONSET_SHARE = 0.5


def collect(job: dict[int, dict]) -> dict:
    """Fold ``sink.load_job`` output into per-ordinal per-rank state:
    ``{"ordinals": {seq: {rank: {family, t0, dur, phases: {phase:
    secs}, links: {peer: {"secs", "transport"}}}}}, "ranks": [...],
    "audit": [...], "recovery": {rank: [...]}, "alerts": [...],
    "torn": {rank: n}, "meta": {rank: {...}}}``."""
    ordinals: dict[int, dict[int, dict]] = {}
    audit_recs: list[dict] = []
    recovery: dict[int, list] = {}
    alerts: list[dict] = []
    seen_alerts: set = set()
    torn: dict[int, int] = {}
    meta: dict[int, dict] = {}

    def cell(rank: int, seq: int) -> dict:
        return ordinals.setdefault(seq, {}).setdefault(rank, {
            "family": None, "t0": None, "dur": 0.0,
            "phases": dict.fromkeys(_PHASES, 0.0), "links": {}})

    for rank, doc in job.items():
        torn[rank] = int(doc.get("torn", 0))
        for rec in doc.get("records", ()):
            kind = rec.get("t")
            if kind == "meta" and rank not in meta:
                meta[rank] = rec
            elif kind == "spans":
                for s in rec.get("spans", ()):
                    _fold_span(cell, rank, s)
            elif kind == "audit":
                for a in rec.get("records", ()):
                    a = dict(a)
                    a["rank"] = rank
                    audit_recs.append(a)
            elif kind == "recovery":
                recovery.setdefault(rank, []).extend(
                    rec.get("events", ()))
            elif kind == "alerts":
                # health-plane verdict events (ISSUE 12): dedup by the
                # master's monotone alert id — an alert orphaned onto
                # a fallback rank must not double in the timeline
                for ev in rec.get("alerts", ()):
                    key = ev.get("id")
                    if key is not None and key in seen_alerts:
                        continue
                    seen_alerts.add(key)
                    alerts.append(ev)
    alerts.sort(key=lambda e: (e.get("wall") or 0, e.get("id") or 0))
    return {"ordinals": ordinals, "ranks": sorted(job),
            "audit": audit_recs, "recovery": recovery,
            "alerts": alerts, "torn": torn, "meta": meta}


def _fold_span(cell, rank: int, s: list) -> None:
    try:
        name, cat, t0, dur, pid, _tid, args = s
    except (TypeError, ValueError):
        return
    args = args or {}
    if cat == "collective":
        seq = int(args.get("seq") or 0)
        if not seq:
            return
        c = cell(rank, seq)
        c["family"] = name
        c["t0"] = float(t0)
        c["dur"] = float(dur)
    elif cat == "phase" and name in _PHASES:
        seq = int(args.get("seq") or 0)
        if not seq:
            return
        c = cell(rank, seq)
        c["phases"][name] += float(dur)
        if name == "wire":
            peer = args.get("peer")
            if peer is not None:
                link = c["links"].setdefault(int(peer), {
                    "secs": 0.0, "transport": None, "bytes": 0})
                link["secs"] += float(dur)
                if args.get("transport"):
                    link["transport"] = args["transport"]
                link["bytes"] += int(args.get("bytes_sent") or 0) \
                    + int(args.get("bytes_recv") or 0)


def attribute(ordinals: dict[int, dict[int, dict]]) -> list[dict]:
    """Per-ordinal critical-path attribution (module docstring rule);
    only ordinals at least two ranks reported with collective spans
    are attributable. Returns rows sorted by ordinal::

        {"seq", "family", "start", "end", "dur", "dominator",
         "cause", "transport", "score", "margin",
         "waits": {rank: {"wire","reduce","serialize","other"}}}
    """
    rows: list[dict] = []
    for seq in sorted(ordinals):
        cells = {r: c for r, c in ordinals[seq].items()
                 if c["t0"] is not None}
        if len(cells) < 2:
            continue
        starts = {r: c["t0"] for r, c in cells.items()}
        ends = {r: c["t0"] + c["dur"] for r, c in cells.items()}
        durs = sorted(c["dur"] for c in cells.values())
        med_dur = durs[len(durs) // 2]
        # LOWER median start: the upper median would zero the skew
        # whenever half the ranks (or the peer, at n=2) are late
        # together — a 2-rank job's 10 s straggler must still read as
        # late-arrival, not as wire blame on its waiting peer
        med_start = sorted(starts.values())[(len(starts) - 1) // 2]
        late_rank = max(starts, key=lambda r: (starts[r], -r))
        late_by = starts[late_rank] - med_start
        fam = next((c["family"] for c in cells.values()
                    if c["family"]), "?")

        waits = {}
        for r, c in cells.items():
            busy = sum(c["phases"].values())
            waits[r] = {**{p: c["phases"][p] for p in _PHASES},
                        "other": max(0.0, c["dur"] - busy)}

        if late_by > max(_LATE_FRAC * med_dur, _LATE_FLOOR):
            dom, cause, transport = late_rank, "late-arrival", None
            score = late_by
        else:
            # blame votes: time rank r spent on its link with peer p
            # is blame on p (the link books waiting for p's bytes);
            # own reduce/serialize busy is self-blame
            blame = dict.fromkeys(cells, 0.0)
            via: dict[int, dict] = {r: {} for r in cells}
            for r, c in cells.items():
                blame[r] += (c["phases"]["reduce"]
                             + c["phases"]["serialize"])
                for peer, link in c["links"].items():
                    if peer in blame and peer != r:
                        blame[peer] += link["secs"]
                        via[peer][r] = link
            # a rank's own wire busy also scores on itself (an
            # injected-slow rank's sleeps book there)
            score_of = {r: blame[r] + cells[r]["phases"]["wire"]
                        for r in cells}
            dom = max(score_of, key=lambda r: (score_of[r], -r))
            score = score_of[dom]
            received = blame[dom] - (cells[dom]["phases"]["reduce"]
                                     + cells[dom]["phases"]["serialize"])
            own = waits[dom]
            own_max = max(_PHASES, key=lambda p: own[p])
            if received > 0 and received >= own[own_max] * 0.5:
                voters = via[dom]
                transport = next(
                    (lk["transport"] for lk in voters.values()
                     if lk.get("transport")), None)
                cause = f"link->{dom}"
                if transport:
                    cause += f" over {transport}"
            else:
                cause, transport = own_max, None
        others = [e for r, e in ends.items() if r != dom]
        rows.append({
            "seq": seq, "family": fam,
            "start": min(starts.values()), "end": max(ends.values()),
            "dur": max(ends.values()) - min(starts.values()),
            "dominator": dom, "cause": cause, "transport": transport,
            "score": score,
            "margin": max(0.0, ends[dom] - max(others))
            if others else 0.0,
            "waits": waits,
        })
    return rows


def dominator_table(rows: list[dict]) -> dict[int, dict]:
    """Aggregate attribution rows per rank: ordinals gated, share of
    all attributed ordinals, cumulative gated seconds (sum of the
    rank's dominated ordinal durations), and the most common cause."""
    out: dict[int, dict] = {}
    n = len(rows)
    for row in rows:
        e = out.setdefault(row["dominator"], {
            "ordinals": 0, "share": 0.0, "gated_secs": 0.0,
            "causes": {}})
        e["ordinals"] += 1
        e["gated_secs"] += row["dur"]
        e["causes"][row["cause"]] = e["causes"].get(row["cause"], 0) + 1
    for e in out.values():
        e["share"] = e["ordinals"] / n if n else 0.0
        e["top_cause"] = max(e["causes"], key=e["causes"].get)
    return out


def onset_trend(rows: list[dict], window: int = ONSET_WINDOW,
                share: float = ONSET_SHARE) -> list[dict]:
    """Straggler-onset detection: slide a ``window``-ordinal window
    over the attribution rows; whenever a rank FIRST reaches a
    dominance share >= ``share`` inside a window, emit an onset event
    with the window's first ordinal and its wall timestamp. A rank
    that later drops below half the threshold and crosses again emits
    a fresh onset (intermittent stragglers show every episode)."""
    events: list[dict] = []
    active: dict[int, bool] = {}
    step = max(1, window // 2)
    starts = list(range(0, max(len(rows) - window + 1, 1), step))
    # always scan a final window ending at the last row: a straggler
    # whose onset falls in the job's trailing < window ordinals (the
    # degradation right before a crash — exactly the signal this
    # exists for) must not fall between window starts
    tail_start = max(0, len(rows) - window)
    if rows and starts[-1] != tail_start:
        starts.append(tail_start)
    for i in starts:
        win = rows[i:i + window]
        if not win:
            break
        counts: dict[int, int] = {}
        for row in win:
            counts[row["dominator"]] = counts.get(row["dominator"],
                                                  0) + 1
        for rank, c in counts.items():
            frac = c / len(win)
            if frac >= share and not active.get(rank):
                active[rank] = True
                first = win[0]
                events.append({
                    "rank": rank, "share": frac,
                    "onset_seq": first["seq"],
                    "onset_wall": first["start"],
                    "cause": max((r["cause"] for r in win
                                  if r["dominator"] == rank),
                                 key=[r["cause"] for r in win
                                      if r["dominator"] == rank].count),
                })
        for rank in list(active):
            if counts.get(rank, 0) / len(win) < share / 2:
                active[rank] = False
    return events


def analyze(job: dict[int, dict]) -> dict:
    """The full structured analysis of one sink directory's load:
    timeline rows, dominator table, onset events, per-rank phase
    totals, audit/recovery/torn summaries."""
    state = collect(job)
    rows = attribute(state["ordinals"])
    table = dominator_table(rows)
    phase_totals: dict[int, dict] = {}
    for row in rows:
        for r, w in row["waits"].items():
            acc = phase_totals.setdefault(r, dict.fromkeys(
                (*_PHASES, "other"), 0.0))
            for k, v in w.items():
                acc[k] += v
    divergences = [a for a in state["audit"] if a.get("err")]
    # the self-tuning data plane's durable decision history (ISSUE 15):
    # every applied/decided/tripped event the slaves noted into their
    # recovery logs, pulled out for `mp4j-scope tuner` — next to the
    # fenced leader updates and trip alerts that ride the alert pipe
    tuner_events: list[dict] = []
    for rank, events in state["recovery"].items():
        for ev in events:
            try:
                ts, kind, detail = ev
            except (TypeError, ValueError):
                continue
            if kind == "tuner":
                tuner_events.append({"rank": rank, "ts": ts,
                                     "msg": detail})
    tuner_events.sort(key=lambda e: (e["ts"], e["rank"]))
    return {
        "ranks": state["ranks"],
        "ordinals_attributed": len(rows),
        "rows": rows,
        "dominators": table,
        "onsets": onset_trend(rows),
        "phase_totals": phase_totals,
        "torn": state["torn"],
        "recovery": state["recovery"],
        "health_alerts": state["alerts"],
        "tuner_events": tuner_events,
        "audit_records": len(state["audit"]),
        "audit_errors": divergences,
        "meta": state["meta"],
    }


def fmt_wall(ts) -> str:
    """THE wall-timestamp formatter every obs report shares (analyze
    rows, health timelines, postmortem sections) — one place for the
    format, not three drifting copies."""
    if not isinstance(ts, (int, float)):
        return "?"
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(ts)) \
        + f".{int(ts % 1 * 1000):03d}"


_fmt_wall = fmt_wall


def format_report(analysis: dict, root: str = "",
                  last_rows: int = 8) -> str:
    """The ``mp4j-scope analyze`` report: header, dominator table,
    per-phase wait decomposition, onset trend, recovery/torn notes,
    and the tail of the per-ordinal timeline."""
    a = analysis
    lines = [f"critical-path report{': ' + root if root else ''} — "
             f"{len(a['ranks'])} rank(s), "
             f"{a['ordinals_attributed']} attributed collective(s)"]
    torn = {r: n for r, n in a["torn"].items() if n}
    if torn:
        lines.append("torn tails: " + ", ".join(
            f"rank {r}: {n}" for r, n in sorted(torn.items()))
            + " (segment cut mid-record — all prior records recovered)")
    if not a["rows"]:
        lines.append("(no attributable collectives — need collective "
                     "spans from >= 2 ranks; is the sink enabled and "
                     "MP4J_SPAN_RING > 0?)")
        lines.extend(_health_lines(a))
        return "\n".join(lines)

    lines.append("")
    lines.append("critical-path dominators:")
    lines.append(f"  {'rank':>4}  {'ordinals':>8}  {'share':>6}  "
                 f"{'gated s':>8}  top cause")
    for r in sorted(a["dominators"],
                    key=lambda r: -a["dominators"][r]["ordinals"]):
        e = a["dominators"][r]
        lines.append(f"  {r:>4}  {e['ordinals']:>8}  "
                     f"{e['share'] * 100:>5.1f}%  "
                     f"{e['gated_secs']:>8.3f}  {e['top_cause']}")

    lines.append("")
    lines.append("per-phase wait decomposition (busy seconds, "
                 "attributed ordinals):")
    lines.append(f"  {'rank':>4}  {'wire':>8}  {'reduce':>8}  "
                 f"{'serialize':>9}  {'other/wait':>10}")
    for r in sorted(a["phase_totals"]):
        p = a["phase_totals"][r]
        lines.append(f"  {r:>4}  {p['wire']:>8.3f}  "
                     f"{p['reduce']:>8.3f}  {p['serialize']:>9.3f}  "
                     f"{p['other']:>10.3f}")

    if a["onsets"]:
        lines.append("")
        lines.append("straggler onset:")
        for ev in a["onsets"]:
            lines.append(
                f"  rank {ev['rank']} began dominating the critical "
                f"path at collective #{ev['onset_seq']} "
                f"({_fmt_wall(ev['onset_wall'])}), "
                f"{ev['share'] * 100:.0f}% of the window, "
                f"cause {ev['cause']}")
    lines.extend(_health_lines(a))
    for rank, events in sorted(a["recovery"].items()):
        if events:
            tail = "; ".join(f"{kind}({detail})" if detail else kind
                             for _, kind, detail in events[-4:])
            lines.append(f"rank {rank} recovery events (last "
                         f"{min(len(events), 4)}): {tail}")
    if a["audit_errors"]:
        lines.append(f"audit: {len(a['audit_errors'])} errored "
                     "collective record(s) in the stream")

    lines.append("")
    lines.append(f"last {min(last_rows, len(a['rows']))} collectives:")
    for row in a["rows"][-last_rows:]:
        cause = row["cause"]
        lines.append(
            f"  #{row['seq']:<5} {row['family']:<22} "
            f"{row['dur'] * 1e3:>8.2f} ms  gated by rank "
            f"{row['dominator']} ({cause})")
    return "\n".join(lines)


def _health_lines(a: dict) -> list[str]:
    """The health plane's durable verdict history (ISSUE 12): what
    degraded first, when, and which detector saw it. Local import —
    :mod:`health` imports this module for the online attribution."""
    if not a.get("health_alerts"):
        return []
    from ytk_mp4j_tpu.obs import health as health_mod
    return ["", *health_mod.format_history(
        a["health_alerts"], a["ranks"]).splitlines()]


def format_row(row: dict) -> str:
    """One timeline line (the ``mp4j-scope tail`` increment)."""
    return (f"#{row['seq']:<5} {row['family']:<22} "
            f"{_fmt_wall(row['start'])}  {row['dur'] * 1e3:>8.2f} ms  "
            f"gated by rank {row['dominator']} ({row['cause']})")
