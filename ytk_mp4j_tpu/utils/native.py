"""ctypes loader/builder for the native C++ hot loops.

Compiles ``csrc/mp4j_native.cpp`` with g++ on first use (cached by source
mtime) and exposes

- :func:`reduce_into` — ``acc = op(acc, src)`` element-wise, the socket
  path's merge hot loop,
- :func:`sendrecv_raw` — the poll()-driven full-duplex raw socket
  exchange (csrc/mp4j_transport.cpp), the native data plane under
  ProcessCommSlave's numeric collectives (one-directional steps pass
  None for the inactive side).

Falls back to numpy/pure-Python transparently if the toolchain is
unavailable; the active backend is reported by :data:`HAVE_NATIVE`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError

_CSRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc")
_SRC = os.path.join(_CSRC, "mp4j_native.cpp")
_SRCS = [_SRC, os.path.join(_CSRC, "mp4j_transport.cpp"),
         os.path.join(_CSRC, "mp4j_parse.cpp")]
_BUILD_DIR = os.path.join(_CSRC, "build")
_SO = os.path.join(_BUILD_DIR, "libmp4j_native.so")

# Must match csrc/mp4j_native.cpp DType.
_DTYPE_CODES = {
    np.dtype(np.float64): 0,
    np.dtype(np.float32): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.int16): 4,
    np.dtype(np.int8): 5,
}

_lock = threading.Lock()
_lib = None
# Tri-state: None = not attempted, True = loaded, False = unavailable
# (negative result is cached so the hot loop never retries the build).
HAVE_NATIVE: bool | None = None


def _build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    newest_src = max(os.path.getmtime(s) for s in _SRCS)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= newest_src:
        return _SO
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-march=native",
        *_SRCS, "-o", _SO + ".tmp",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(_SO + ".tmp", _SO)
    return _SO


def _load():
    global _lib, HAVE_NATIVE
    if HAVE_NATIVE is not None:  # lock-free fast path for the hot loop
        return _lib
    with _lock:
        if HAVE_NATIVE is not None:
            return _lib
        try:
            lib = ctypes.CDLL(_build())
            # probe the NEWEST symbol: a stale cached .so (an
            # mtime-preserving sync of newer sources over an old build
            # tree) would lack it, and missing symbols must mean
            # "native unavailable", never an AttributeError crash in
            # every consumer
            lib.mp4j_progress_multi
        except (OSError, subprocess.CalledProcessError,
                AttributeError):
            HAVE_NATIVE = False
            return None
        lib.mp4j_reduce.restype = ctypes.c_int
        lib.mp4j_reduce.argtypes = [
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.mp4j_sendrecv_raw.restype = ctypes.c_int
        lib.mp4j_sendrecv_raw.argtypes = [
            ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.mp4j_progress_multi.restype = ctypes.c_int
        lib.mp4j_progress_multi.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_int64,
        ]
        lib.mp4j_run_legs.restype = ctypes.c_int
        lib.mp4j_run_legs.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int64,
        ]
        lib.mp4j_parse_libsvm.restype = ctypes.c_int64
        lib.mp4j_parse_libsvm.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
        HAVE_NATIVE = True
        return _lib


def reduce_into(operator, acc: np.ndarray, src: np.ndarray) -> None:
    """In-place ``acc[i] = operator(acc[i], src[i])``.

    Uses the C++ kernel for builtin operators on contiguous same-dtype
    buffers; numpy otherwise (user-defined operators always go through
    their ``np_fn``).
    """
    if acc.shape != src.shape:
        raise Mp4jError(f"shape mismatch {acc.shape} vs {src.shape}")
    lib = _load()
    if (
        lib is not None
        and operator.native_code is not None
        and acc.dtype == src.dtype
        and acc.dtype in _DTYPE_CODES
        and acc.flags.c_contiguous
        and src.flags.c_contiguous
        and acc.flags.writeable
    ):
        rc = lib.mp4j_reduce(
            _DTYPE_CODES[acc.dtype],
            operator.native_code,
            acc.ctypes.data_as(ctypes.c_void_p),
            src.ctypes.data_as(ctypes.c_void_p),
            acc.size,
        )
        if rc == 0:
            return
    np.copyto(acc, operator.np_fn(acc, src))


_RAW_ERRORS = {
    -1: "socket error during raw exchange",
    -2: "peer closed connection mid-message",
    -3: "raw exchange timed out (peer dead or stalled?)",
}


def _data_ptr(arr: np.ndarray | None):
    if arr is None or arr.size == 0:
        return None
    return ctypes.c_void_p(arr.ctypes.data)


def _nbytes(arr: np.ndarray | None) -> int:
    return 0 if arr is None else arr.nbytes


def sendrecv_raw(send_fd: int, recv_fd: int, sarr: np.ndarray | None,
                 rarr: np.ndarray | None, timeout: float | None) -> bool:
    """Full-duplex raw exchange via the native poll loop.

    ``sarr`` must be C-contiguous (or None); ``rarr`` must be a writable
    C-contiguous buffer (or None). Returns False when the native library
    is unavailable (caller falls back to the Python raw path); raises
    Mp4jError on wire failure. ``timeout=None`` blocks forever — the
    reference's fail-stop behavior.
    """
    lib = _load()
    if lib is None:
        return False
    # Round sub-millisecond (but positive) timeouts up to 1 ms so they
    # keep their "tiny grace period" meaning instead of degenerating to
    # an instant -3 failure; the framed path's socket timeout behaves
    # the same way for an instantly-ready peer.
    if timeout is None:
        timeout_ms = -1
    elif timeout <= 0:
        timeout_ms = 0
    else:
        timeout_ms = max(1, int(timeout * 1000))
    rc = lib.mp4j_sendrecv_raw(send_fd, recv_fd, _data_ptr(sarr),
                               _nbytes(sarr), _data_ptr(rarr),
                               _nbytes(rarr), timeout_ms)
    if rc != 0:
        raise Mp4jError(_RAW_ERRORS.get(rc, f"raw exchange failed ({rc})"))
    return True


def ensure_loaded() -> bool:
    """Force the one-time load/build attempt NOW, on the caller's
    thread, outside any lock the caller should be holding. The lazy
    ``_load()`` path may shell out to g++ (seconds) the first time —
    long-lived components that later consult the cached verdict from
    under their own locks (the progression scheduler's ``_full_ok``
    runs under its condition variable; mp4j-lint R20) call this at
    construction so the build can never run inside a held region."""
    return _load() is not None


def have_progress_multi() -> bool:
    """Whether the native multi-leg progress driver is available (the
    nonblocking scheduler falls back to its pure-Python pumps when
    not)."""
    return _load() is not None


def progress_multi(fds: np.ndarray, dirs: np.ndarray, bufs,
                   lens: np.ndarray, dones: np.ndarray,
                   status: np.ndarray, timeout: float) -> int:
    """Drive a set of runnable legs through ONE native poll loop
    (ISSUE 11; see ``csrc/mp4j_transport.cpp``).

    ``fds``/``dirs`` int32 arrays (dir 0=send, 1=recv), ``bufs`` a
    ``(ctypes.c_void_p * n)`` array of buffer pointers, ``lens`` int64,
    ``dones`` int64 IN-OUT progress, ``status`` int8 OUT. Sockets must
    already be nonblocking (the scheduler owns the mode for the
    batch). Returns the number of legs that newly completed, or 0 on a
    timeout tick (the caller polls the epoch fence and re-enters);
    raises on wire failure, naming the failing leg index."""
    lib = _load()
    n = int(fds.size)
    rc = lib.mp4j_progress_multi(
        ctypes.c_void_p(fds.ctypes.data),
        ctypes.c_void_p(dirs.ctypes.data),
        ctypes.cast(bufs, ctypes.c_void_p),
        ctypes.c_void_p(lens.ctypes.data),
        ctypes.c_void_p(dones.ctypes.data),
        ctypes.c_void_p(status.ctypes.data),
        n, max(1, int(timeout * 1000)))
    if rc < 0:
        bad = int(np.flatnonzero(status != 0)[0]) \
            if np.any(status != 0) else -1
        raise Mp4jError(
            f"{_RAW_ERRORS.get(rc, f'progress failed ({rc})')} "
            f"(leg {bad})")
    return rc


def run_legs(fds, dirs, bufs, lens, dones, gates, mdst, msrc, mdtype,
             mopcode, mcount, mchunk, melems, status, wake_fd: int,
             timeout: float) -> int:
    """Drive a whole engine batch's leg graph natively (ISSUE 11; see
    ``csrc/mp4j_transport.cpp mp4j_run_legs``). Reduce-merges run
    chunk-granularly as bytes land: ``mchunk`` is the per-leg merge
    step in elements (the tuner-adapted chunk schedule; 0 = whole
    buffer), ``melems`` the in-out merge cursor. Returns 1 (all legs
    complete), 0 (timeout tick — poll the fence and re-enter) or 2
    (``wake_fd`` readable — new submissions to admit); raises on wire
    failure. ``dones``/``melems`` are in-out, so the call is
    re-entrant."""
    lib = _load()
    rc = lib.mp4j_run_legs(
        ctypes.c_void_p(fds.ctypes.data),
        ctypes.c_void_p(dirs.ctypes.data),
        ctypes.cast(bufs, ctypes.c_void_p),
        ctypes.c_void_p(lens.ctypes.data),
        ctypes.c_void_p(dones.ctypes.data),
        ctypes.c_void_p(gates.ctypes.data),
        ctypes.cast(mdst, ctypes.c_void_p),
        ctypes.cast(msrc, ctypes.c_void_p),
        ctypes.c_void_p(mdtype.ctypes.data),
        ctypes.c_void_p(mopcode.ctypes.data),
        ctypes.c_void_p(mcount.ctypes.data),
        ctypes.c_void_p(mchunk.ctypes.data),
        ctypes.c_void_p(melems.ctypes.data),
        ctypes.c_void_p(status.ctypes.data),
        int(fds.size), wake_fd, max(1, int(timeout * 1000)))
    if rc < 0:
        bad = int(np.flatnonzero(status != 0)[0]) \
            if np.any(status != 0) else -1
        raise Mp4jError(
            f"{_RAW_ERRORS.get(rc, f'batch progress failed ({rc})')} "
            f"(leg {bad})")
    return rc


def reduce_opcode(operator, dtype) -> int | None:
    """The (dtype, operator) native codes for a batch merge spec, or
    None when this combination has no native kernel (the engine then
    keeps the per-leg path whose merges run through reduce_into's
    fallback).

    Reads the CACHED load verdict only — never triggers the build.
    The callers sit under the progression scheduler's condition
    variable, and the first ``_load()`` may compile the extension
    (``subprocess.run`` of g++, seconds): a build under that lock
    stalls every submit()/wait() on the scheduler for its duration
    (mp4j-lint R20, found by the whole-program pass). The scheduler
    forces the one-time attempt via :func:`ensure_loaded` at
    construction, so an unattempted verdict here means "no native
    kernels", exactly like a missing toolchain."""
    if not HAVE_NATIVE or _lib is None or operator.native_code is None:
        return None
    dt = np.dtype(dtype)
    if dt not in _DTYPE_CODES:
        return None
    return _DTYPE_CODES[dt], operator.native_code


def parse_libsvm_chunk(blob: bytes, n_rows: int, max_nnz: int):
    """Native one-pass chunk parse (csrc/mp4j_parse.cpp): a chunk of
    newline-joined libsvm/libffm lines -> padded
    ``(feats, fields, vals, y)`` arrays, exactly the shape
    ``utils.libsvm.read_libsvm`` yields.

    Returns None when the native library is unavailable OR the strict
    parser refused the chunk (exotic-but-valid literals, or genuinely
    malformed lines) — the caller replays through the Python parser,
    which either accepts slowly or raises the exact diagnostic.
    """
    lib = _load()
    if lib is None:
        return None
    feats = np.zeros((n_rows, max_nnz), np.int32)
    fields = np.zeros((n_rows, max_nnz), np.int32)
    vals = np.zeros((n_rows, max_nnz), np.float32)
    y = np.zeros(n_rows, np.float32)
    out_rows = ctypes.c_int64(0)
    rc = lib.mp4j_parse_libsvm(
        blob, len(blob), max_nnz, n_rows,
        feats.ctypes.data_as(ctypes.c_void_p),
        fields.ctypes.data_as(ctypes.c_void_p),
        vals.ctypes.data_as(ctypes.c_void_p),
        y.ctypes.data_as(ctypes.c_void_p),
        ctypes.byref(out_rows))
    if rc != 0 or out_rows.value != n_rows:
        return None
    return feats, fields, vals, y


# NOTE: a native sorted-u64 key-union kernel (merge_unique_u64) plus a
# vectorized packed map merge were prototyped here for the socket map
# path and MEASURED SLOWER than the per-key dict loop (0.85-0.95x at
# 20k-200k int keys: the dict->array->dict conversions cost more than
# the loop saves; Python dict ops are already C-level). Removed rather
# than kept as dead capability — the map-merge hot loop is the plain
# loop in ProcessCommSlave._merge_maps by measurement, not by neglect.
