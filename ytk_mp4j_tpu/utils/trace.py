"""Lightweight per-collective tracing.

The reference has no built-in profiling (SURVEY.md section 5: "at most
log-line timing in check programs"); this subsystem is the cheap win
named there. Zero overhead when disabled (one module-global check per
collective call); when enabled inside :class:`trace_collectives`, every
backend collective (socket, thread, device) records a
``(name, seconds, nbytes)`` event, and :func:`summary` aggregates
count / time / bytes / effective GB/s per collective.

Optionally forwards to the JAX profiler: pass ``profile_dir`` to wrap
the traced region in ``jax.profiler.start_trace`` so device-path
collectives appear on the XLA timeline (TensorBoard-loadable).

Usage::

    from ytk_mp4j_tpu.utils import trace

    with trace.trace_collectives():
        cluster.allreduce_array(arrs, Operands.FLOAT, Operators.SUM)
    print(trace.summary())
"""

from __future__ import annotations

import functools
import math
import threading
import time
from typing import Any

import numpy as np

from ytk_mp4j_tpu.obs import spans as _spans

_lock = threading.Lock()
_enabled = False
_events: list[tuple[str, float, int]] = []


def _payload_bytes(x: Any, _seen: set[int] | None = None) -> int:
    """Best-effort payload size of a collective operand.

    Containers (dicts/lists of arrays) count each distinct underlying
    buffer ONCE: two views sharing a base — e.g. the halves of one
    scratch array deposited under two dict keys — must not double-count
    (dedup by ``id(arr.base)``, no O(n^2) ``np.shares_memory`` sweep).
    Non-numeric scalars (``None``, arbitrary objects, non-numeric numpy
    scalars) count 0, not a phantom 8.
    """
    if isinstance(x, np.ndarray):
        if _seen is not None:
            base = x.base if isinstance(x.base, np.ndarray) else x
            if id(base) in _seen:
                return 0
            _seen.add(id(base))
        return x.nbytes
    if isinstance(x, np.generic):
        return x.nbytes if np.issubdtype(x.dtype, np.number) else 0
    if isinstance(x, dict):
        seen = set() if _seen is None else _seen
        return sum(_payload_bytes(v, seen) for v in x.values())
    if isinstance(x, (list, tuple)):
        seen = set() if _seen is None else _seen
        return sum(_payload_bytes(v, seen) for v in x)
    if isinstance(x, (bytes, str)):
        return len(x)
    if isinstance(x, (int, float, complex)):
        return 8
    if hasattr(x, "nbytes"):  # jax arrays
        try:
            return int(x.nbytes)
        except Exception:
            return 0
    return 0


def record(name: str, seconds: float, nbytes: int) -> None:
    if _enabled:
        with _lock:
            _events.append((name, seconds, nbytes))


# Canonical collective-method list shared by every backend; instrument()
# skips names a backend doesn't define (e.g. the in-jit functional layer
# has no maps), so one list serves all without drift.
COLLECTIVE_METHODS = (
    "allreduce_array", "reduce_array", "broadcast_array",
    "allgather_array", "gather_array", "scatter_array",
    "reduce_scatter_array", "allreduce_map", "allreduce_map_async",
    "allreduce_map_multi", "allreduce_array_multi",
    "reduce_map", "broadcast_map", "gather_map", "allgather_map",
    "scatter_map", "reduce_scatter_map", "barrier", "thread_barrier",
)
# NOTE: the _async row times the DISPATCH half only (encode + device
# launch + d2h start); the blocking fetch/decode lives in the
# handle's result() and is deliberately not a collective row.


def instrument(cls, methods=COLLECTIVE_METHODS):
    """Wrap each of ``cls``'s collective methods with :func:`traced`
    (names the class doesn't define are skipped)."""
    for name in methods:
        fn = cls.__dict__.get(name)
        if fn is not None and callable(fn):
            setattr(cls, name, traced(fn))
    return cls


_in_collective = threading.local()


def traced(fn):
    """Wrap a collective method: when tracing is enabled, time the call
    and record the payload size of its first data argument. Only the
    OUTERMOST traced call on a thread records — collectives implemented
    by composing other collectives (e.g. allreduce_map = reduce_map +
    broadcast_map) must not double-count or emit phantom rows.

    Independently of the trace on/off switch, the wrapper scopes the
    backend's always-on :class:`~ytk_mp4j_tpu.utils.stats.CommStats`
    (when the instance carries one as ``_comm_stats``) so wire/reduce/
    serialize phase events recorded deeper in the stack attribute to
    the collective that caused them; each OUTERMOST scope also lands as
    a span in the bounded ring (obs.spans, Chrome-trace exportable) and,
    on failure, fires the backend's ``_on_collective_error`` hook (the
    slave ships a DIAGNOSE to the master so a timed-out collective
    yields a cluster-wide hang diagnosis instead of a bare error)."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        stats = getattr(self, "_comm_stats", None)
        outermost = (stats.begin(fn.__name__)
                     if stats is not None else 0)
        trace_this = _enabled and getattr(_in_collective, "depth", 0) == 0
        if not trace_this and not outermost:
            try:
                return fn(self, *args, **kwargs)
            finally:
                if stats is not None:
                    stats.end(outermost)
        nbytes = _payload_bytes(args[0]) if (trace_this and args) else 0
        if trace_this:
            _in_collective.depth = 1
        t0 = time.perf_counter()
        try:
            out = fn(self, *args, **kwargs)
        except Exception as e:
            # hook BEFORE stats.end so the diagnosis payload still sees
            # the failed collective as `current` (best-effort, only at
            # the outermost frame — composed collectives report once)
            if outermost:
                hook = getattr(self, "_on_collective_error", None)
                if hook is not None:
                    hook(fn.__name__, e)
            raise
        finally:
            if trace_this:
                _in_collective.depth = 0
            dur = time.perf_counter() - t0
            if outermost:
                _spans.collective(fn.__name__, t0, dur,
                                  stats.rank, outermost)
            if stats is not None:
                stats.end(outermost)
        if trace_this:
            record(f"{type(self).__name__}.{fn.__name__}", dur, nbytes)
        return out

    return wrapper


class trace_collectives:
    """Context manager enabling collective tracing (optionally plus the
    JAX profiler when ``profile_dir`` is given). Re-entrant: nested
    scopes keep tracing enabled until the outermost exits. At most ONE
    scope in the stack may pass ``profile_dir`` (the JAX profiler cannot
    nest); a second raises before any state changes."""

    _depth = 0
    _profiler_owner: "trace_collectives | None" = None

    def __init__(self, profile_dir: str | None = None, clear: bool = True):
        self.profile_dir = profile_dir
        self.clear = clear

    def __enter__(self):
        global _enabled
        # start the profiler BEFORE flipping global state: __exit__ never
        # runs when __enter__ raises, so state must only change once
        # nothing else can fail
        if self.profile_dir is not None:
            with _lock:
                if trace_collectives._profiler_owner is not None:
                    raise RuntimeError(
                        "a trace_collectives scope with profile_dir is "
                        "already active; the JAX profiler cannot nest")
                trace_collectives._profiler_owner = self
            try:
                import jax

                jax.profiler.start_trace(self.profile_dir)
            except BaseException:
                with _lock:
                    trace_collectives._profiler_owner = None
                raise
        with _lock:
            if trace_collectives._depth == 0 and self.clear:
                _events.clear()
            trace_collectives._depth += 1
            _enabled = True
        return self

    def __exit__(self, *exc):
        global _enabled
        if trace_collectives._profiler_owner is self:
            import jax

            jax.profiler.stop_trace()
            with _lock:
                trace_collectives._profiler_owner = None
        with _lock:
            trace_collectives._depth -= 1
            if trace_collectives._depth == 0:
                _enabled = False
        return False


def events() -> list[tuple[str, float, int]]:
    """Raw ``(name, seconds, nbytes)`` events recorded so far."""
    with _lock:
        return list(_events)


def clear() -> None:
    with _lock:
        _events.clear()


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted list."""
    idx = math.ceil(q * len(sorted_vals)) - 1
    return sorted_vals[max(0, min(len(sorted_vals) - 1, idx))]


def summary() -> dict[str, dict[str, float]]:
    """Aggregate events: per collective name, ``{calls, seconds, bytes,
    gb_per_s}`` (payload bytes over wall time — an effective, not wire,
    rate) plus per-call duration percentiles ``{p50, p95, max}`` in
    seconds — one straggling call stays visible behind a healthy mean."""
    agg: dict[str, dict[str, float]] = {}
    durs: dict[str, list[float]] = {}
    for name, sec, nb in events():
        a = agg.setdefault(name, {"calls": 0, "seconds": 0.0, "bytes": 0})
        a["calls"] += 1
        a["seconds"] += sec
        a["bytes"] += nb
        durs.setdefault(name, []).append(sec)
    for name, a in agg.items():
        a["gb_per_s"] = (a["bytes"] / a["seconds"] / 1e9
                         if a["seconds"] > 0 else 0.0)
        ds = sorted(durs[name])
        a["p50"] = _percentile(ds, 0.50)
        a["p95"] = _percentile(ds, 0.95)
        a["max"] = ds[-1]
    return agg


def format_summary() -> str:
    """Human-readable table of :func:`summary` (rank-0-style report)."""
    agg = summary()
    if not agg:
        return "(no collective events traced)"
    w = max(len(k) for k in agg)
    lines = [f"{'collective':<{w}}  calls  seconds    MB      GB/s"
             f"    p50ms    p95ms    maxms"]
    for name in sorted(agg):
        a = agg[name]
        lines.append(
            f"{name:<{w}}  {a['calls']:>5d}  {a['seconds']:>7.4f}  "
            f"{a['bytes'] / 1e6:>7.2f}  {a['gb_per_s']:>7.3f}  "
            f"{a['p50'] * 1e3:>7.3f}  {a['p95'] * 1e3:>7.3f}  "
            f"{a['max'] * 1e3:>7.3f}")
    return "\n".join(lines)


def export_chrome_trace(path: str) -> int:
    """Export the span ring (collective + chunk-level wire/reduce/
    serialize phase spans, always-on — see :mod:`ytk_mp4j_tpu.obs.spans`)
    as Chrome-trace/Perfetto JSON; returns the event count. One file per
    process; merge per-rank files with ``mp4j-scope merge``."""
    return _spans.export_chrome_trace(path)
