"""mp4j-tuner — the self-tuning data plane's policy core (ISSUE 15).

The repo's observability planes *measure* everything (per-link wire
seconds with transport attribution, critical-path dominators, content
digests) but until this module the transport *decided* statically: one
job-wide ``MP4J_CHUNK_BYTES``, compression fixed by the operand, host
leaders fixed by roster order. This module closes the observe→decide
loop with PURE FUNCTIONS over rolling stats windows — no sockets, no
threads, no clocks — so the whole decision surface is unit-testable
and replayable:

- :func:`decide_link` — per-link ``(chunk_bytes, compress)`` decisions
  from the link's windowed wire GB/s and observed compression ratio,
  with hysteresis (:data:`SUSTAIN_WINDOWS` consecutive agreeing
  windows before any change) so scheduler noise can never flap a
  knob;
- :func:`decide_leaders` — the PR 9 follow-up: on a two-level
  topology, a host leader whose LINK persistently dominates the
  critical path (the health engine's online dominator rows, cause
  ``link->L over ...``) is demoted in favor of the next co-located
  rank; the master applies the override through a fenced topology
  update so every rank switches at the same collective boundary;
- :class:`LinkTuner` — the thin per-slave state holder: snapshot
  diffing, per-link hysteresis state, the pending-decision queue the
  slave drains at outermost-collective boundaries, and the audit
  trip (divergence ⇒ back to static defaults, adaptation frozen).

Safety argument (why per-link decisions cannot desync a pair):

- **compression** is receiver-auto-detected by frame tag on the
  framed plane (the only plane these decisions touch — the raw plane
  stays governed by the job-wide ``operand.compress``/``_raw_ok``
  rule), so a sender-side per-link choice is always decodable;
- **chunk size** shapes only the local exchange granularity of a
  byte-stream transport (TCP, or a frame-routed shm stream) — chunk
  boundaries never travel on the wire. Links with shm traffic are
  EXCLUDED from chunk decisions: there the raw plane's per-exchange
  ring/carrier routing makes the schedule part of the wire contract
  (mp4j-lint R8's reasoning, honored by construction);
- **application timing**: decisions queue and apply only at
  outermost-collective boundaries (the slave's recovery wrapper),
  never mid-collective — the same fence discipline the autoscaler
  uses.

Numeric thresholds for transport decisions live HERE or in
:mod:`ytk_mp4j_tpu.utils.tuning` — nowhere else (mp4j-lint R22, the
knob-drift rule this PR adds).
"""

from __future__ import annotations

import threading

# -- policy constants (the sanctioned literal home, mp4j-lint R22) ----
# evidence floors: a window must move this much before it counts
MIN_WINDOW_BYTES = 256 * 1024
MIN_COMP_BYTES = 64 * 1024
# hysteresis: consecutive agreeing windows before a decision commits
SUSTAIN_WINDOWS = 3
# compression policy (probe/measure — see decide_link): the effective
# PAYLOAD throughput of a compressed stream is zlib-bound, so it says
# nothing about the raw link speed; the policy therefore PROBES
# (compress off for a sustained verdict), measures the plain link
# rate, and keeps whichever mode moves more payload per second.
KEEP_OFF_FACTOR = 1.2      # plain must beat compressed by 20% to stay
COMPRESS_ON_GBS = 0.08     # a link this slow + a good ratio: turn on
RATIO_GOOD = 2.0
EWMA_ALPHA = 0.5           # window-rate smoothing
# chunk policy bounds and triggers: adapt toward the link's observed
# BULK transfer size (booked by the collective engine per exchange),
# one doubling/halving per sustained verdict
CHUNK_MIN = 256 * 1024
CHUNK_MAX = 8 * 1024 * 1024
CHUNK_TARGET_DIV = 4       # target chunk ~ avg transfer / 4
# leader demotion: fraction of the recent dominator window one
# leader's LINK must gate (slow rows only) before demotion
LEADER_WINDOW = 16
LEADER_SHARE = 0.75
# socket-buffer policy (ISSUE 17): on a sustained-bulk tcp link whose
# applied sndbuf/rcvbuf sit below the observed bandwidth-delay
# product, raise them toward it — one doubling per sustained verdict,
# raise-only (shrinking buffers under load thrashes the kernel), and
# capped. RTT is not measured per link; SOCKBUF_RTT_S is the assumed
# in-flight window a bulk stream must cover (1 ms spans same-DC hops;
# loopback links simply never sustain a BDP above their buffers).
SOCKBUF_RTT_S = 1e-3
SOCKBUF_BULK_BYTES = 4 * 1024 * 1024   # window floor to call it bulk
SOCKBUF_MAX = 8 * 1024 * 1024


# -- roster topology (shared with comm + master) ----------------------
def host_groups(roster) -> list[list[int]]:
    """Rank groups sharing a host fingerprint, ordered by first
    appearance; each group ascending (``group[0]`` is the DEFAULT host
    leader — the smallest rank on that host). Fingerprint-less entries
    become singleton groups. Pure function of the shared roster — the
    one topology derivation the slave (`_set_roster`) and the master's
    tuner controller both use, so they can never disagree."""
    groups: dict[str, list[int]] = {}
    singles: list[list[int]] = []
    for rank, entry in enumerate(roster):
        fp = entry[2] if len(entry) > 2 else ""
        if fp:
            groups.setdefault(fp, []).append(rank)
        else:
            singles.append([rank])
    out = list(groups.values()) + singles
    out.sort(key=lambda g: g[0])
    return out


def leaders_for(groups: list[list[int]],
                overrides: dict[int, int] | None) -> list[int]:
    """The effective per-group leader list: the default (smallest
    rank) unless a validated override names another MEMBER of that
    group. Invalid overrides (stale group index, rank not in the
    group — e.g. after a membership change) fall back to the default,
    never to an arbitrary rank."""
    leaders = []
    for i, g in enumerate(groups):
        cand = (overrides or {}).get(i)
        leaders.append(cand if cand in g else g[0])
    return leaders


# -- per-link decision policy -----------------------------------------
def initial_state() -> dict:
    """One link's hysteresis state: the committed decision fields, the
    pending-proposal ladder, and the probe bookkeeping (smoothed
    payload rates per mode)."""
    return {"compress": None, "chunk_bytes": None,
            "pend_key": None, "pend_n": 0,
            "probing": False, "comp_gbs": None, "plain_gbs": None}


# the monotone accumulator keys a window diffs; anything else in a
# link snapshot (applied so_sndbuf/so_rcvbuf, the transport tag) is a
# FACT and passes through at its current value
_COUNTER_KEYS = frozenset({
    "bytes", "secs", "frames", "bytes_tcp", "bytes_shm",
    "comp_raw", "comp_wire", "comp_frames", "xfer_bytes", "xfers"})


def link_delta(cur: dict[int, dict], prev: dict[int, dict]
               ) -> dict[int, dict]:
    """Window = ``cur - prev`` per link over the monotone accumulator
    keys (:data:`_COUNTER_KEYS`); non-counter facts — applied socket
    buffer sizes, the transport tag — pass through from ``cur`` at
    their absolute values."""
    out: dict[int, dict] = {}
    for peer, entry in cur.items():
        base = prev.get(peer, {})
        delta = {}
        for k, v in entry.items():
            if k in _COUNTER_KEYS:
                delta[k] = v - base.get(k, 0)
            else:
                delta[k] = v
        if delta.get("bytes") or delta.get("comp_raw"):
            out[peer] = delta
    return out


def _ewma(old: float | None, new: float) -> float:
    return new if old is None else old + EWMA_ALPHA * (new - old)


def _proposals(delta: dict, state: dict, default_chunk: int) -> dict:
    """The raw (un-hysteresed) verdicts one window supports:
    ``{"compress": bool}`` and/or ``{"chunk_bytes": int}`` — empty
    when the evidence is insufficient or already matches. MUTATES
    ``state``'s rate bookkeeping (the caller owns the copy).

    Compression is a PROBE/MEASURE cycle because a compressed
    stream's wire seconds hide the raw link speed (the receiver's
    read blocks on the sender's zlib): while compressing, the policy
    records the effective PAYLOAD rate (raw bytes per wire second)
    and — lacking any plain-traffic baseline — proposes a probe
    (compress off). Once plain traffic flows it keeps whichever mode
    moved more payload per second: a loopback/shm-class link beats
    the zlib bound by an order of magnitude and stays uncompressed;
    a genuinely slow link loses the comparison and reverts within
    one window."""
    out: dict = {}
    bytes_ = float(delta.get("bytes") or 0)
    secs = float(delta.get("secs") or 0.0)
    comp_raw = float(delta.get("comp_raw") or 0)
    comp_wire = float(delta.get("comp_wire") or 0)
    cur = state.get("compress")
    # effective payload rate: compressed wire bytes count at their
    # RAW size (that is what the application actually moved)
    payload = bytes_ - comp_wire + comp_raw
    if secs > 0 and payload >= MIN_WINDOW_BYTES:
        pg = payload / secs / 1e9
        if comp_raw >= MIN_COMP_BYTES:
            state["comp_gbs"] = _ewma(state.get("comp_gbs"), pg)
            if comp_wire > 0:
                # remembered ratio: the re-enable rule below needs it
                # AFTER a committed compress=False has suppressed all
                # compressed evidence
                state["ratio"] = comp_raw / comp_wire
            if state.get("plain_gbs") is None and cur is None:
                # no plain baseline and no committed decision yet:
                # propose the probe. cur=False is excluded — in
                # observe mode nothing applies, so compressed
                # evidence keeps flowing after the commit and the
                # probe would re-commit (and re-log) forever
                out["compress"] = False
            elif (state.get("plain_gbs") is not None
                  and state["plain_gbs"] < COMPRESS_ON_GBS
                  and comp_wire > 0
                  and comp_raw / comp_wire >= RATIO_GOOD
                  and cur is not True):
                out["compress"] = True
        else:
            state["plain_gbs"] = _ewma(state.get("plain_gbs"), pg)
            comp_g = state.get("comp_gbs")
            if state.get("probing") and comp_g is not None:
                if pg >= comp_g * KEEP_OFF_FACTOR:
                    # probe verdict: the plain link wins — stay off
                    # (already committed off; just end the probe)
                    state["probing"] = False
                else:
                    # probe failed: the link is genuinely slow enough
                    # that compression paid — revert NOW (one window,
                    # not SUSTAIN: a failed probe must not linger)
                    state["probing"] = False
                    out["compress"] = True
                    out["_revert"] = True
            elif (cur is False
                  and pg < COMPRESS_ON_GBS
                  and (state.get("ratio") or 0.0) >= RATIO_GOOD):
                # a committed compress=False is not a life sentence:
                # the decision itself suppresses compressed evidence,
                # so re-enable from the REMEMBERED ratio when the
                # plain link degrades into the regime where the zlib
                # trade pays (normal SUSTAIN hysteresis applies)
                out["compress"] = True
    # chunk size: adapt toward the observed BULK transfer size —
    # EXCEPT on links with shm traffic, where the raw plane's
    # per-exchange ring/carrier routing makes the chunk schedule part
    # of the wire contract (see module docstring)
    if not delta.get("bytes_shm"):
        xfers = float(delta.get("xfers") or 0)
        xbytes = float(delta.get("xfer_bytes") or 0)
        cur_chunk = state.get("chunk_bytes") or default_chunk
        if xfers > 0 and xbytes >= MIN_WINDOW_BYTES:
            target = xbytes / xfers / CHUNK_TARGET_DIV
            if target >= cur_chunk * 2 and cur_chunk * 2 <= CHUNK_MAX:
                out["chunk_bytes"] = cur_chunk * 2
            elif target <= cur_chunk // 2 \
                    and cur_chunk // 2 >= CHUNK_MIN:
                out["chunk_bytes"] = cur_chunk // 2
    # socket buffers: a sustained-bulk tcp link whose applied buffers
    # sit below the observed bandwidth-delay product cannot keep its
    # pipe full — raise toward the BDP, one doubling per sustained
    # verdict, raise-only, capped (SOCKBUF_MAX). The applied sizes are
    # FACTS in the window (note_link re-reads them after every apply),
    # so the ladder converges and never flaps.
    if (delta.get("transport") == "tcp" and not delta.get("bytes_shm")
            and secs > 0 and bytes_ >= SOCKBUF_BULK_BYTES):
        bdp = bytes_ / secs * SOCKBUF_RTT_S
        for key in ("so_sndbuf", "so_rcvbuf"):
            cur_buf = int(delta.get(key) or 0)
            if cur_buf and cur_buf < SOCKBUF_MAX \
                    and bdp >= cur_buf * 2:
                out[key] = min(SOCKBUF_MAX, cur_buf * 2)
    return out


def decide_link(delta: dict, state: dict, default_chunk: int
                ) -> tuple[dict, dict | None]:
    """Fold one window into a link's hysteresis state; returns
    ``(new_state, decision_or_None)``. A decision only emerges after
    :data:`SUSTAIN_WINDOWS` consecutive windows propose the SAME
    change (the pending ladder resets on any disagreement) — except a
    failed compression probe, which reverts in ONE window — and the
    emitted decision is the link's full committed record
    ``{"compress": ..., "chunk_bytes": ...}``, idempotent to apply."""
    state = dict(state)
    props = _proposals(delta, state, default_chunk)
    revert_now = props.pop("_revert", False)
    if not props:
        state["pend_key"], state["pend_n"] = None, 0
        return state, None
    key = tuple(sorted(props.items()))
    if key == state.get("pend_key"):
        state["pend_n"] += 1
    else:
        state["pend_key"], state["pend_n"] = key, 1
    if not revert_now and state["pend_n"] < SUSTAIN_WINDOWS:
        return state, None
    state["pend_key"], state["pend_n"] = None, 0
    state.update(props)
    if props.get("compress") is False:
        # the commit that starts (or continues) the probe phase
        state["probing"] = state.get("plain_gbs") is None
    decision = {"compress": state["compress"],
                "chunk_bytes": state["chunk_bytes"]}
    for k in ("so_sndbuf", "so_rcvbuf"):
        if state.get(k):
            decision[k] = state[k]
    return state, decision


# -- leader demotion policy (the PR 9 follow-up) ----------------------
def decide_leaders(rows: list[dict], groups: list[list[int]],
                   overrides: dict[int, int] | None,
                   window: int = LEADER_WINDOW,
                   share: float = LEADER_SHARE) -> dict[int, int] | None:
    """Consult the rolling critpath dominator rows (``{seq, dom,
    cause, slow}`` — the health engine's online attribution) and
    demote a host leader whose LINK persistently gates the critical
    path: in the last ``window`` attributed ordinals, SLOW rows whose
    cause is ``link->L ...`` with ``L`` the effective leader of a
    multi-member host group must hold at least ``share`` of the
    window. Returns the new override map (existing overrides
    preserved; the demoted group's leadership rotates to the next
    member, cyclically, so repeated demotions try every co-located
    rank) — or ``None`` when no demotion is warranted."""
    win = rows[-window:]
    if len(win) < window:
        return None
    leaders = leaders_for(groups, overrides)
    votes: dict[int, int] = {}
    for row in win:
        if not row.get("slow"):
            continue
        cause = str(row.get("cause") or "")
        if not cause.startswith("link->"):
            continue
        dom = int(row.get("dom", -1))
        # belt-and-braces: critpath constructs the cause as
        # f"link->{dominator}", so the named link target IS the
        # dominator — but the demotion predicate is "THIS rank's
        # link gates", so verify the name rather than trusting the
        # format never drifts
        target = cause[len("link->"):].split(" ", 1)[0]
        if not target.isdigit() or int(target) != dom:
            continue
        votes[dom] = votes.get(dom, 0) + 1
    for dom, n in sorted(votes.items(), key=lambda kv: -kv[1]):
        if n / len(win) < share:
            continue
        for gi, g in enumerate(groups):
            if leaders[gi] == dom and len(g) > 1:
                nxt = g[(g.index(dom) + 1) % len(g)]
                new = dict(overrides or {})
                new[gi] = nxt
                return new
    return None


# -- the per-slave state holder ---------------------------------------
class LinkTuner:
    """Per-slave tuner state around the pure policy core: snapshot
    diffing, per-link hysteresis, the pending-decision queue drained
    at outermost-collective boundaries, and the trip latch. Holds no
    sockets and no threads of its own — the slave's heartbeat thread
    calls :meth:`observe`, its collective thread calls
    :meth:`take_pending`; one lock arbitrates."""

    def __init__(self, mode: str, default_chunk: int,
                 so_buf_map: dict[int, tuple[int, int]] | None = None):
        self.mode = mode                      # "observe" | "act"
        self.default_chunk = int(default_chunk)
        self.so_buf_map = dict(so_buf_map or {})
        self.tripped: str | None = None       # why, once tripped
        self.decisions_total = 0              # committed (or would-be)
        self._lock = threading.Lock()
        self._prev: dict[int, dict] = {}
        self._states: dict[int, dict] = {}
        self._pending: dict[int, dict] = {}   # peer -> decision
        self._applied: dict[int, dict] = {}   # peer -> decision live
        self._revert = False                  # trip: clear at boundary

    # -- heartbeat side ------------------------------------------------
    def observe(self, links: dict[int, dict]) -> list[tuple[int, dict]]:
        """Fold one stats window; returns the decisions that COMMITTED
        this window (for logging/telemetry). In ``act`` mode they also
        queue for boundary application; in ``observe`` mode they are
        recorded only."""
        out: list[tuple[int, dict]] = []
        with self._lock:
            delta = link_delta(links, self._prev)
            self._prev = links
            if self.tripped is not None:
                return out
            for peer, d in delta.items():
                st = self._states.get(peer) or initial_state()
                st, decision = decide_link(d, st, self.default_chunk)
                self._states[peer] = st
                if decision is not None:
                    self.decisions_total += 1
                    out.append((peer, decision))
                    if self.mode == "act":
                        self._pending[peer] = decision
        return out

    # -- collective-boundary side --------------------------------------
    @property
    def dirty(self) -> bool:
        """Cheap hot-path check: anything to apply at this boundary?"""
        return bool(self._pending) or self._revert

    def take_pending(self) -> tuple[dict[int, dict], bool]:
        """Drain ``(decisions, revert_all)`` for boundary application;
        the applied map updates optimistically (the caller IS about to
        apply them)."""
        with self._lock:
            pending, self._pending = self._pending, {}
            revert, self._revert = self._revert, False
            if revert:
                self._applied.clear()
            self._applied.update(pending)
            return pending, revert

    def reset(self) -> None:
        """Membership change (replacement, shrink renumbering, grow):
        every per-link accumulator, hysteresis state and committed
        decision is evidence about the OLD rank numbering — a
        renumbered (or replaced) peer id must not inherit the old
        occupant's adaptation. The trip latch SURVIVES: a job whose
        data plane produced a divergence stays on static defaults
        through membership churn too."""
        with self._lock:
            self._prev = {}
            self._states.clear()
            self._pending.clear()
            self._applied.clear()
            self._revert = False

    # -- safety rails --------------------------------------------------
    def trip(self, why: str) -> None:
        """Audit divergence under adaptation: freeze the policy and
        schedule a revert to static defaults at the next boundary.
        Tripping is latched for the job's lifetime — a data plane that
        produced one cross-rank divergence has forfeited the benefit
        of the doubt."""
        with self._lock:
            if self.tripped is not None:
                return
            self.tripped = str(why)[:300]
            self._pending.clear()
            self._states.clear()
            self._revert = True

    def effective_compress(self, peer: int, requested: bool) -> bool:
        """The framed plane's per-link compression choice: the
        committed decision when one is live, else the operand's
        request. Lock-free read of an atomically swapped dict — the
        hot path pays one ``dict.get``."""
        d = self._applied.get(peer)
        if d is None or d.get("compress") is None:
            return requested
        return bool(d["compress"])

    def effective_chunk(self, peer: int, default: int) -> int:
        d = self._applied.get(peer)
        if d is None or not d.get("chunk_bytes"):
            return default
        return int(d["chunk_bytes"])

    def status(self) -> dict:
        """The telemetry document (heartbeat ``tuner`` field /
        ``mp4j-scope tuner``)."""
        with self._lock:
            return {
                "mode": self.mode,
                "tripped": self.tripped,
                "decisions_total": self.decisions_total,
                "pending": len(self._pending),
                "applied": {int(p): dict(d)
                            for p, d in self._applied.items()},
            }
