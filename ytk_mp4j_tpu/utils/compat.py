"""jax API-surface compatibility shims.

jax promoted ``shard_map`` out of ``jax.experimental`` (and renamed its
replication check ``check_rep`` -> ``check_vma``) around 0.6. This
codebase is written against the current spelling — ``jax.shard_map``
with ``check_vma=`` — at every call site; on older jax, :func:`install`
backfills that surface once so models/check/tests code stays on one
spelling instead of each module carrying its own try/except.

``install()`` runs from the package root ``__init__``, so any
``import ytk_mp4j_tpu...`` makes ``jax.shard_map`` usable.
"""

from __future__ import annotations

import jax

try:
    from jax import shard_map as shard_map      # noqa: F401  (jax >= 0.6)
    _NEEDS_BACKFILL = False
except ImportError:
    from jax.experimental.shard_map import shard_map as _experimental

    def shard_map(f, /, **kwargs):
        # check_vma maps onto the old check_rep; sites that leave it
        # unset get check_rep=False, because old jax has no replication
        # rule for pallas_call (and several collectives) — the check is
        # a diagnostic, correct programs run identically without it
        kwargs["check_rep"] = kwargs.pop("check_vma", False)
        return _experimental(f, **kwargs)

    _NEEDS_BACKFILL = True


def install() -> None:
    """Backfill the current-jax API surface this codebase is written
    against on older jax. Attributes are only added when absent —
    current jax is left untouched.

    - ``jax.shard_map`` — the promoted experimental entry point;
    - ``jax.typeof`` — aval lookup (old avals carry no ``.vma``, which
      callers already treat as "no varying-axes info");
    - ``jax.lax.axis_size`` — static axis size from the axis env;
    - ``jax.lax.pcast`` — identity: VMA annotations don't exist before
      0.6, so there is nothing to cast (replication checking on old jax
      is shard_map's check_rep, handled by the shard_map shim).
    """
    from jax import core, lax

    if _NEEDS_BACKFILL and not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map
    if not hasattr(jax, "typeof"):
        jax.typeof = core.get_aval
    if not hasattr(lax, "axis_size"):
        def _axis_size(axis_name):
            size = core.axis_frame(axis_name)
            # axis_frame returned the frame object on some 0.4.x
            # releases and the bare size on others
            return getattr(size, "size", size)
        lax.axis_size = _axis_size
    if not hasattr(lax, "pcast"):
        lax.pcast = lambda x, axis_name=None, *, to=None: x
    _install_pallas()


def _install_pallas() -> None:
    """``pltpu.CompilerParams`` was named ``TPUCompilerParams`` (with a
    smaller field set) before jax 0.6: alias it, dropping fields the old
    dataclass doesn't know (``has_side_effects`` — outputs of the
    kernels here are always consumed, so DCE cannot strike them)."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:       # pallas unavailable on this platform
        return
    if hasattr(pltpu, "CompilerParams") \
            or not hasattr(pltpu, "TPUCompilerParams"):
        return
    import inspect

    fields = set(inspect.signature(pltpu.TPUCompilerParams).parameters)

    def CompilerParams(**kwargs):               # noqa: N802
        return pltpu.TPUCompilerParams(
            **{k: v for k, v in kwargs.items() if k in fields})

    pltpu.CompilerParams = CompilerParams
