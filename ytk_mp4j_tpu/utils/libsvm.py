"""Streaming libsvm / libffm text reader — the consumer-side ingestion
path for data that cannot be staged in memory (ytk-learn trains from
libsvm-format files; BASELINE.json configs[4] names a 1TB workload).

Formats, one instance per line:

    libsvm:  ``label feat:val feat:val ...``
    libffm:  ``label field:feat:val field:feat:val ...``

``read_libsvm`` yields fixed-width ``(feats, fields, vals, y)`` numpy
chunks of at most ``chunk_rows`` rows, each slot axis padded to
``max_nnz`` (padded slots carry value 0, the mask convention of
``FMTrainer``) — exactly the minibatch shape ``FMTrainer.fit_stream``
consumes, so ``fit_stream(read_libsvm(path, ...))`` trains end-to-end
without ever holding more than one chunk in memory.
"""

from __future__ import annotations

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError


def parse_line(line: str, max_nnz: int, lineno: int):
    """One ``label [field:]feat:val ...`` line -> (y, feats, fields,
    vals) lists. Mixed 2- and 3-part tokens on one line are an error;
    more than ``max_nnz`` tokens are an error (silent truncation would
    quietly change the model)."""
    parts = line.split()
    try:
        y = float(parts[0])
    except ValueError:
        raise Mp4jError(
            f"line {lineno}: label {parts[0]!r} is not a number") from None
    if len(parts) - 1 > max_nnz:
        raise Mp4jError(
            f"line {lineno}: {len(parts) - 1} entries exceed "
            f"max_nnz={max_nnz}")
    feats, fields, vals = [], [], []
    width = None
    for tok in parts[1:]:
        pieces = tok.split(":")
        if width is None:
            width = len(pieces)
        if len(pieces) != width or width not in (2, 3):
            raise Mp4jError(
                f"line {lineno}: token {tok!r} is neither feat:val nor "
                "field:feat:val (or the line mixes the two)")
        try:
            if width == 2:
                feats.append(int(pieces[0]))
                fields.append(0)
                vals.append(float(pieces[1]))
            else:
                fields.append(int(pieces[0]))
                feats.append(int(pieces[1]))
                vals.append(float(pieces[2]))
        except ValueError:
            raise Mp4jError(
                f"line {lineno}: malformed token {tok!r}") from None
    return y, feats, fields, vals


# MEASURED (round 5, don't redo): a numpy-vectorized chunk parser is a
# dead end. np.char.partition is a per-element Python loop (30x slower
# than one C split of the colon-replaced join), and numpy's
# string->number array casts cost the same ~95 ns/item as Python's
# int()/float(), so the best all-numpy pipeline reached only 1.0-1.3x
# the per-line parser. The fast path is the native one-pass C++ scanner
# (csrc/mp4j_parse.cpp via utils.native.parse_libsvm_chunk); Python
# parse_line stays as the semantic contract and the diagnostics/replay
# path.


def _parse_chunk_slow(lines, linenos, max_nnz: int):
    """Per-line replay of a chunk the native parser refused: raises the
    exact :func:`parse_line` error, or returns the parsed chunk when
    the lines are individually valid (e.g. exotic-but-valid literals
    like underscores, inf labels, or huge Python ints)."""
    n = len(lines)
    feats = np.zeros((n, max_nnz), np.int32)
    fields = np.zeros((n, max_nnz), np.int32)
    vals = np.zeros((n, max_nnz), np.float32)
    y = np.zeros(n, np.float32)
    for i, (ln, lno) in enumerate(zip(lines, linenos)):
        yv, f, fl, v = parse_line(ln, max_nnz, lno)
        y[i] = yv
        feats[i, : len(f)] = f
        fields[i, : len(fl)] = fl
        vals[i, : len(v)] = v
    return feats, fields, vals, y


def read_libsvm(path_or_lines, chunk_rows: int, max_nnz: int):
    """Stream a libsvm/libffm source in fixed-width numpy chunks.

    ``path_or_lines``: a file path or any iterable of text lines (an
    open file object streams without loading the file). Yields
    ``(feats [N, max_nnz] i32, fields [N, max_nnz] i32,
    vals [N, max_nnz] f32, y [N] f32)`` with ``N <= chunk_rows`` —
    feed directly to ``FMTrainer.fit_stream`` (pass
    ``batch_rows=chunk_rows`` so the short final chunk reuses the same
    compiled step).

    Parsing rides the native one-pass chunk scanner
    (``csrc/mp4j_parse.cpp``); chunks it refuses — malformed lines,
    over-long lines, exotic literals — replay per line through
    :func:`parse_line`, so error messages keep their exact line numbers
    and anything Python accepts still parses (slowly).
    """
    from ytk_mp4j_tpu.utils import native

    if chunk_rows <= 0:
        raise Mp4jError(f"chunk_rows must be positive, got {chunk_rows}")

    def parse(buf, lnos):
        got = native.parse_libsvm_chunk(
            "\n".join(buf).encode(), len(buf), max_nnz)
        if got is None:
            return _parse_chunk_slow(buf, lnos, max_nnz)
        return got

    def chunks(lines):
        buf, lnos = [], []
        for lineno, line in enumerate(lines, 1):
            if not line.strip():
                continue
            buf.append(line)
            lnos.append(lineno)
            if len(buf) == chunk_rows:
                yield parse(buf, lnos)
                buf, lnos = [], []
        if buf:
            yield parse(buf, lnos)

    if isinstance(path_or_lines, str):
        def from_path():
            with open(path_or_lines) as fh:
                yield from chunks(fh)
        return from_path()
    return chunks(path_or_lines)


def dense_chunks(chunks, n_features: int):
    """Adapt :func:`read_libsvm`'s padded-sparse chunks to dense
    ``(x [N, F], y)`` pairs — the shape ``LinearTrainer.fit_stream``
    consumes (ytk-learn's linear family trains from the same libsvm
    text as FFM). Duplicate feature ids on one line ACCUMULATE (the
    additive convention of a sparse dot product); padded slots carry
    value 0 and add nothing. Feature ids must lie in [0, n_features).
    """
    for feats, fields, vals, y in chunks:
        if feats.size and (feats.min() < 0
                           or feats.max() >= n_features):
            raise Mp4jError(
                f"feature id out of range [0, {n_features}) in chunk")
        N = feats.shape[0]
        # bincount, not np.add.at: identical duplicate-accumulating
        # semantics at C speed (add.at is an unbuffered per-element
        # loop, ~10x slower on the ms-per-chunk host budget)
        flat = (np.arange(N, dtype=np.int64)[:, None]
                * n_features + feats).ravel()
        x = np.bincount(flat, weights=vals.ravel().astype(np.float64),
                        minlength=N * n_features)
        yield x.reshape(N, n_features).astype(np.float32), y
