"""Streaming libsvm / libffm text reader — the consumer-side ingestion
path for data that cannot be staged in memory (ytk-learn trains from
libsvm-format files; BASELINE.json configs[4] names a 1TB workload).

Formats, one instance per line:

    libsvm:  ``label feat:val feat:val ...``
    libffm:  ``label field:feat:val field:feat:val ...``

``read_libsvm`` yields fixed-width ``(feats, fields, vals, y)`` numpy
chunks of at most ``chunk_rows`` rows, each slot axis padded to
``max_nnz`` (padded slots carry value 0, the mask convention of
``FMTrainer``) — exactly the minibatch shape ``FMTrainer.fit_stream``
consumes, so ``fit_stream(read_libsvm(path, ...))`` trains end-to-end
without ever holding more than one chunk in memory.
"""

from __future__ import annotations

import numpy as np

from ytk_mp4j_tpu.exceptions import Mp4jError


def parse_line(line: str, max_nnz: int, lineno: int):
    """One ``label [field:]feat:val ...`` line -> (y, feats, fields,
    vals) lists. Mixed 2- and 3-part tokens on one line are an error;
    more than ``max_nnz`` tokens are an error (silent truncation would
    quietly change the model)."""
    parts = line.split()
    try:
        y = float(parts[0])
    except ValueError:
        raise Mp4jError(
            f"line {lineno}: label {parts[0]!r} is not a number") from None
    if len(parts) - 1 > max_nnz:
        raise Mp4jError(
            f"line {lineno}: {len(parts) - 1} entries exceed "
            f"max_nnz={max_nnz}")
    feats, fields, vals = [], [], []
    width = None
    for tok in parts[1:]:
        pieces = tok.split(":")
        if width is None:
            width = len(pieces)
        if len(pieces) != width or width not in (2, 3):
            raise Mp4jError(
                f"line {lineno}: token {tok!r} is neither feat:val nor "
                "field:feat:val (or the line mixes the two)")
        try:
            if width == 2:
                feats.append(int(pieces[0]))
                fields.append(0)
                vals.append(float(pieces[1]))
            else:
                fields.append(int(pieces[0]))
                feats.append(int(pieces[1]))
                vals.append(float(pieces[2]))
        except ValueError:
            raise Mp4jError(
                f"line {lineno}: malformed token {tok!r}") from None
    return y, feats, fields, vals


def read_libsvm(path_or_lines, chunk_rows: int, max_nnz: int):
    """Stream a libsvm/libffm source in fixed-width numpy chunks.

    ``path_or_lines``: a file path or any iterable of text lines (an
    open file object streams without loading the file). Yields
    ``(feats [N, max_nnz] i32, fields [N, max_nnz] i32,
    vals [N, max_nnz] f32, y [N] f32)`` with ``N <= chunk_rows`` —
    feed directly to ``FMTrainer.fit_stream`` (pass
    ``batch_rows=chunk_rows`` so the short final chunk reuses the same
    compiled step).
    """
    if chunk_rows <= 0:
        raise Mp4jError(f"chunk_rows must be positive, got {chunk_rows}")

    def chunks(lines):
        buf_y, buf_f, buf_fl, buf_v = [], [], [], []

        def flush():
            n = len(buf_y)
            feats = np.zeros((n, max_nnz), np.int32)
            fields = np.zeros((n, max_nnz), np.int32)
            vals = np.zeros((n, max_nnz), np.float32)
            for i, (f, fl, v) in enumerate(zip(buf_f, buf_fl, buf_v)):
                feats[i, : len(f)] = f
                fields[i, : len(fl)] = fl
                vals[i, : len(v)] = v
            y = np.asarray(buf_y, np.float32)
            buf_y.clear(), buf_f.clear(), buf_fl.clear(), buf_v.clear()
            return feats, fields, vals, y

        for lineno, line in enumerate(lines, 1):
            if not line.strip():
                continue
            y, feats, fields, vals = parse_line(line, max_nnz, lineno)
            buf_y.append(y)
            buf_f.append(feats)
            buf_fl.append(fields)
            buf_v.append(vals)
            if len(buf_y) == chunk_rows:
                yield flush()
        if buf_y:
            yield flush()

    if isinstance(path_or_lines, str):
        def from_path():
            with open(path_or_lines) as fh:
                yield from chunks(fh)
        return from_path()
    return chunks(path_or_lines)
