"""Per-collective transport statistics.

Unlike :mod:`ytk_mp4j_tpu.utils.trace` (opt-in wall-time tracing of
whole collective calls), this layer is ALWAYS ON and counts what the
data plane actually did, per collective family: wire bytes moved in
each direction, wire/reduce/serialize busy-time, chunk count, and call
count. The counters are cheap (a locked dict update per chunk/phase,
not per element) and are the measurement substrate every perf PR is
judged against — ``comm.stats()`` on the process and thread backends
returns a snapshot.

Attribution: :func:`ytk_mp4j_tpu.utils.trace.traced` (which already
wraps every backend collective) calls :meth:`CommStats.begin` /
:meth:`CommStats.end` around the OUTERMOST collective call on a
thread, so phase events recorded deeper in the stack (channel sends,
native exchanges, merge kernels — possibly on helper threads) land on
the collective that caused them. Events outside any collective land on
``"<untracked>"``.

Observability hooks (ISSUE 3): every outermost ``begin`` bumps the
per-slave monotonically increasing collective **sequence number** the
cluster hang diagnosis compares across ranks, :meth:`progress` is the
heartbeat payload the slave ships to the master, and every phase event
also lands in the bounded span ring (:mod:`ytk_mp4j_tpu.obs.spans`) as
a chunk-granularity timeline span tagged with its collective and
sequence number.

Schema of one snapshot entry (all keys always present)::

    {"calls": int, "bytes_sent": int, "bytes_recv": int,
     "chunks": int, "keys": int, "retries": int, "reconnects": int,
     "aborts_seen": int, "wire_bytes_tcp": int, "wire_bytes_shm": int,
     "wire_seconds": float, "reduce_seconds": float,
     "serialize_seconds": float}

Phase seconds are BUSY times and may overlap in wall time (the whole
point of the pipelined engine is that wire and reduce overlap), so
their sum can exceed the collective's wall time.

``wire_bytes_tcp`` / ``wire_bytes_shm`` (ISSUE 7) split the wire
bytes (both directions summed) by the transport they rode, so
``mp4j-scope live`` and postmortem bundles show which plane moved a
collective's data; events whose channel does not declare a transport
(bare test channels) book into neither, so the split is a lower bound
that equals the total whenever every byte rode a tagged channel. The
frame-size histogram splits the same way (``frame_bytes/tcp`` /
``frame_bytes/shm`` metric families).

``keys`` counts map entries this rank encoded into columnar frames
(the socket map plane, ISSUE 4) — per call it equals the local map
size, so analytic keys-per-second and wire-bytes-per-key fall straight
out of a snapshot. Columnar phase attribution: codec encode/decode and
value packing book ``serialize_seconds`` (they are serialization, like
pickle on the object path), the vectorized sorted-union merge books
``reduce_seconds``, and the paired column frames book wire
seconds/bytes through the channel like any framed array.
"""

from __future__ import annotations

import threading
import time

from ytk_mp4j_tpu.obs import metrics as metrics_mod
from ytk_mp4j_tpu.obs import spans

_PHASES = ("wire_seconds", "reduce_seconds", "serialize_seconds")
# retries/reconnects/aborts_seen (ISSUE 5): how many recovery rounds a
# collective burned (booked into its bucket), how many peer channels
# were re-dialed into a fresh epoch, and how many abort fan-outs this
# rank observed (control-plane events, booked wherever the rank stood).
# replacements_seen/shrinks_seen (ISSUE 10): membership changes this
# rank lived through — an adoption on the joiner, a renumbering on
# every shrink survivor.
# outstanding_peak/coalesced_frames + async_inflight/async_overlap
# (ISSUE 11): the nonblocking scheduler's counters. outstanding_peak
# is kept monotone by booking increases only (per-rank value = the
# true peak; cluster folds sum peaks across ranks); coalesced_frames
# counts fused map executions that merged >= 2 maps in one frame
# train; async_inflight/async_overlap are WALL seconds with >= 1 /
# >= 2 collectives outstanding (suffix-free on purpose — they are
# wall intervals, not busy phases, and must stay out of the phase
# span/critpath machinery), the substrate of the ovl% column.
# wire_bytes_shm_ring (ISSUE 15): the subset of wire_bytes_shm that
# moved through the lock-free rings themselves (raw-plane pieces AND
# frame-routed payload units) rather than the pair's TCP carrier — the
# acceptance evidence that the framed/columnar-map planes actually
# ride the rings for co-located pairs.
# coalesced_elems (ISSUE 17): elements shipped by fused
# allreduce_array_multi batches that merged >= 2 arrays — the array
# plane's analogue of the map plane's keys-under-coalescing evidence.
_COUNTERS = ("calls", "bytes_sent", "bytes_recv", "chunks", "keys",
             "retries", "reconnects", "aborts_seen",
             "replacements_seen", "shrinks_seen",
             "wire_bytes_tcp", "wire_bytes_shm",
             "wire_bytes_shm_ring",
             "outstanding_peak", "coalesced_frames",
             "coalesced_elems",
             "async_inflight", "async_overlap")

# transports the wire split books (ISSUE 7); anything else (bare test
# channels, transport-agnostic callers) keeps the untagged totals only
_TRANSPORTS = ("tcp", "shm")


def _zero() -> dict[str, float]:
    entry: dict[str, float] = {k: 0 for k in _COUNTERS}
    entry.update({k: 0.0 for k in _PHASES})
    return entry


class CommStats:
    """Per-backend collective counters (see module docstring).

    ``begin``/``end`` nest per THREAD (only the outermost names the
    bucket); the add methods may be called from any thread — helper
    threads inherit the bucket that was current when the work was
    handed to them via the ``bucket()`` handle.

    ``rank`` (set by the owning slave after rendezvous) tags the span
    ring's timeline track and the heartbeat's identity; ``None`` (e.g.
    a standalone thread group) renders as rank 0.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._agg: dict[str, dict[str, float]] = {}
        self._tl = threading.local()
        self.rank: int | None = None
        # metrics plane (ISSUE 6): per-family latency + frame-size
        # histograms ride here; the heartbeat ships their deltas.
        # MP4J_METRICS=0 turns every observe into a flag check.
        self.metrics = metrics_mod.MetricsRegistry()
        # audit plane (ISSUE 8): the owning slave's AuditRing, set
        # alongside ``rank`` — channels reach it through their
        # ``stats`` attachment for the per-frame wire digests
        # (MP4J_AUDIT=verify|capture); None when auditing is off or
        # the stats belong to a non-audited backend
        self.audit = None
        # progress state for the telemetry heartbeat / hang diagnosis
        self._seq = 0                      # outermost collectives entered
        self._current: str | None = None   # collective in flight
        self._current_since = 0.0
        self._last: str | None = None      # last collective completed
        self._last_phase: str | None = None
        # helper-thread fallback: pool workers doing wire work on a
        # collective's behalf have no thread-local scope, so the
        # outermost begin also publishes the name here. Concurrent
        # outermost scopes only happen on the thread backend, where the
        # barrier-aligned schedule guarantees they share one name.
        self._shared_name: str | None = None
        self._shared_seq = 0
        self._shared_depth = 0
        # per-link rolling accumulators (ISSUE 15): the tuner's
        # evidence substrate — cumulative wire bytes/seconds/frames per
        # peer link (split per transport) plus compression outcomes
        # (raw payload bytes -> wire bytes), all monotone so windowed
        # deltas fall out of two snapshots. Applied per-link socket
        # buffer sizes land here too (note_link) so the decision the
        # transport actually took is observable next to its evidence.
        self._links: dict[int, dict[str, float]] = {}

    # -- attribution ---------------------------------------------------
    def begin(self, name: str) -> int:
        """Enter a collective scope; returns the (truthy) sequence
        number when this is the outermost scope on the calling thread,
        0 for nested scopes (the caller must pass the return value back
        to :meth:`end`)."""
        depth = getattr(self._tl, "depth", 0)
        self._tl.depth = depth + 1
        if depth == 0:
            self._tl.name = name
            now = time.perf_counter()
            # per-thread start time: on the shared thread-backend stats
            # another thread's begin() can overwrite _current_since, so
            # the latency histogram reads the thread-local copy
            self._tl.t0 = now
            with self._lock:
                self._seq += 1
                seq = self._seq
                self._current = name
                self._current_since = now
                self._last_phase = None  # phase is per-collective: a
                # rank stuck before booking any phase must not report
                # the PREVIOUS collective's last phase in its heartbeat
                self._bucket_locked(name)["calls"] += 1
                self._shared_name = name
                self._shared_seq = seq
                self._shared_depth += 1
            self._tl.seq = seq
            return seq
        return 0

    def end(self, outermost: int) -> None:
        self._tl.depth = getattr(self._tl, "depth", 1) - 1
        if outermost:
            name = getattr(self._tl, "name", None)
            t0 = getattr(self._tl, "t0", None)
            self._tl.name = None
            with self._lock:
                self._last = self._current or self._last
                self._current = None
                self._shared_depth -= 1
                if self._shared_depth <= 0:
                    self._shared_name = None
            # per-family latency histogram (metrics plane, ISSUE 6):
            # observed outside the lock — the registry has its own
            if name is not None and t0 is not None:
                self.metrics.observe(
                    f"latency/{name}", time.perf_counter() - t0,
                    metrics_mod.LATENCY_LO, metrics_mod.LATENCY_BUCKETS)

    def bucket(self) -> str:
        """The current attribution bucket: this thread's collective
        scope, else the slave's active collective (helper threads),
        else ``"<untracked>"``."""
        return self._attribution()[0]

    def _attribution(self) -> tuple[str, int]:
        """(bucket, seq) captured TOGETHER, so a span's seq tag always
        matches the collective instance it is attributed to — on the
        shared thread-backend stats another thread's begin() may bump
        the global seq while this thread's scope is still open."""
        name = getattr(self._tl, "name", None)
        if name is not None:
            return name, getattr(self._tl, "seq", 0)
        shared = self._shared_name
        if shared is not None:
            return shared, self._shared_seq
        return "<untracked>", self._seq

    # -- nonblocking-scheduler attribution (ISSUE 11) ------------------
    def async_begin(self, name: str) -> int:
        """Open a scheduler-driven collective scope WITHOUT the
        thread-local nesting of :meth:`begin` — the progression thread
        holds several collectives open at once, and per-thread depth
        tracking would fold them into one. Bumps the sequence number,
        counts the call, and publishes the shared helper-thread
        attribution name; pair with :meth:`async_end`."""
        now = time.perf_counter()
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._current = name
            self._current_since = now
            self._last_phase = None
            self._bucket_locked(name)["calls"] += 1
            self._shared_name = name
            self._shared_seq = seq
            self._shared_depth += 1
        return seq

    def async_end(self, name: str, seconds: float) -> None:
        """Close an :meth:`async_begin` scope, feeding the per-family
        latency histogram with the collective's submit-to-complete
        wall time."""
        with self._lock:
            self._last = name
            self._shared_depth -= 1
            if self._shared_depth <= 0:
                self._shared_depth = 0
                self._shared_name = None
                self._current = None
        self.metrics.observe(
            f"latency/{name}", seconds,
            metrics_mod.LATENCY_LO, metrics_mod.LATENCY_BUCKETS)

    class _Scope:
        __slots__ = ("stats", "name", "seq", "prev")

        def __init__(self, stats, name, seq):
            self.stats = stats
            self.name = name
            self.seq = seq

        def __enter__(self):
            tl = self.stats._tl
            self.prev = (getattr(tl, "name", None),
                         getattr(tl, "seq", 0))
            tl.name = self.name
            tl.seq = self.seq
            return self

        def __exit__(self, *exc):
            tl = self.stats._tl
            tl.name, tl.seq = self.prev
            return False

    def scope(self, name: str, seq: int):
        """Thread-local attribution override (no depth/seq side
        effects): the nonblocking engine wraps a blocking primitive it
        executes on a collective's behalf so the primitive's internal
        bookings land on that collective's bucket."""
        return self._Scope(self, name, seq)

    def seed_seq(self, seq: int) -> None:
        """Seed the collective sequence number of a freshly adopted
        joiner (ISSUE 10): its heartbeats must report the JOB's
        position, not 0 — a zero seq would read as the maximal laggard
        in every skew table and hang diagnosis the moment it joins."""
        with self._lock:
            self._seq = max(self._seq, int(seq))
            self._shared_seq = self._seq

    def progress(self) -> dict:
        """The heartbeat progress record (schema: obs.telemetry):
        sequence number, the collective in flight (and for how long),
        the last completed collective, and the last phase booked."""
        with self._lock:
            current_secs = (time.perf_counter() - self._current_since
                            if self._current is not None else 0.0)
            return {"seq": self._seq, "current": self._current,
                    "last": self._last, "phase": self._last_phase,
                    "current_secs": current_secs}

    # -- recording -----------------------------------------------------
    def _bucket_locked(self, name: str) -> dict[str, float]:
        entry = self._agg.get(name)
        if entry is None:
            entry = self._agg[name] = _zero()
        return entry

    def add(self, key: str, value: float, bucket: str | None = None) -> None:
        if bucket is None:
            name, seq = self._attribution()
        else:
            name, seq = bucket, self._seq
        is_phase = key.endswith("_seconds")
        with self._lock:
            self._bucket_locked(name)[key] += value
            if is_phase:
                self._last_phase = key[:-len("_seconds")]
        # module-flag guard: with spans disabled (MP4J_SPAN_RING=0) the
        # hot path pays one attribute read, not a call + kwargs dict
        if is_phase and spans._enabled:
            spans.phase(key[:-len("_seconds")], value, self.rank, name,
                        seq)

    def add_wire(self, bytes_sent: int, bytes_recv: int, seconds: float,
                 chunks: int = 1, bucket: str | None = None,
                 peer: int | None = None,
                 transport: str | None = None) -> None:
        if bucket is None:
            name, seq = self._attribution()
        else:
            name, seq = bucket, self._seq
        tagged = transport if transport in _TRANSPORTS else None
        with self._lock:
            e = self._bucket_locked(name)
            e["bytes_sent"] += bytes_sent
            e["bytes_recv"] += bytes_recv
            e["wire_seconds"] += seconds
            e["chunks"] += chunks
            if tagged is not None:
                e[f"wire_bytes_{tagged}"] += bytes_sent + bytes_recv
            if peer is not None:
                lk = self._link_locked(peer)
                lk["bytes"] += bytes_sent + bytes_recv
                lk["secs"] += seconds
                lk["frames"] += 1
                if tagged is not None:
                    lk[f"bytes_{tagged}"] += bytes_sent + bytes_recv
            self._last_phase = "wire"
        if spans._enabled:
            # transport rides the span args too (ISSUE 9): the
            # critical-path analyzer attributes a dominated ordinal to
            # a (rank, peer link, transport), so the wire span must
            # name the plane the bytes rode, not just the peer
            spans.phase("wire", seconds, self.rank, name, seq,
                        bytes_sent=bytes_sent or None,
                        bytes_recv=bytes_recv or None, peer=peer,
                        transport=tagged)
        # frame-size histogram, one observation per direction moved,
        # split per transport (the ISSUE 7 attribution satellite)
        if self.metrics.enabled:
            fam = (f"frame_bytes/{tagged}" if tagged is not None
                   else "frame_bytes")
            if bytes_sent:
                self.metrics.observe(fam, bytes_sent,
                                     metrics_mod.FRAME_LO,
                                     metrics_mod.FRAME_BUCKETS)
            if bytes_recv:
                self.metrics.observe(fam, bytes_recv,
                                     metrics_mod.FRAME_LO,
                                     metrics_mod.FRAME_BUCKETS)

    # -- per-link evidence (ISSUE 15) ----------------------------------
    def _link_locked(self, peer: int) -> dict[str, float]:
        lk = self._links.get(peer)
        if lk is None:
            lk = self._links[peer] = {
                "bytes": 0, "secs": 0.0, "frames": 0,
                "bytes_tcp": 0, "bytes_shm": 0,
                "comp_raw": 0, "comp_wire": 0, "comp_frames": 0,
                "xfer_bytes": 0, "xfers": 0}
        return lk

    def add_transfer(self, peer: int, nbytes: int) -> None:
        """Book one BULK transfer (a collective exchange segment) on
        ``peer``'s link — the granularity evidence the tuner's chunk
        policy consumes (add_wire's per-chunk frames can't recover
        the original transfer size)."""
        with self._lock:
            lk = self._link_locked(peer)
            lk["xfer_bytes"] += nbytes
            lk["xfers"] += 1

    def add_compress(self, peer: int, raw: int, wire: int) -> None:
        """Book one compression outcome on ``peer``'s link: ``raw``
        payload bytes went out as ``wire`` bytes. The rolling ratio
        (and the implied zlib cost already booked as serialize
        seconds) is the evidence the tuner's per-link compression
        policy weighs."""
        with self._lock:
            lk = self._link_locked(peer)
            lk["comp_raw"] += raw
            lk["comp_wire"] += wire
            lk["comp_frames"] += 1

    def note_link(self, peer: int, **info) -> None:
        """Record non-counter link facts (applied socket buffer
        sizes, transport tag) — absolute values, not accumulators."""
        with self._lock:
            self._link_locked(peer).update(info)

    def link_snapshot(self) -> dict[int, dict[str, float]]:
        """Per-peer-link rolling accumulators (ISSUE 15); two
        snapshots diff into one tuner decision window."""
        with self._lock:
            return {p: dict(v) for p, v in self._links.items()}

    def forget_links(self) -> None:
        """Drop the per-link accumulators (membership changes: a
        renumbered peer id must not inherit the old occupant's
        evidence)."""
        with self._lock:
            self._links.clear()

    # -- reading -------------------------------------------------------
    def snapshot(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._agg.items()}

    def clear(self) -> None:
        with self._lock:
            self._agg.clear()


def merge_snapshots(*snaps: dict[str, dict[str, float]]
                    ) -> dict[str, dict[str, float]]:
    """Key-wise sum of snapshots (the thread backend combines its
    intra-process counters with the shared process slave's; the master
    folds heartbeat DELTAS back into its rolling cumulative view)."""
    out: dict[str, dict[str, float]] = {}
    for snap in snaps:
        for name, entry in snap.items():
            acc = out.setdefault(name, _zero())
            for k, v in entry.items():
                acc[k] = acc.get(k, 0) + v
    return out


def diff_snapshots(cur: dict[str, dict[str, float]],
                   prev: dict[str, dict[str, float]]
                   ) -> dict[str, dict[str, float]]:
    """``cur - prev``, pruned to families that actually changed —
    the heartbeat payload (ISSUE 6 satellite): a long job's beat is
    bounded by activity since the last beat, not by every collective
    family ever seen. All stats are monotone accumulators, so
    ``merge_snapshots(prev, diff_snapshots(cur, prev)) == cur``."""
    out: dict[str, dict[str, float]] = {}
    for name, entry in cur.items():
        base = prev.get(name)
        if base is None:
            delta = dict(entry)
        else:
            delta = {k: v - base.get(k, 0) for k, v in entry.items()}
        if any(delta.values()):
            out[name] = delta
    return out
