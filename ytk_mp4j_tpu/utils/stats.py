"""Per-collective transport statistics.

Unlike :mod:`ytk_mp4j_tpu.utils.trace` (opt-in wall-time tracing of
whole collective calls), this layer is ALWAYS ON and counts what the
data plane actually did, per collective family: wire bytes moved in
each direction, wire/reduce/serialize busy-time, chunk count, and call
count. The counters are cheap (a locked dict update per chunk/phase,
not per element) and are the measurement substrate every perf PR is
judged against — ``comm.stats()`` on the process and thread backends
returns a snapshot.

Attribution: :func:`ytk_mp4j_tpu.utils.trace.traced` (which already
wraps every backend collective) calls :meth:`CommStats.begin` /
:meth:`CommStats.end` around the OUTERMOST collective call on a
thread, so phase events recorded deeper in the stack (channel sends,
native exchanges, merge kernels — possibly on helper threads) land on
the collective that caused them. Events outside any collective land on
``"<untracked>"``.

Schema of one snapshot entry (all keys always present)::

    {"calls": int, "bytes_sent": int, "bytes_recv": int,
     "chunks": int, "wire_seconds": float, "reduce_seconds": float,
     "serialize_seconds": float}

Phase seconds are BUSY times and may overlap in wall time (the whole
point of the pipelined engine is that wire and reduce overlap), so
their sum can exceed the collective's wall time.
"""

from __future__ import annotations

import threading

_PHASES = ("wire_seconds", "reduce_seconds", "serialize_seconds")
_COUNTERS = ("calls", "bytes_sent", "bytes_recv", "chunks")


def _zero() -> dict[str, float]:
    entry: dict[str, float] = {k: 0 for k in _COUNTERS}
    entry.update({k: 0.0 for k in _PHASES})
    return entry


class CommStats:
    """Per-backend collective counters (see module docstring).

    ``begin``/``end`` nest per THREAD (only the outermost names the
    bucket); the add methods may be called from any thread — helper
    threads inherit the bucket that was current when the work was
    handed to them via the ``bucket()`` handle.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._agg: dict[str, dict[str, float]] = {}
        self._tl = threading.local()
        # helper-thread fallback: pool workers doing wire work on a
        # collective's behalf have no thread-local scope, so the
        # outermost begin also publishes the name here. Concurrent
        # outermost scopes only happen on the thread backend, where the
        # barrier-aligned schedule guarantees they share one name.
        self._shared_name: str | None = None
        self._shared_depth = 0

    # -- attribution ---------------------------------------------------
    def begin(self, name: str) -> bool:
        """Enter a collective scope; returns True when this is the
        outermost scope on the calling thread (the caller must pass
        that flag back to :meth:`end`)."""
        depth = getattr(self._tl, "depth", 0)
        self._tl.depth = depth + 1
        if depth == 0:
            self._tl.name = name
            with self._lock:
                self._bucket_locked(name)["calls"] += 1
                self._shared_name = name
                self._shared_depth += 1
            return True
        return False

    def end(self, outermost: bool) -> None:
        self._tl.depth = getattr(self._tl, "depth", 1) - 1
        if outermost:
            self._tl.name = None
            with self._lock:
                self._shared_depth -= 1
                if self._shared_depth <= 0:
                    self._shared_name = None

    def bucket(self) -> str:
        """The current attribution bucket: this thread's collective
        scope, else the slave's active collective (helper threads),
        else ``"<untracked>"``."""
        name = getattr(self._tl, "name", None)
        if name is not None:
            return name
        return self._shared_name or "<untracked>"

    # -- recording -----------------------------------------------------
    def _bucket_locked(self, name: str) -> dict[str, float]:
        entry = self._agg.get(name)
        if entry is None:
            entry = self._agg[name] = _zero()
        return entry

    def add(self, key: str, value: float, bucket: str | None = None) -> None:
        with self._lock:
            self._bucket_locked(bucket or self.bucket())[key] += value

    def add_wire(self, bytes_sent: int, bytes_recv: int, seconds: float,
                 chunks: int = 1, bucket: str | None = None) -> None:
        with self._lock:
            e = self._bucket_locked(bucket or self.bucket())
            e["bytes_sent"] += bytes_sent
            e["bytes_recv"] += bytes_recv
            e["wire_seconds"] += seconds
            e["chunks"] += chunks

    # -- reading -------------------------------------------------------
    def snapshot(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._agg.items()}

    def clear(self) -> None:
        with self._lock:
            self._agg.clear()


def merge_snapshots(*snaps: dict[str, dict[str, float]]
                    ) -> dict[str, dict[str, float]]:
    """Key-wise sum of snapshots (the thread backend combines its
    intra-process counters with the shared process slave's)."""
    out: dict[str, dict[str, float]] = {}
    for snap in snaps:
        for name, entry in snap.items():
            acc = out.setdefault(name, _zero())
            for k, v in entry.items():
                acc[k] = acc.get(k, 0) + v
    return out
