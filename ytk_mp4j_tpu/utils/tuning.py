"""Transport tuning knobs + size-aware collective algorithm selection.

Everything in this module is a PURE FUNCTION of job-wide call
parameters (payload size, rank count, env-configured thresholds) —
mp4j-lint R1/R8 territory: two ranks evaluating the same collective
call must derive the identical algorithm and chunk schedule, or they
would disagree about the wire protocol and deadlock. The env knobs are
therefore JOB-wide configuration: every rank of a job must run with the
same values (exactly like ``native_transport``).

Knobs (all validated where they are consumed; garbage raises
``Mp4jError`` at slave/channel setup, not mid-collective):

- ``MP4J_CHUNK_BYTES`` — pipeline chunk size for the chunked
  collective engine (default 1 MiB, measured on the bench host: the
  scratch-buffer pool already keeps receive pages warm, so sub-MiB
  chunks pay per-exchange poll/syscall overhead without buying more
  cache locality; 1 MiB leaves typical segments monolithic while
  bounding the merge granularity of multi-MB segments and sizing the
  streaming-compression pieces).
- ``MP4J_ALGO_SMALL_BYTES`` / ``MP4J_ALGO_LARGE_BYTES`` — the
  ``algo="auto"`` thresholds: payloads <= small take the binomial tree
  (latency-bound regime), payloads >= large take the pipelined ring
  (bandwidth-bound regime), in between recursive halving/doubling.
  Defaults are grounded in ``bench.py``'s ``socket_allreduce_sweep``
  (see BENCH JSON ``extra``).
- ``MP4J_SO_SNDBUF`` / ``MP4J_SO_RCVBUF`` — socket buffer sizes applied
  at channel setup (``transport/tcp.py``); unset keeps the kernel
  defaults.
- ``MP4J_SHM`` — the intra-host shared-memory transport
  (``transport/shm.py``): ``1`` (default) lets rendezvous negotiate a
  shm ring pair for every SAME-host peer pair (host fingerprints
  compared from the roster; cross-host pairs always keep TCP); ``0``
  forces TCP everywhere. JOB-wide like ``native_transport`` — the
  handshake carries the decision, but every rank must agree on whether
  to offer it.
- ``MP4J_SHM_RING_BYTES`` — bytes per DIRECTION of each shm peer
  pair's ring buffer (default 1 MiB, matching ``MP4J_CHUNK_BYTES`` so
  a pipeline chunk fits the ring in one pass). Since ISSUE 15 the
  rings carry BOTH planes: raw-plane transfers clearing
  ``SHM_RING_MIN_BYTES`` and framed/columnar-map payloads clearing
  ``MP4J_SHM_FRAME_MIN`` (the header-derived frame routing below) —
  not just the raw plane.
- ``MP4J_SHM_FRAME_MIN`` — frame-level ring routing threshold
  (ISSUE 15): a FRAMED payload (array frames, object frames,
  columnar-map columns, streamed-compression pieces) whose byte
  length — already known to both ends from the frame header / chunk
  length prefix — clears this value rides the shm ring instead of
  the TCP carrier. ``0`` disables frame routing (every framed byte
  keeps the carrier — the pre-ISSUE-15 wire layout). JOB-wide like
  ``native_transport``: the threshold IS the wire protocol for shm
  pairs, so every rank must agree.
- ``MP4J_HEARTBEAT_SECS`` — period of the slave->master telemetry
  heartbeat (``comm/process_comm.py``); ``0`` disables heartbeats.
- ``MP4J_SPAN_RING`` — capacity of the in-process span ring buffer
  (``obs/spans.py``); ``0`` disables span recording.
- ``MP4J_LOG_LEVEL`` — minimum level the master's log sink prints
  (``DEBUG``/``INFO``/``WARN``/``ERROR``).
- ``MP4J_MAP_COLUMNAR`` — socket map-collective wire plane: ``1``
  (default) ships numeric-operand maps as (codes, values) columns
  through the persistent key codec; ``0`` forces the pickled-dict
  reference path (``comm/process_comm.py``; README "Sparse map
  collectives").
- ``MP4J_MAX_RETRIES`` — how many epoch-fenced abort/retry rounds a
  failed collective may attempt before the job aborts terminally
  (``resilience/recovery.py``); ``0`` restores the reference's
  fail-stop behavior (first transport error is final).
- ``MP4J_RECONNECT_BACKOFF`` — base seconds of the capped exponential
  backoff used when re-dialing a dead peer channel during recovery.
- ``MP4J_DEAD_RANK_SECS`` — how stale a rank may go (no abort ack, no
  barrier arrival) before the master declares it dead and fans out a
  terminal abort (``comm/master.py``).
- ``MP4J_FAULT_PLAN`` — deterministic fault-injection plan for chaos
  testing (``resilience/faults.py``; empty disables injection).
- ``MP4J_METRICS`` — the live metrics plane (``obs/metrics.py``): ``1``
  (default) records latency/frame-size histograms and ships metric
  deltas on the heartbeat; ``0`` turns recording into a no-op (the
  bench A/B knob).
- ``MP4J_METRICS_PORT`` — the master's control-plane HTTP metrics
  endpoint (``comm/master.py``): unset/empty disables it, ``0`` binds
  an ephemeral port (``Master.metrics_port`` reports it), anything
  else binds that port.
- ``MP4J_METRICS_WINDOW_SECS`` — the sliding window the master derives
  rates (GB/s, collectives/s, keys/s) over from its ring of interval
  snapshots.
- ``MP4J_POSTMORTEM_DIR`` — flight-recorder directory
  (``obs/postmortem.py``): on any terminal abort every rank dumps a
  postmortem bundle here and the master writes a cluster manifest;
  empty disables the recorder.
- ``MP4J_AUDIT`` — the collective correctness auditing plane
  (``obs/audit.py``): ``off`` | ``digest`` (default: record per-
  collective input/output digests in a bounded ring, record-only) |
  ``verify`` (also ship digest records on the heartbeat and fold
  per-frame wire digests so the master can flag cross-rank
  divergences) | ``capture`` (verify + capture input payloads for
  offline ``mp4j-scope replay``). JOB-wide like ``native_transport``:
  cross-rank digest comparison is only meaningful when every rank
  computes digests the same way over the same schedule.
- ``MP4J_AUDIT_RING`` — capacity (records) of the per-rank audit
  record ring; bounds postmortem/replay coverage and, under
  ``capture``, the payload memory held per rank.
- ``MP4J_SINK`` / ``MP4J_SINK_DIR`` — the durable streaming telemetry
  sink (``obs/sink.py``): with ``MP4J_SINK_DIR`` set (and ``MP4J_SINK``
  not ``off``) every rank drains its span/metrics/audit/recovery rings
  into crc-framed append-only segment files under
  ``<dir>/rank_NNNN/`` on a background thread, so a multi-day job
  keeps full history on disk instead of ring tails
  (``mp4j-scope analyze`` / ``tail``). Unset dir disables the sink.
- ``MP4J_SINK_BYTES`` — PER-RANK disk budget for sink segments; the
  writer rotates segments and evicts the oldest whole segment when
  the rank's directory would exceed it (a job's total footprint is
  bounded by ``slave_num * MP4J_SINK_BYTES``).
- ``MP4J_SINK_FLUSH_SECS`` — period of the sink's background drain
  thread; each drain appends everything new in the source rings as
  frame-wise unbuffered writes, so a ``kill -9`` loses at most one
  flush interval of undrained telemetry plus the single frame being
  written (the torn tail the segment reader detects and reports).
- ``MP4J_ELASTIC`` — elastic-membership mode (ISSUE 10;
  ``resilience/membership.py``): ``off`` (default — a permanently dead
  rank is a job-wide ``Mp4jFatalError``, exactly the pre-elastic
  contract), ``replace`` (the master adopts a warm spare into the dead
  rank's id at the next epoch and the fenced retry continues
  bit-exactly), ``shrink`` (survivors renumber contiguously and
  continue at n-1 — reduction-only workloads), or ``grow`` (ISSUE 13:
  replacement-on-death PLUS roster EXPANSION — registered spares are
  adopted into NEW rank ids at an explicit app epoch boundary,
  ``ProcessCommSlave.resize_point()``, gated by ``MP4J_AUTOSCALE=act``).
  JOB-wide like ``native_transport``. CONFLICTS with
  ``MP4J_MAX_RETRIES=0``: the fenced retry IS the mechanism that
  re-runs the interrupted collective after a membership change, so
  fail-stop mode hard-rejects every elastic mode at setup (a
  validated-knob error, never a silent precedence).
- ``MP4J_SPARES`` — how many warm-spare registrations the master's
  rendezvous waits for before starting the job (spares registered
  later, mid-job, are accepted too); 0 (default) starts without any.
- ``MP4J_ADOPT_SECS`` — how long the master waits for an adopted
  spare's ack before declaring the spare dead and trying the next one
  (or going terminal when the pool is empty).
- ``MP4J_ASYNC`` — the nonblocking-collective scheduler (ISSUE 11;
  ``comm/progress.py``): ``1`` (default) runs ``i*`` submissions on
  the per-slave helper progression thread (interleaved raw-plane
  engine + coalescing + inline execution); ``0`` makes every ``i*``
  call execute EAGERLY on the caller's thread and return an
  already-resolved future — the bench A/B knob, and the frozen-leg
  pin (the shm/audit/sink precedent). A LOCAL execution-strategy
  knob: the wire bytes and their per-channel order are identical
  either way, so ranks need not agree.
- ``MP4J_COALESCE_USECS`` — the small-message coalescing window
  (ISSUE 11): ``iallreduce_map`` submissions arriving within this
  many microseconds fuse into ONE ``allreduce_map_multi`` negotiation
  + columnar frame train, de-fused on completion. ISSUE 17 extends
  the same window to the ARRAY plane: consecutive same-signature
  small ``iallreduce`` submissions fuse into one count-negotiated
  ``allreduce_array_multi`` exchange (tree schedule — the one their
  sizes resolve to individually, so fused == sequential bit-exact).
  ``0`` (default) disables fusion (every ``iallreduce_map`` runs the
  classic single-map plane, every small ``iallreduce`` its own tree
  walk). JOB-wide like ``native_transport``: whether a collective
  call uses the count-negotiating multi protocol or the classic one
  must match on every rank (the negotiated batch size then absorbs
  ragged coalescing depth).
- ``MP4J_OVERLAP`` — trainer-loop compute/communication overlap
  (ISSUE 17; ``models/_base.py``): ``1`` submits each step's
  host-statistics exchange as nonblocking ``iallreduce`` /
  ``iallreduce_map`` futures and drains them at the NEXT step
  boundary (``wait_all``), so the progression thread drives the wire
  while the device runs step k+1; ``0`` (default) keeps today's
  blocking per-step exchange bit-for-bit. A LOCAL execution-strategy
  knob like ``MP4J_ASYNC``: submit order equals collective order on
  every rank either way, only the wait point moves, so ranks need
  not agree. Frozen bench legs pin it off (the shm/audit/sink/
  health/autoscale/tuner precedent).
- ``MP4J_MAX_OUTSTANDING`` — how many nonblocking collectives may be
  queued + in flight per slave before ``i*`` submission blocks
  (backpressure); also caps the engine batch and the coalescing
  fuse depth.
- ``MP4J_HEALTH`` — the streaming health plane (ISSUE 12;
  ``obs/health.py``): ``1``/``on`` (default) has every slave fold its
  span-ring delta into per-ordinal cells on the heartbeat and the
  master run the detector set (online critpath dominance, latency
  drift, storms, sink outages, backlog growth, heartbeat flapping,
  audit escalation) driving per-rank HEALTHY -> DEGRADED -> SUSPECT ->
  EVICT_RECOMMENDED verdicts; ``0``/``off`` disables both sides — the
  bench A/B knob and the frozen-leg pin (the shm/audit/sink
  precedent).
- ``MP4J_HEALTH_WINDOW`` — sliding window (attributed collective
  ordinals) the online dominator computes dominance shares over.
- ``MP4J_HEALTH_DOMINATOR_ORDINALS`` — consecutive slow ordinals one
  rank must gate before the engine recommends eviction (the ROADMAP
  autoscaler contract: "dominator for 500 consecutive ordinals should
  be evictable"); SUSPECT is forced at half this streak.
- ``MP4J_HEALTH_DRIFT_PCT`` — how far (percent) a rank's per-family
  latency must rise above its OWN rolling baseline — with the log2-
  histogram bucket shift confirming — before the drift detector fires.
- ``MP4J_AUTOSCALE`` — the closed-loop elastic autoscaler (ISSUE 13;
  ``resilience/autoscaler.py``): ``off`` (default — the master runs no
  controller, today's behavior bit-for-bit), ``observe`` (the
  controller runs, evaluates the health verdicts and LOGS every action
  it would take, but never acts), ``act`` (planned eviction of
  ``EVICT_RECOMMENDED`` ranks, spare auto-provisioning at pool
  exhaustion, and grow adoption at ``resize_point()`` boundaries all
  fire autonomously, behind the safety rails). Master-side only.
- ``MP4J_AUTOSCALE_COOLDOWN_SECS`` — minimum seconds between two
  autoscaler actions of the same kind; the anti-flap rail (a verdict
  that persists through the cooldown is a trend, not a blip).
- ``MP4J_AUTOSCALE_BUDGET`` — job-lifetime cap on autoscaler actions;
  a controller that wants action N+1 is oscillating, and a bounded
  actuator is strictly safer than an unbounded one.
- ``MP4J_PROVISION_CMD`` — operator hook command: when the warm-spare
  pool drains to zero under ``MP4J_AUTOSCALE=act``, the master runs
  this shell command (env ``MP4J_MASTER_HOST``/``MP4J_MASTER_PORT``
  point at the rendezvous listener) to spawn a fresh ``spare=True``
  process; empty disables the subprocess path (the
  ``Master(provision_hook=)`` constructor seam still works).
- ``MP4J_TUNER`` — the self-tuning data plane (ISSUE 15;
  ``utils/tuner.py``): ``off`` (static knobs only, the pre-tuner
  behavior bit-for-bit), ``observe`` (default: the policy core
  evaluates the rolling per-link stats every window and RECORDS the
  decisions it would make — telemetry, ``mp4j-scope tuner`` — but
  applies nothing), ``act`` (per-link chunk-size / compression /
  socket-buffer decisions apply at outermost-collective boundaries,
  and the master may demote a persistently wire-dominated host
  leader through a fenced topology update). A LOCAL
  execution-strategy knob for the per-link decisions (the framed
  wire format is receiver-auto-detected, so sender-side decisions
  never desync a pair) — but run every rank with the same value so
  the telemetry reads coherently.
- ``MP4J_TUNER_WINDOW_SECS`` — how often the tuner folds the rolling
  per-link stats into a decision window; hysteresis is counted in
  these windows (a decision changes only after
  ``tuner.SUSTAIN_WINDOWS`` consecutive windows agree).
- ``MP4J_FLEET_POLL_SECS`` / ``MP4J_FLEET_STALE_SECS`` /
  ``MP4J_FLEET_SINK_DIR`` — the cross-job fleet poller (ISSUE 18;
  ``obs/fleet.py`` behind ``mp4j-scope fleet``): sweep period, the
  seconds-without-a-scrape bound that degrades a job ``LIVE ->
  STALE`` (``GONE`` at 3x), and the durable fleet-history directory
  (crc-framed segments, ``mp4j-scope fleet-report``; empty disables
  it). SCRAPER-side knobs — they configure the observer machine, not
  the jobs, so no job-wide-agreement requirement applies.
- ``MP4J_SO_BUF_MAP`` — explicit PER-LINK socket buffer overrides:
  ``"peer:sndbuf[/rcvbuf],..."`` (e.g. ``"2:262144,3:524288/1048576"``)
  applies those buffer sizes to the TCP link with that peer rank at
  channel setup (dial side before ``connect()``, accept side after
  the handshake identifies the peer), overriding the job-wide
  ``MP4J_SO_{SND,RCV}BUF`` for that link; the applied values are
  recorded per link in ``comm.link_stats()``.
"""

from __future__ import annotations

import os

from ytk_mp4j_tpu.exceptions import Mp4jError

DEFAULT_CHUNK_BYTES = 1024 * 1024
# Sweep-grounded (bench.py socket_allreduce_sweep on the bench host,
# BENCH JSON extra): the binomial tree wins the latency-bound regime up
# to ~256 KiB (~1.5x over RHD at 64 KiB); RHD wins the middle; from
# ~4 MiB the pipelined ring's uniform per-step segments edge out RHD's
# large first-round exchange (~1.15x at 8 MiB). Hosts with different
# core counts / NICs tune via env.
DEFAULT_ALGO_SMALL_BYTES = 256 * 1024
DEFAULT_ALGO_LARGE_BYTES = 4 * 1024 * 1024
# Shared-memory transport defaults (ISSUE 7): ring sized to one
# pipeline chunk so a chunked exchange streams through without an
# intermediate wait in the common case.
DEFAULT_SHM_RING_BYTES = 1024 * 1024
# The raw-plane ring threshold (ISSUE 7, centralized here by ISSUE 15's
# R22 knob discipline): a raw transfer below this rides the shm pair's
# TCP carrier — the kernel's recv wakeup beats every user-space wait on
# an oversubscribed host (measured, see transport/shm.py) — and one at
# or above it streams through the ring in pieces. Part of the shm wire
# protocol: both ends derive the route from the same transfer size.
SHM_RING_MIN_BYTES = 256 * 1024
# Floor for ring capacity (the MP4J_SHM_RING_BYTES validator, the peer
# handshake's sanity check, and the piece-size clamp all share it): one
# frame header plus a compressed chunk length must always be
# ring-transitable.
SHM_RING_FLOOR = 4096
# Frame-level ring routing default (ISSUE 15): smaller than the raw
# plane's SHM_RING_MIN_BYTES because framed payloads (map value
# columns, compressed pieces) already paid the framing/serialize tax —
# the ring memcpy wins earlier there; the sync-byte wakeup still rides
# the carrier, so small frames keep the pure kernel path.
DEFAULT_SHM_FRAME_MIN = 64 * 1024
# Resilience defaults (ISSUE 5): recovery is ON by default — two
# epoch-fenced retry rounds per failed collective — because the fence
# itself is a flag check (~0 steady-state cost; the input-preservation
# copy is the only measurable term, see README "Fault tolerance").
# The dead-rank threshold is deliberately much larger than any
# per-collective timeout: declaring a slow rank dead is irreversible.
DEFAULT_MAX_RETRIES = 2
DEFAULT_RECONNECT_BACKOFF = 0.05
DEFAULT_DEAD_RANK_SECS = 120.0
# Telemetry defaults: a heartbeat is one ~300-byte control frame per
# rank per period (off the data plane entirely), and a span is one
# O(1) deque append — both default-on, both sized so the observability
# tax stays well under the <2% bench budget (ISSUE 3).
DEFAULT_HEARTBEAT_SECS = 0.5
DEFAULT_SPAN_RING = 65536
# Audit-plane defaults (ISSUE 8): digest-mode recording is default-on
# (one vectorized hash pass per collective input/output — the wire
# crc folds and heartbeat shipping only arm in verify/capture); the
# ring bounds postmortem/replay coverage at a fixed memory cost, like
# the span ring.
DEFAULT_AUDIT_MODE = "digest"
DEFAULT_AUDIT_RING = 1024
AUDIT_MODES = ("off", "digest", "verify", "capture")
# Durable-sink defaults (ISSUE 9): armed only when MP4J_SINK_DIR is
# set. 64 MiB per rank holds hours of span-level history at typical
# collective rates (one ~120 B span record per chunk/phase); the 1 s
# flush period bounds kill -9 telemetry loss to one interval while
# keeping the drain thread's duty cycle negligible.
DEFAULT_SINK_BYTES = 64 * 1024 * 1024
DEFAULT_SINK_FLUSH_SECS = 1.0
# Elastic-membership defaults (ISSUE 10): OFF by default — replacing
# or renumbering ranks is a semantic contract change the operator must
# opt into; the adoption deadline is generous (a spare only has to ack
# a control message, but a loaded host may schedule it late) while
# still far below MP4J_DEAD_RANK_SECS so a dead spare costs one
# deadline, not the whole recovery budget.
DEFAULT_ELASTIC_MODE = "off"
ELASTIC_MODES = ("off", "replace", "shrink", "grow")
DEFAULT_SPARES = 0
DEFAULT_ADOPT_SECS = 10.0
# Metrics-plane default (ISSUE 6): the window the master's rate ring
# covers. Heartbeats arrive every DEFAULT_HEARTBEAT_SECS, so 60 s keeps
# ~120 interval points per rank — enough for a stable GB/s readout,
# small enough that a stall shows within a minute.
DEFAULT_METRICS_WINDOW_SECS = 60.0

# Log-level ladder for the master's log sink (MP4J_LOG_LEVEL).
LOG_LEVELS = {"DEBUG": 10, "INFO": 20, "WARN": 30, "ERROR": 40}


def env_bytes(name: str, default: int, minimum: int = 1) -> int:
    """A byte-count knob from the environment, validated: an unset or
    empty var yields ``default``; anything else must parse as an int
    >= ``minimum`` (suffix-free; ``262144``, not ``256k``) or the
    caller's setup fails with a diagnosable Mp4jError instead of a
    mid-collective surprise."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        val = int(raw)
    except ValueError:
        raise Mp4jError(
            f"{name}={raw!r} is not an integer byte count") from None
    if val < minimum:
        raise Mp4jError(f"{name}={val} must be >= {minimum}")
    return val


def env_float(name: str, default: float, minimum: float = 0.0) -> float:
    """A float knob from the environment, validated like
    :func:`env_bytes`: unset/empty yields ``default``; anything else
    must parse as a float >= ``minimum`` or setup fails cleanly."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        val = float(raw)
    except ValueError:
        raise Mp4jError(f"{name}={raw!r} is not a number") from None
    if val < minimum:
        raise Mp4jError(f"{name}={val} must be >= {minimum}")
    return val


def env_int(name: str, default: int, minimum: int = 0) -> int:
    """A plain integer-count knob (retry budgets, not byte sizes) —
    same validation shape as :func:`env_bytes` with an honest
    diagnostic."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    try:
        val = int(raw)
    except ValueError:
        raise Mp4jError(
            f"{name}={raw!r} is not an integer") from None
    if val < minimum:
        raise Mp4jError(f"{name}={val} must be >= {minimum}")
    return val


def chunk_bytes() -> int:
    return env_bytes("MP4J_CHUNK_BYTES", DEFAULT_CHUNK_BYTES, minimum=64)


def heartbeat_secs() -> float:
    """Slave->master telemetry heartbeat period; 0 disables."""
    return env_float("MP4J_HEARTBEAT_SECS", DEFAULT_HEARTBEAT_SECS,
                     minimum=0.0)


def span_ring_capacity() -> int:
    """Capacity of the in-process span ring (obs.spans); 0 disables."""
    return env_bytes("MP4J_SPAN_RING", DEFAULT_SPAN_RING, minimum=0)


def log_level() -> str:
    """The master log sink's minimum level (``MP4J_LOG_LEVEL``),
    validated against :data:`LOG_LEVELS` — a typo'd level fails master
    setup cleanly instead of silently printing everything."""
    raw = os.environ.get("MP4J_LOG_LEVEL")
    if raw is None or raw.strip() == "":
        return "INFO"
    name = raw.strip().upper()
    if name not in LOG_LEVELS:
        raise Mp4jError(
            f"MP4J_LOG_LEVEL={raw!r} is not one of "
            f"{sorted(LOG_LEVELS)}")
    return name


def shm_enabled() -> bool:
    """Whether rendezvous may negotiate the shared-memory transport for
    same-host peer pairs (``MP4J_SHM``). JOB-wide like
    ``native_transport``: the dialer offers shm in the peer handshake
    and the accepter attaches, so every rank must run with the same
    value or a pair could disagree about its data plane."""
    raw = os.environ.get("MP4J_SHM")
    if raw is None or raw.strip() == "":
        return True
    val = raw.strip()
    if val not in ("0", "1"):
        raise Mp4jError(f"MP4J_SHM={raw!r} must be 0 or 1")
    return val == "1"


def shm_ring_bytes() -> int:
    """Bytes per direction of each shm peer pair's ring
    (``MP4J_SHM_RING_BYTES``). Since ISSUE 15 the rings carry the
    framed/columnar-map plane too (see :func:`shm_frame_min`), not
    just raw transfers. The floor (:data:`SHM_RING_FLOOR`) keeps one
    frame header plus a compressed chunk length always
    ring-transitable."""
    return env_bytes("MP4J_SHM_RING_BYTES", DEFAULT_SHM_RING_BYTES,
                     minimum=SHM_RING_FLOOR)


def shm_frame_min() -> int:
    """Frame-level ring routing threshold (``MP4J_SHM_FRAME_MIN``,
    ISSUE 15): a framed payload whose length — carried by the frame
    header / chunk length prefix, so both ends know it BEFORE any
    payload byte moves — clears this value rides the shm ring; ``0``
    disables frame routing (all framed bytes keep the TCP carrier,
    the pre-ISSUE-15 wire layout). JOB-wide like ``native_transport``:
    the threshold is part of the shm pair's wire protocol."""
    return env_bytes("MP4J_SHM_FRAME_MIN", DEFAULT_SHM_FRAME_MIN,
                     minimum=0)


def map_columnar_enabled() -> bool:
    """Whether numeric-operand socket map collectives default to the
    columnar (codes, values) wire plane (``MP4J_MAP_COLUMNAR``).
    JOB-wide, exactly like ``native_transport``: both ends of every
    exchange must agree on the plane, so every rank of a job must run
    with the same value (the per-call negotiation header then handles
    data-dependent fallback consistently)."""
    raw = os.environ.get("MP4J_MAP_COLUMNAR")
    if raw is None or raw.strip() == "":
        return True
    val = raw.strip()
    if val not in ("0", "1"):
        raise Mp4jError(
            f"MP4J_MAP_COLUMNAR={raw!r} must be 0 or 1")
    return val == "1"


def max_retries() -> int:
    """Epoch-fenced retry budget per failed collective
    (``MP4J_MAX_RETRIES``); 0 restores the reference's fail-stop."""
    return env_int("MP4J_MAX_RETRIES", DEFAULT_MAX_RETRIES, minimum=0)


def reconnect_backoff() -> float:
    """Base seconds of the capped exponential re-dial backoff
    (``MP4J_RECONNECT_BACKOFF``)."""
    return env_float("MP4J_RECONNECT_BACKOFF", DEFAULT_RECONNECT_BACKOFF,
                     minimum=0.0)


def dead_rank_secs(override=None) -> float:
    """Seconds of silence (missing abort ack / stalled barrier) before
    the master declares a rank dead and fans out a terminal abort
    (``MP4J_DEAD_RANK_SECS``); must be positive — a zero threshold
    would declare every rank dead at the first tick (master) and
    expire every recovery deadline instantly (slave). ``override`` is
    an explicit constructor arg taking the SAME validation as the env
    path, so master- and slave-side acceptance can never diverge;
    ``float('inf')`` is the documented disable idiom."""
    if override is None:
        return env_float("MP4J_DEAD_RANK_SECS", DEFAULT_DEAD_RANK_SECS,
                         minimum=0.001)
    val = float(override)
    if not val > 0:
        raise Mp4jError(
            f"dead_rank_secs={override} must be > 0 "
            f"(use float('inf') to disable the escalation)")
    return val


def metrics_enabled() -> bool:
    """Whether the metrics plane records (``MP4J_METRICS``): latency /
    frame-size histograms plus the heartbeat's metric deltas. Default
    on — recording is a lock + two integer bumps per event; ``0`` is
    the bench's A/B knob, turning every observe into a no-op."""
    raw = os.environ.get("MP4J_METRICS")
    if raw is None or raw.strip() == "":
        return True
    val = raw.strip()
    if val not in ("0", "1"):
        raise Mp4jError(f"MP4J_METRICS={raw!r} must be 0 or 1")
    return val == "1"


def metrics_port(override=None) -> int | None:
    """The master's HTTP metrics endpoint port (``MP4J_METRICS_PORT``).
    ``None`` (unset/empty) disables the endpoint; ``0`` binds an
    ephemeral port (read ``Master.metrics_port`` for the real one);
    otherwise must be a valid TCP port. ``override`` is the explicit
    ``Master(metrics_port=...)`` constructor value — it bypasses the
    env read but gets the SAME validation (one validator per knob, the
    PR 5 discipline), so a typo'd port raises a clean ``Mp4jError``
    instead of a raw socket OverflowError at bind time."""
    if override is not None:
        raw = str(override)
    else:
        raw = os.environ.get("MP4J_METRICS_PORT")
        if raw is None or raw.strip() == "":
            return None
    try:
        val = int(raw)
    except (TypeError, ValueError):
        raise Mp4jError(
            f"MP4J_METRICS_PORT={raw!r} is not an integer port") from None
    if not 0 <= val <= 65535:
        raise Mp4jError(
            f"MP4J_METRICS_PORT={val} outside [0, 65535]")
    return val


def metrics_window_secs() -> float:
    """Sliding window (seconds) for the master's derived rates
    (``MP4J_METRICS_WINDOW_SECS``); must be positive — a zero window
    can never hold two interval snapshots, so every rate would read
    0."""
    return env_float("MP4J_METRICS_WINDOW_SECS",
                     DEFAULT_METRICS_WINDOW_SECS, minimum=0.001)


def postmortem_dir() -> str:
    """The flight-recorder directory (``MP4J_POSTMORTEM_DIR``); empty
    disables the recorder. Validated lightly here (it must not name an
    existing regular file — every rank is about to mkdir under it);
    creation happens lazily at dump time."""
    raw = os.environ.get("MP4J_POSTMORTEM_DIR", "").strip()
    if raw and os.path.isfile(raw):
        raise Mp4jError(
            f"MP4J_POSTMORTEM_DIR={raw!r} names an existing regular "
            "file, not a directory")
    return raw


def audit_mode(override=None) -> str:
    """The audit plane's mode (``MP4J_AUDIT``): one of
    :data:`AUDIT_MODES`. ``override`` is the explicit constructor arg
    (``ProcessCommSlave(audit=...)``) — it bypasses the env read but
    gets the SAME validation (one validator per knob, the PR 5
    discipline). JOB-wide: every rank must run the same mode or
    cross-rank digest comparison would flag healthy seqs."""
    if override is not None:
        raw = str(override)
    else:
        raw = os.environ.get("MP4J_AUDIT")
        if raw is None or raw.strip() == "":
            return DEFAULT_AUDIT_MODE
    name = raw.strip().lower()
    if name not in AUDIT_MODES:
        raise Mp4jError(
            f"MP4J_AUDIT={raw!r} is not one of {list(AUDIT_MODES)}")
    return name


def audit_ring() -> int:
    """Capacity (records) of the per-rank audit record ring
    (``MP4J_AUDIT_RING``); must be >= 1 — disabling the plane is
    ``MP4J_AUDIT=off``, not a zero ring."""
    return env_int("MP4J_AUDIT_RING", DEFAULT_AUDIT_RING, minimum=1)


def sink_enabled() -> bool:
    """Whether the durable telemetry sink may arm (``MP4J_SINK``).
    ``on``/``1`` (default) lets a set ``MP4J_SINK_DIR`` arm it;
    ``off``/``0`` pins it off regardless of the dir — the bench A/B
    knob, mirroring the shm/audit frozen-leg precedent."""
    raw = os.environ.get("MP4J_SINK")
    if raw is None or raw.strip() == "":
        return True
    val = raw.strip().lower()
    if val not in ("on", "off", "0", "1"):
        raise Mp4jError(
            f"MP4J_SINK={raw!r} must be one of on/off/0/1")
    return val in ("on", "1")


def sink_dir() -> str:
    """The durable sink's root directory (``MP4J_SINK_DIR``); empty
    disables the sink. Validated like ``MP4J_POSTMORTEM_DIR`` (must
    not name an existing regular file — every rank mkdirs under it);
    creation happens lazily at the first drain."""
    raw = os.environ.get("MP4J_SINK_DIR", "").strip()
    if raw and os.path.isfile(raw):
        raise Mp4jError(
            f"MP4J_SINK_DIR={raw!r} names an existing regular file, "
            "not a directory")
    return raw


def sink_bytes() -> int:
    """PER-RANK disk budget for sink segments (``MP4J_SINK_BYTES``).
    The floor keeps at least two rotatable segments alive — eviction
    removes whole segments and must never have to evict the one being
    written."""
    return env_bytes("MP4J_SINK_BYTES", DEFAULT_SINK_BYTES,
                     minimum=128 * 1024)


def sink_flush_secs() -> float:
    """Background drain period of the durable sink
    (``MP4J_SINK_FLUSH_SECS``); must be positive — the sink is
    disabled by unsetting ``MP4J_SINK_DIR`` (or ``MP4J_SINK=off``),
    not by a zero period."""
    return env_float("MP4J_SINK_FLUSH_SECS", DEFAULT_SINK_FLUSH_SECS,
                     minimum=0.01)


def elastic_mode(override=None, max_retries=None) -> str:
    """The elastic-membership mode (``MP4J_ELASTIC``): one of
    :data:`ELASTIC_MODES`. ``override`` is the explicit constructor arg
    (``Master(elastic=...)`` / ``ProcessCommSlave(elastic=...)``) — it
    bypasses the env read but gets the SAME validation (one validator
    per knob, the PR 5 discipline). JOB-wide: the master drives the
    membership protocol, but every slave validates the same value so a
    misconfigured rank fails at setup, not mid-recovery.

    CONFLICT RULE (ISSUE 10 bugfix guard): ``MP4J_MAX_RETRIES=0`` is
    the exact fail-stop reference contract — the first transport error
    is final and no abort round ever runs — while both elastic modes
    NEED the fenced retry to re-run the interrupted collective after a
    membership change. An elastic mode next to a zero retry budget is
    therefore a contradiction, and it raises here as a validated-knob
    error instead of one knob silently winning. ``max_retries`` is the
    caller's explicit budget (None reads ``MP4J_MAX_RETRIES``)."""
    if override is not None:
        raw = str(override)
    else:
        raw = os.environ.get("MP4J_ELASTIC")
        if raw is None or raw.strip() == "":
            raw = DEFAULT_ELASTIC_MODE
    name = raw.strip().lower()
    if name not in ELASTIC_MODES:
        raise Mp4jError(
            f"MP4J_ELASTIC={raw!r} is not one of {list(ELASTIC_MODES)}")
    if name != "off":
        budget = (max_retries if max_retries is not None
                  else env_int("MP4J_MAX_RETRIES", DEFAULT_MAX_RETRIES,
                               minimum=0))
        if budget == 0:
            raise Mp4jError(
                f"MP4J_ELASTIC={name} conflicts with MP4J_MAX_RETRIES=0: "
                "fail-stop mode disables the epoch-fenced retry that "
                "elastic membership re-runs the interrupted collective "
                "through; set MP4J_MAX_RETRIES>=1 or MP4J_ELASTIC=off")
    return name


def spares(override=None) -> int:
    """How many warm-spare registrations rendezvous waits for before
    the job starts (``MP4J_SPARES``); spares may also register mid-job.
    ``override`` is the explicit ``Master(spares=...)`` value, same
    validation as the env path."""
    if override is None:
        return env_int("MP4J_SPARES", DEFAULT_SPARES, minimum=0)
    val = int(override)
    if val < 0:
        raise Mp4jError(f"spares={override} must be >= 0")
    return val


def adopt_secs(override=None) -> float:
    """The spare-adoption deadline (``MP4J_ADOPT_SECS``): how long the
    master waits for an adopted spare's ack before trying the next
    spare; must be positive (a zero deadline would burn the whole pool
    before any spare could answer)."""
    if override is None:
        return env_float("MP4J_ADOPT_SECS", DEFAULT_ADOPT_SECS,
                         minimum=0.001)
    val = float(override)
    if not val > 0:
        raise Mp4jError(f"adopt_secs={override} must be > 0")
    return val


# Nonblocking-collective defaults (ISSUE 11): the scheduler is ON by
# default (a job that never calls i* pays nothing — the progression
# thread starts lazily); coalescing is opt-in (it changes the map wire
# protocol job-wide, so the default must be the classic plane); the
# outstanding cap bounds snapshot memory (each outstanding collective
# may hold one payload-sized retry snapshot).
DEFAULT_MAX_OUTSTANDING = 64


def async_enabled() -> bool:
    """Whether ``i*`` submissions run on the helper progression thread
    (``MP4J_ASYNC``); ``0`` = eager caller-thread execution returning
    resolved futures (the bench A/B knob). Local execution strategy —
    wire-identical either way."""
    raw = os.environ.get("MP4J_ASYNC")
    if raw is None or raw.strip() == "":
        return True
    val = raw.strip()
    if val not in ("0", "1"):
        raise Mp4jError(f"MP4J_ASYNC={raw!r} must be 0 or 1")
    return val == "1"


def coalesce_usecs() -> int:
    """The small-message coalescing window in MICROseconds
    (``MP4J_COALESCE_USECS``); 0 disables fusion. JOB-wide: selects
    between the classic and the count-negotiating multi map protocol,
    so every rank must agree."""
    return env_int("MP4J_COALESCE_USECS", 0, minimum=0)


def overlap_enabled() -> bool:
    """Whether the trainer epoch loops overlap each step's host
    statistics exchange with the next step's compute
    (``MP4J_OVERLAP``); ``0``/unset keeps the blocking per-step
    exchange. Local wait-point strategy — wire-identical either
    way (submit order == collective order on every rank)."""
    raw = os.environ.get("MP4J_OVERLAP")
    if raw is None or raw.strip() == "":
        return False
    val = raw.strip()
    if val not in ("0", "1"):
        raise Mp4jError(f"MP4J_OVERLAP={raw!r} must be 0 or 1")
    return val == "1"


def max_outstanding() -> int:
    """Outstanding-collective cap per slave (``MP4J_MAX_OUTSTANDING``);
    submission blocks past it. Must be >= 1 — disabling async is
    ``MP4J_ASYNC=0``, not a zero window."""
    return env_int("MP4J_MAX_OUTSTANDING", DEFAULT_MAX_OUTSTANDING,
                   minimum=1)


# Health-plane defaults (ISSUE 12): default-on like the metrics plane
# (the slave side is one span-ring delta fold per heartbeat, the
# master side a handful of dict updates per beat). The dominator
# eviction threshold is the ROADMAP's verbatim contract; the drift
# threshold is one full log2 histogram bucket (2x) so scheduler noise
# on microsecond collectives never reads as degradation.
DEFAULT_HEALTH_WINDOW = 64
DEFAULT_HEALTH_DOMINATOR_ORDINALS = 500
DEFAULT_HEALTH_DRIFT_PCT = 100.0


def health_enabled(override=None) -> bool:
    """Whether the streaming health plane runs (``MP4J_HEALTH``).
    ``override`` is the explicit constructor arg
    (``Master(health=...)`` / ``ProcessCommSlave(health=...)``) — it
    bypasses the env read but gets the SAME validation (one validator
    per knob, the PR 5 discipline). JOB-wide in practice: a slave with
    it off simply never ships health deltas, so its dominator cells
    are missing and the master attributes nothing — run every rank
    with the same value."""
    if override is not None:
        return bool(override)
    raw = os.environ.get("MP4J_HEALTH")
    if raw is None or raw.strip() == "":
        return True
    val = raw.strip().lower()
    if val not in ("on", "off", "0", "1"):
        raise Mp4jError(
            f"MP4J_HEALTH={raw!r} must be one of on/off/0/1")
    return val in ("on", "1")


def health_window() -> int:
    """Sliding window, in attributed collective ordinals, for the
    online dominator's dominance shares (``MP4J_HEALTH_WINDOW``)."""
    return env_int("MP4J_HEALTH_WINDOW", DEFAULT_HEALTH_WINDOW,
                   minimum=4)


def health_dominator_ordinals() -> int:
    """Consecutive slow dominated ordinals before the engine
    recommends eviction (``MP4J_HEALTH_DOMINATOR_ORDINALS``); SUSPECT
    is forced at half this streak. Must be >= 2 — a single ordinal is
    noise, not a verdict."""
    return env_int("MP4J_HEALTH_DOMINATOR_ORDINALS",
                   DEFAULT_HEALTH_DOMINATOR_ORDINALS, minimum=2)


def health_drift_pct() -> float:
    """Percent above a rank's own latency baseline before the drift
    detector fires (``MP4J_HEALTH_DRIFT_PCT``); must be positive —
    disabling the plane is ``MP4J_HEALTH=0``, not a zero threshold."""
    return env_float("MP4J_HEALTH_DRIFT_PCT", DEFAULT_HEALTH_DRIFT_PCT,
                     minimum=1.0)


# Autoscaler defaults (ISSUE 13): OFF by default — acting on health
# verdicts is an operator opt-in on top of the elastic machinery. The
# cooldown is deliberately long relative to the health plane's
# detection latency (one action per verdict trend, never per fold);
# the budget bounds a flapping controller's lifetime damage.
AUTOSCALE_MODES = ("off", "observe", "act")
DEFAULT_AUTOSCALE_MODE = "off"
DEFAULT_AUTOSCALE_COOLDOWN_SECS = 30.0
DEFAULT_AUTOSCALE_BUDGET = 16


def autoscale_mode(override=None) -> str:
    """The autoscaler's mode (``MP4J_AUTOSCALE``): one of
    :data:`AUTOSCALE_MODES`. ``override`` is the explicit
    ``Master(autoscale=...)`` constructor value — same validation as
    the env path (one validator per knob, the PR 5 discipline).
    Master-side only: slaves never read it."""
    if override is not None:
        raw = str(override)
    else:
        raw = os.environ.get("MP4J_AUTOSCALE")
        if raw is None or raw.strip() == "":
            return DEFAULT_AUTOSCALE_MODE
    name = raw.strip().lower()
    if name not in AUTOSCALE_MODES:
        raise Mp4jError(
            f"MP4J_AUTOSCALE={raw!r} is not one of "
            f"{list(AUTOSCALE_MODES)}")
    return name


def autoscale_cooldown_secs(override=None) -> float:
    """Minimum seconds between two autoscaler actions of one kind
    (``MP4J_AUTOSCALE_COOLDOWN_SECS``); >= 0 (0 is legal for tests —
    the budget and the one-action-in-flight rule still bound the
    controller)."""
    if override is None:
        return env_float("MP4J_AUTOSCALE_COOLDOWN_SECS",
                         DEFAULT_AUTOSCALE_COOLDOWN_SECS, minimum=0.0)
    val = float(override)
    if val < 0:
        raise Mp4jError(
            f"autoscale_cooldown={override} must be >= 0")
    return val


def autoscale_budget(override=None) -> int:
    """Job-lifetime autoscaler action cap (``MP4J_AUTOSCALE_BUDGET``);
    must be >= 1 — disabling the controller is ``MP4J_AUTOSCALE=off``,
    not a zero budget."""
    if override is None:
        return env_int("MP4J_AUTOSCALE_BUDGET",
                       DEFAULT_AUTOSCALE_BUDGET, minimum=1)
    val = int(override)
    if val < 1:
        raise Mp4jError(f"autoscale_budget={override} must be >= 1")
    return val


def provision_cmd() -> str:
    """The operator's spare-provisioning shell command
    (``MP4J_PROVISION_CMD``; '' disables the subprocess hook). Run by
    the master with ``MP4J_MASTER_HOST``/``MP4J_MASTER_PORT`` in the
    environment when the warm-spare pool drains to zero under
    ``MP4J_AUTOSCALE=act``."""
    return os.environ.get("MP4J_PROVISION_CMD", "").strip()


# Self-tuning data plane defaults (ISSUE 15): OBSERVE by default — the
# policy core runs and its would-be decisions are visible everywhere
# (telemetry, `mp4j-scope tuner`), but nothing changes until the
# operator opts into `act`; the window paces evidence collection (a
# decision needs SUSTAIN_WINDOWS consecutive agreeing windows, so the
# reaction time is window * sustain, deliberately slower than any
# single noisy interval).
TUNER_MODES = ("off", "observe", "act")
DEFAULT_TUNER_MODE = "observe"
DEFAULT_TUNER_WINDOW_SECS = 2.0


def tuner_mode(override=None) -> str:
    """The self-tuning data plane's mode (``MP4J_TUNER``): one of
    :data:`TUNER_MODES`. ``override`` is the explicit constructor arg
    (``ProcessCommSlave(tuner=...)`` / ``Master(tuner=...)``) — it
    bypasses the env read but gets the SAME validation (one validator
    per knob, the PR 5 discipline)."""
    if override is not None:
        raw = str(override)
    else:
        raw = os.environ.get("MP4J_TUNER")
        if raw is None or raw.strip() == "":
            return DEFAULT_TUNER_MODE
    name = raw.strip().lower()
    if name not in TUNER_MODES:
        raise Mp4jError(
            f"MP4J_TUNER={raw!r} is not one of {list(TUNER_MODES)}")
    return name


def tuner_window_secs() -> float:
    """The tuner's decision-window period
    (``MP4J_TUNER_WINDOW_SECS``); must be positive — disabling the
    tuner is ``MP4J_TUNER=off``, not a zero window."""
    return env_float("MP4J_TUNER_WINDOW_SECS",
                     DEFAULT_TUNER_WINDOW_SECS, minimum=0.05)


# Serve-plane defaults (ISSUE 19): the micro-batcher holds the first
# request of a batch at most DEADLINE_MS before dispatching whatever
# has accumulated (tail latency bound), and never accumulates past
# MAX_BATCH (queueing bound). The cache rows/staleness knobs bound the
# frontend's hot-key row cache: CACHE_ROWS caps resident rows (LRU),
# STALE_VERSIONS is the published staleness bound — a cached row may
# lag the live table by at most that many model-version bumps before a
# lookup treats it as a miss. The load-following thresholds feed the
# autoscaler's observe-first serve policy (idle QPS below IDLE_QPS for
# IDLE_SECS proposes a shrink; QPS above BUSY_QPS proposes a grow).
DEFAULT_SERVE_DEADLINE_MS = 2.0
DEFAULT_SERVE_MAX_BATCH = 32
DEFAULT_SERVE_CACHE_ROWS = 100_000
DEFAULT_SERVE_STALE_VERSIONS = 0
DEFAULT_SERVE_IDLE_QPS = 1.0
DEFAULT_SERVE_BUSY_QPS = 1000.0
DEFAULT_SERVE_IDLE_SECS = 60.0


def serve_deadline_ms(override=None) -> float:
    """Micro-batch accumulation deadline in milliseconds
    (``MP4J_SERVE_DEADLINE_MS``): the longest the batcher may hold the
    OLDEST queued request before dispatching a partial batch. Must be
    positive — a zero deadline is the unbatched loop, spelled
    ``MP4J_SERVE_MAX_BATCH=1``. ``override`` is the explicit
    constructor value (``MicroBatcher(deadline_ms=...)``) — it bypasses
    the env read but gets the same validation."""
    if override is None:
        return env_float("MP4J_SERVE_DEADLINE_MS",
                         DEFAULT_SERVE_DEADLINE_MS, minimum=0.01)
    val = float(override)
    if val <= 0:
        raise Mp4jError(
            f"serve deadline_ms={override} must be positive")
    return val


def serve_max_batch(override=None) -> int:
    """Micro-batch size cap (``MP4J_SERVE_MAX_BATCH``): a full batch
    dispatches immediately without waiting out the deadline. ``1``
    IS the unbatched reference loop (the bench A/B arm)."""
    if override is None:
        return env_int("MP4J_SERVE_MAX_BATCH",
                       DEFAULT_SERVE_MAX_BATCH, minimum=1)
    val = int(override)
    if val < 1:
        raise Mp4jError(f"serve max_batch={override} must be >= 1")
    return val


def serve_cache_rows(override=None) -> int:
    """Hot-key row cache capacity in ROWS (``MP4J_SERVE_CACHE_ROWS``);
    ``0`` disables the cache (every request pulls its rows — the bench
    A/B knob for the cache figure)."""
    if override is None:
        return env_int("MP4J_SERVE_CACHE_ROWS",
                       DEFAULT_SERVE_CACHE_ROWS, minimum=0)
    val = int(override)
    if val < 0:
        raise Mp4jError(f"serve cache_rows={override} must be >= 0")
    return val


def serve_stale_versions(override=None) -> int:
    """The cache's published staleness bound
    (``MP4J_SERVE_STALE_VERSIONS``): a cached row whose stamp lags the
    live model version by MORE than this many bumps is treated as a
    miss (and counted ``serve/cache_stale``). ``0`` (default) means a
    version bump invalidates everything cached under older stamps."""
    if override is None:
        return env_int("MP4J_SERVE_STALE_VERSIONS",
                       DEFAULT_SERVE_STALE_VERSIONS, minimum=0)
    val = int(override)
    if val < 0:
        raise Mp4jError(
            f"serve stale_versions={override} must be >= 0")
    return val


def serve_idle_qps() -> float:
    """Load-following shrink threshold (``MP4J_SERVE_IDLE_QPS``):
    sustained serve QPS below this proposes releasing a serve rank
    (observe mode first — ISSUE 19)."""
    return env_float("MP4J_SERVE_IDLE_QPS", DEFAULT_SERVE_IDLE_QPS,
                     minimum=0.0)


def serve_busy_qps() -> float:
    """Load-following grow threshold (``MP4J_SERVE_BUSY_QPS``): serve
    QPS at or above this proposes growing the roster at the next
    ``resize_point()``. Must exceed the idle threshold — a crossed
    pair would flap."""
    idle = serve_idle_qps()
    val = env_float("MP4J_SERVE_BUSY_QPS", DEFAULT_SERVE_BUSY_QPS,
                    minimum=0.0)
    if val <= idle:
        raise Mp4jError(
            f"MP4J_SERVE_BUSY_QPS={val} must exceed "
            f"MP4J_SERVE_IDLE_QPS={idle}")
    return val


def serve_idle_secs() -> float:
    """How long serve QPS must stay below the idle threshold before
    the shrink proposal fires (``MP4J_SERVE_IDLE_SECS``) — sustained
    idleness, not one quiet window."""
    return env_float("MP4J_SERVE_IDLE_SECS", DEFAULT_SERVE_IDLE_SECS,
                     minimum=0.0)


def so_buf_map() -> dict[int, tuple[int, int]]:
    """Explicit per-link socket buffer overrides (``MP4J_SO_BUF_MAP``,
    ISSUE 15 satellite): ``"peer:sndbuf[/rcvbuf],..."`` parsed into
    ``{peer_rank: (sndbuf, rcvbuf)}`` (one size applies to both
    directions when no ``/rcvbuf`` is given). Validated here like
    every other knob — a malformed entry fails slave setup with the
    offending token named, never a mid-dial surprise."""
    raw = os.environ.get("MP4J_SO_BUF_MAP", "").strip()
    out: dict[int, tuple[int, int]] = {}
    if not raw:
        return out
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        try:
            rank_s, sizes = tok.split(":", 1)
            rank = int(rank_s)
            if "/" in sizes:
                snd_s, rcv_s = sizes.split("/", 1)
                snd, rcv = int(snd_s), int(rcv_s)
            else:
                snd = rcv = int(sizes)
        except ValueError:
            raise Mp4jError(
                f"MP4J_SO_BUF_MAP entry {tok!r} is not "
                "'peer:sndbuf[/rcvbuf]'") from None
        if rank < 0 or snd < 0 or rcv < 0:
            raise Mp4jError(
                f"MP4J_SO_BUF_MAP entry {tok!r} has a negative value")
        out[rank] = (snd, rcv)
    return out


# -- fleet observability (ISSUE 18: mp4j-fleet) ------------------------
# The cross-job fleet poller (obs/fleet.py) scrapes N job masters'
# /metrics.json + /health.json control surfaces on a cadence. These
# knobs configure the SCRAPER, not the jobs: they live on the machine
# running `mp4j-scope fleet`, so unlike the transport knobs above they
# carry no job-wide-agreement requirement.
DEFAULT_FLEET_POLL_SECS = 2.0
DEFAULT_FLEET_STALE_SECS = 10.0


def fleet_poll_secs() -> float:
    """Fleet poller sweep period (``MP4J_FLEET_POLL_SECS``); must be
    positive — the poller is stopped by exiting it, not by a zero
    period."""
    return env_float("MP4J_FLEET_POLL_SECS", DEFAULT_FLEET_POLL_SECS,
                     minimum=0.05)


def fleet_stale_secs() -> float:
    """Seconds without a successful scrape before a job's fleet state
    degrades ``LIVE -> STALE`` (``MP4J_FLEET_STALE_SECS``); ``GONE``
    follows at 3x this bound (obs.fleet.GONE_FACTOR). Must exceed the
    poll period in practice or every job flaps STALE between sweeps —
    the floor only guards nonsense values."""
    return env_float("MP4J_FLEET_STALE_SECS", DEFAULT_FLEET_STALE_SECS,
                     minimum=0.1)


def fleet_sink_dir() -> str:
    """The fleet poller's durable history directory
    (``MP4J_FLEET_SINK_DIR``); empty disables the fleet sink.
    Validated like ``MP4J_SINK_DIR`` (must not name an existing
    regular file); creation happens lazily at the first append."""
    raw = os.environ.get("MP4J_FLEET_SINK_DIR", "").strip()
    if raw and os.path.isfile(raw):
        raise Mp4jError(
            f"MP4J_FLEET_SINK_DIR={raw!r} names an existing regular "
            "file, not a directory")
    return raw


def fault_plan_spec() -> str:
    """The raw ``MP4J_FAULT_PLAN`` grammar string ('' disables
    injection); parsed and validated by
    :func:`ytk_mp4j_tpu.resilience.faults.FaultPlan.parse`."""
    return os.environ.get("MP4J_FAULT_PLAN", "").strip()


def algo_thresholds() -> tuple[int, int]:
    """(small, large) byte thresholds for ``algo="auto"``; validated
    jointly: small must not exceed large or the medium regime would be
    empty in a surprising order-dependent way."""
    small = env_bytes("MP4J_ALGO_SMALL_BYTES", DEFAULT_ALGO_SMALL_BYTES,
                      minimum=0)
    large = env_bytes("MP4J_ALGO_LARGE_BYTES", DEFAULT_ALGO_LARGE_BYTES,
                      minimum=0)
    if small > large:
        raise Mp4jError(
            f"MP4J_ALGO_SMALL_BYTES={small} exceeds "
            f"MP4J_ALGO_LARGE_BYTES={large}")
    return small, large


def select_allreduce_algo(nbytes: int, n: int, small: int,
                          large: int) -> str:
    """The ``algo="auto"`` rule for allreduce: binomial tree for
    latency-bound small payloads, recursive halving/doubling for the
    middle, pipelined ring for bandwidth-bound large payloads. A pure
    function of (payload bytes, rank count, thresholds) — never of any
    rank-local state."""
    if n <= 2:
        # at n=2 RHD degenerates to the single optimal pairwise
        # exchange; tree/ring only add rounds
        return "rhd"
    if nbytes <= small:
        return "tree"
    if nbytes >= large:
        return "ring"
    return "rhd"


def select_twolevel(host_sizes: list[int]) -> bool:
    """Whether ``algo="auto"`` should take the topology-aware two-level
    schedule (intra-host reduce over shm -> one inter-host exchange per
    host leader -> intra-host broadcast): true exactly when there are
    MULTIPLE hosts and at least one host co-locates ranks — otherwise
    the flat schedule is already optimal (single host: every pair rides
    shm anyway; one rank per host: there is no intra level). A pure
    function of the roster-derived host grouping (identical on every
    rank — mp4j-lint R1/R8 discipline)."""
    return len(host_sizes) > 1 and any(s > 1 for s in host_sizes)


def select_partitioned_algo(nbytes: int, n: int, small: int,
                            large: int) -> str:
    """``algo="auto"`` for reduce_scatter / allgather: rooted binomial
    tree composition below the latency threshold, ring otherwise (the
    ring is both the medium and large choice — it is bandwidth-optimal
    and these collectives have no halving/doubling variant)."""
    if nbytes <= small and n > 2:
        return "tree"
    return "ring"


def chunk_ranges(total: int, itemsize: int,
                 chunk_bytes_: int) -> list[tuple[int, int]]:
    """Element ranges ``[(s, e), ...]`` splitting ``total`` elements
    into pipeline chunks of ~``chunk_bytes_`` bytes. Pure function of
    its arguments (mp4j-lint R8: a chunk schedule must never depend on
    rank-local state). ``total == 0`` yields no chunks."""
    if total <= 0:
        return []
    per = max(1, chunk_bytes_ // max(1, itemsize))
    return [(s, min(s + per, total)) for s in range(0, total, per)]
