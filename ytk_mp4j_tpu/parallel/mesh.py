"""Mesh construction helpers.

The reference's "cluster shape" is (process count x thread count)
(SURVEY.md section 2: two-level process x thread data parallelism). The
TPU-native analogue is a :class:`jax.sharding.Mesh` with one axis for flat
collectives or two axes (``inter`` x ``intra``) for the hierarchical path,
where ``intra`` maps to ICI within a slice and ``inter`` to DCN across
slices/hosts.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ytk_mp4j_tpu.exceptions import Mp4jError

DEFAULT_AXIS = "mp4j"
INTER_AXIS = "inter"  # across slices / hosts (DCN-like)
INTRA_AXIS = "intra"  # within a slice (ICI-like)


def device_count() -> int:
    return jax.device_count()


def make_mesh(n: int | None = None, axis_name: str = DEFAULT_AXIS,
              devices=None) -> Mesh:
    """A 1-D mesh over ``n`` devices (default: all available).

    ``n`` may be any value <= device_count, including non-powers-of-2 —
    the reference supports non-power-of-2 slave counts (SURVEY.md section
    3b step 4) and so do we, by meshing a device subset.
    """
    if devices is None:
        devices = jax.devices()
    if n is None:
        n = len(devices)
    if n < 1 or n > len(devices):
        raise Mp4jError(f"cannot build mesh of {n} from {len(devices)} devices")
    return Mesh(np.asarray(devices[:n]), (axis_name,))


def make_hier_mesh(inter: int, intra: int,
                   axis_names: tuple[str, str] = (INTER_AXIS, INTRA_AXIS),
                   devices=None) -> Mesh:
    """A 2-D (inter x intra) mesh mirroring the reference's
    process x thread nesting (SURVEY.md section 3d)."""
    if devices is None:
        devices = jax.devices()
    need = inter * intra
    if need < 1 or need > len(devices):
        raise Mp4jError(
            f"cannot build {inter}x{intra} mesh from {len(devices)} devices")
    arr = np.asarray(devices[:need]).reshape(inter, intra)
    return Mesh(arr, axis_names)
