from ytk_mp4j_tpu.parallel.mesh import (
    make_mesh,
    make_hier_mesh,
    device_count,
)

__all__ = ["make_mesh", "make_hier_mesh", "device_count"]
