"""Multi-host backend — the DCN-scale rendezvous and host-level slave.

In the reference, scaling past one machine means pointing every slave
JVM at the master's host:port (SURVEY.md section 3a). The TPU-native
analogue of that rendezvous is ``jax.distributed.initialize``: the
coordinator assigns process indices (ranks) and wires up the PJRT
distributed runtime, after which XLA collectives ride ICI within a slice
and DCN across hosts.

Two layers are exposed here:

- :func:`init_distributed` + :class:`DistributedComm` — a host-level
  slave mirroring the ``ProcessCommSlave`` API (rank / slave_num /
  barrier / info / close + the 7 collectives x {array, map}) where each
  RANK IS A PROCESS (host). Dense reduce/allreduce/reduce-scatter with
  the built-in SUM/MAX/MIN ride ONE device collective (psum / pmax /
  pmin / psum_scatter over a one-device-per-process mesh — 2L(n-1)/n
  wire bytes); PROD, custom operators, and the gather family use
  ``multihost_utils`` allgather. Numeric map operands ride the device
  plane too (round 4): key<->code vocabularies are kept identical on
  every process — only NOVEL keys ride a small pickled exchange, near
  empty once a gradient stream's vocabulary stabilizes — and the
  values travel as one device sparse allreduce; object values (and
  64-bit without x64) fall back to the pickled whole-map exchange
  (the Kryo analogue at DCN scale).
- :func:`global_mesh` / :func:`hier_global_mesh` — mesh builders over
  ALL processes' devices for the perf path: user jit code with
  ``shard_map`` + ``ops.collectives`` (and the model families) runs
  unchanged on a global mesh; XLA stages psum across ICI then DCN
  exactly like the reference's thread-then-process nesting (SURVEY.md
  section 3d).

Single-process fallback: constructing :class:`DistributedComm` without
``jax.distributed`` initialized yields a 1-rank comm (useful for code
that runs unmodified on one host or many).
"""

from __future__ import annotations

import pickle

import numpy as np

import jax
from jax.experimental import multihost_utils
from jax.sharding import Mesh

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.context import CommSlave
from ytk_mp4j_tpu.comm import progress as progress_mod
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operand, Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.parallel.mesh import DEFAULT_AXIS, INTER_AXIS, INTRA_AXIS
from ytk_mp4j_tpu.utils import trace


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     **kwargs) -> "DistributedComm":
    """Join the distributed job and return the host-level comm.

    Mirrors the reference's slave constructor (master host:port ->
    coordinator address; expected slave count -> num_processes; SURVEY.md
    section 3a). With no arguments, JAX auto-detects cluster settings
    (TPU pod metadata) or falls back to single-process.
    """
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id, **kwargs)
    return DistributedComm()


def global_mesh(axis_name: str = DEFAULT_AXIS) -> Mesh:
    """1-D mesh over every device of every process (the flat perf path)."""
    return Mesh(np.asarray(jax.devices()), (axis_name,))


def hier_global_mesh(axis_names: tuple[str, str] = (INTER_AXIS, INTRA_AXIS),
                     ) -> Mesh:
    """2-D (process x local-device) mesh: ``inter`` crosses hosts (DCN),
    ``intra`` stays on-host/slice (ICI) — the device-side analogue of the
    reference's process x thread nesting (SURVEY.md section 3d)."""
    P = jax.process_count()
    L = jax.local_device_count()
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return Mesh(np.asarray(devs).reshape(P, L), axis_names)


class DistributedComm(CommSlave):
    """Host-level slave over the JAX distributed runtime.

    One rank per PROCESS. Collectives move host numpy data through the
    devices (``multihost_utils``), with in-place buffer semantics
    matching the other backends. Use the mesh builders above + the
    functional layer for device-resident perf-path work.
    """

    def __init__(self):
        self._rank = jax.process_index()
        self._n = jax.process_count()
        self._closed = False
        self.final_code: int | None = None  # set by close()
        self._pmesh: Mesh | None = None
        self._djits: dict = {}
        # operator.name -> job-wide agreed device-reduce verdict (see
        # _device_reduce_ok): the probe result is exchanged once and
        # AND-ed so every rank runs the same collective program
        self._agreed_native: dict[str, bool] = {}
        # key kind -> codec, kept IDENTICAL across processes (grown
        # only inside _union_device's synchronized novel-key exchange)
        self._codecs_by_kind: dict[str, object] = {}
        # job-wide AND of jax_enable_x64 (see _job_x64)
        self._x64_all: bool | None = None

    # -- identity / control plane --------------------------------------
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def slave_num(self) -> int:
        return self._n

    def barrier(self, name: str | None = None) -> None:
        self._assert_open()
        tag = name if name is not None else "mp4j_barrier"
        multihost_utils.sync_global_devices(tag)

    def close(self, code: int = 0) -> None:
        """Exchange exit codes, synchronize, then leave the job.

        Matches the reference's close(code) aggregation: every process
        learns the job-wide worst code before teardown —
        :attr:`final_code` is ``max`` over all ranks' codes (the
        coordinator-side ``Master.final_code`` equivalent), and a
        nonzero aggregate is logged on every rank."""
        if self._closed:
            return
        if self._n > 1:
            codes = self._exchange_obj(int(code))
            self.final_code = max(codes)
            if self.final_code != 0:
                self.error(f"job closing with aggregate exit code "
                           f"{self.final_code} (per-rank: {codes})")
            multihost_utils.sync_global_devices("mp4j_close")
            jax.distributed.shutdown()
        else:
            self.final_code = int(code)
        self._closed = True

    def _assert_open(self):
        if self._closed:
            raise Mp4jError("comm is closed")

    # -- internals ------------------------------------------------------
    def _check_numeric(self, operand: Operand):
        if not operand.is_numeric:
            raise Mp4jError(
                f"{operand.name} operands travel the map/object path on "
                "the distributed backend")
        if operand.dtype.itemsize == 8 and not jax.config.jax_enable_x64:
            raise Mp4jError(
                f"{operand.name} needs jax_enable_x64: the payload "
                "round-trips through the devices and would be silently "
                "downcast")

    def _norm_range(self, arr, operand: Operand, lo: int, hi: int | None):
        self._check_numeric(operand)
        arr = operand.check_array(arr)
        if arr.ndim != 1:
            raise Mp4jError("distributed path supports 1-D arrays")
        if hi is None:
            hi = len(arr)
        if not (0 <= lo <= hi <= len(arr)):
            raise Mp4jError(f"range [{lo}, {hi}) out of bounds")
        return arr, lo, hi

    def _allgather_rows(self, row: np.ndarray) -> np.ndarray:
        """[L] per process -> [P, L] on every process (device allgather)."""
        return np.asarray(multihost_utils.process_allgather(row))

    def _exchange_obj(self, obj) -> list:
        """Every process contributes one picklable object; returns the
        list of all processes' objects (rank-ordered). Pickled bytes ride
        a padded uint8 device allgather — the DCN Kryo analogue."""
        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        n = np.asarray([payload.size], np.int64)
        sizes = self._allgather_rows(n)[:, 0]
        cap = int(sizes.max())
        buf = np.zeros(cap, np.uint8)
        buf[: payload.size] = payload
        rows = self._allgather_rows(buf)
        return [pickle.loads(rows[p, : sizes[p]].tobytes())
                for p in range(self._n)]

    def _bcast(self, arr: np.ndarray, root: int) -> np.ndarray:
        return np.asarray(multihost_utils.broadcast_one_to_all(
            arr, is_source=self._rank == root))

    def _check_root(self, root: int):
        if not (0 <= root < self._n):
            raise Mp4jError(f"root {root} out of range [0, {self._n})")

    @staticmethod
    def _reduce_rows(rows: np.ndarray, operator: Operator) -> np.ndarray:
        acc = rows[0].copy()
        for p in range(1, rows.shape[0]):
            acc = operator.np_fn(acc, rows[p])
        return acc

    # -- device data plane ---------------------------------------------
    # One device collective (psum / pmax / pmin / psum_scatter) over a
    # one-device-per-process mesh replaces allgather + host loop for the
    # built-in operators: n*L wire bytes become the collective's
    # 2L(n-1)/n. PROD and custom operators keep the allgather path —
    # XLA has no pprod/custom all-reduce primitive, and a log/exp
    # rewrite would change float semantics.
    # Gated on the builtin Operator OBJECTS (identity, not name): a
    # custom operator named "MAX" must keep the host-reduce path — its
    # fn is the semantics, pmax is not (same shadowing class as
    # sparse._SEGMENT_REDUCERS / _map_device_ok). The lax primitive
    # comes from operator.lax_collective, never from a name table.

    def _device_reduce_ok(self, operator: Operator) -> bool:
        """SUM always lowers natively; MAX/MIN only where the probe (or
        the MP4J_NATIVE_REDUCE / set_native_reduce overrides) says the
        backend accepts non-SUM all-reduce HLO — the same gate every
        other collective honors (axon rejected pmax/pmin in round 1).
        False falls back to the allgather + host-reduce path.

        The probe verdict is resolved JOB-WIDE, not per process: the
        local probe's transient/rejection classification, TTL timing, or
        a per-host MP4J_NATIVE_REDUCE can differ across hosts, and ranks
        disagreeing on device-vs-host here would run mismatched
        collective programs (a hang, or worse). Every rank's local
        (verdict, definitive) pair rides the always-safe
        pickled-allgather path (:meth:`_exchange_obj`) and the AND of
        verdicts decides; all ranks call collectives in the same program
        order, so the exchange itself is symmetric. The agreed verdict
        is PINNED on the comm only once every rank's local verdict is
        definitive (override or cached probe, not a transient-failure
        optimistic default — see
        :func:`ops.collectives.native_reduce_definitive`); until then
        each call re-exchanges, so a backend whose first probes hit
        transient infra errors is not locked onto the native path
        forever. Once pinned, later ``set_native_reduce`` / env flips do
        NOT affect this comm — deliberately: a per-rank override
        consulted mid-job is exactly the desync hazard this exchange
        exists to prevent. Set overrides before first use, or construct
        a fresh comm."""
        if not any(operator is b for b in
                   (Operators.SUM, Operators.MAX, Operators.MIN)):
            return False  # identity, not name: custom "MAX" is not MAX
        if operator.lax_collective == "psum":
            return True  # SUM: no probed collective, natively safe
        agreed = self._agreed_native.get(operator.lax_collective)
        if agreed is not None:  # pinned: skip the local probe entirely
            return agreed       # (its TTL re-probes would be dead work)
        from ytk_mp4j_tpu.ops import collectives as coll
        kind = operator.lax_collective
        # materialize: .flat is a one-shot iterator and both resolver
        # calls below list() it
        devs = list(self._proc_mesh().devices.flat)
        verdict = bool(coll.resolve_native_reduce(operator, devices=devs))
        definitive = coll.native_reduce_definitive(kind, devices=devs)
        if self._n > 1:
            pairs = self._exchange_obj((verdict, definitive))
            verdict = all(v for v, _ in pairs)
            definitive = all(d for _, d in pairs)
        if definitive:
            self._agreed_native[operator.lax_collective] = verdict
        return verdict

    def _proc_mesh(self) -> Mesh:
        if self._pmesh is None:
            per_proc: dict[int, object] = {}
            for d in sorted(jax.devices(),
                            key=lambda d: (d.process_index, d.id)):
                per_proc.setdefault(d.process_index, d)
            self._pmesh = Mesh(
                np.asarray([per_proc[p] for p in range(self._n)]),
                ("proc",))
        return self._pmesh

    def _device_rows_collective(self, kind: str, block: np.ndarray,
                                lax_name: str) -> np.ndarray:
        """Run ONE device collective over per-process [L] blocks.
        kind="allreduce" returns the reduced [L]; kind="reduce_scatter"
        expects [n*B] (n equal blocks) and returns this rank's [B].
        ``lax_name`` is the lax primitive (psum/pmax/pmin), taken from
        the builtin operator's ``lax_collective`` by the callers."""
        from functools import partial
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._proc_mesh()
        sharding = NamedSharding(mesh, P("proc"))
        key = (kind, lax_name, block.dtype.str, block.size)
        fn = self._djits.get(key)
        if fn is None:
            if kind == "allreduce":
                red = getattr(lax, lax_name)

                def body(x):
                    return red(x[0], "proc")[None]
            else:
                def body(x):
                    return lax.psum_scatter(
                        x[0].reshape(self._n, -1), "proc")[None]
            # the psum output is replicated but rides back under the
            # row sharding (each rank reads its own copy) — same
            # check_vma waiver as the driver backend
            fn = jax.jit(partial(
                jax.shard_map, mesh=mesh, check_vma=False,
                in_specs=P("proc"), out_specs=P("proc"))(body))
            self._djits[key] = fn
        garr = jax.make_array_from_process_local_data(
            sharding, block[None, :], (self._n, block.size))
        return np.asarray(fn(garr).addressable_data(0))[0]

    # -- dense-array collectives ---------------------------------------
    def allreduce_array(self, arr, operand: Operand = Operands.FLOAT,
                        operator: Operator = Operators.SUM,
                        from_: int = 0, to: int | None = None):
        self._assert_open()
        arr, lo, hi = self._norm_range(arr, operand, from_, to)
        if self._n == 1 or hi == lo:
            return arr
        if self._device_reduce_ok(operator):
            arr[lo:hi] = self._device_rows_collective(
                "allreduce", np.ascontiguousarray(arr[lo:hi]),
                operator.lax_collective)
            return arr
        rows = self._allgather_rows(np.ascontiguousarray(arr[lo:hi]))
        arr[lo:hi] = self._reduce_rows(rows, operator)
        return arr

    def reduce_array(self, arr, operand: Operand = Operands.FLOAT,
                     operator: Operator = Operators.SUM, root: int = 0,
                     from_: int = 0, to: int | None = None):
        self._assert_open()
        self._check_root(root)
        arr, lo, hi = self._norm_range(arr, operand, from_, to)
        if self._n == 1 or hi == lo:
            return arr
        if self._device_reduce_ok(operator):
            merged = self._device_rows_collective(
                "allreduce", np.ascontiguousarray(arr[lo:hi]),
                operator.lax_collective)
            if self._rank == root:
                arr[lo:hi] = merged
            return arr
        rows = self._allgather_rows(np.ascontiguousarray(arr[lo:hi]))
        if self._rank == root:
            arr[lo:hi] = self._reduce_rows(rows, operator)
        return arr

    def broadcast_array(self, arr, operand: Operand = Operands.FLOAT,
                        root: int = 0, from_: int = 0,
                        to: int | None = None):
        self._assert_open()
        self._check_root(root)
        arr, lo, hi = self._norm_range(arr, operand, from_, to)
        if self._n == 1 or hi == lo:
            return arr
        arr[lo:hi] = self._bcast(np.ascontiguousarray(arr[lo:hi]), root)
        return arr

    def _norm_ranges(self, arr, ranges):
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), self._n)
        if len(ranges) != self._n:
            raise Mp4jError(f"need {self._n} ranges, got {len(ranges)}")
        return ranges

    def allgather_array(self, arr, operand: Operand = Operands.FLOAT,
                        ranges=None):
        self._assert_open()
        arr, _, _ = self._norm_range(arr, operand, 0, None)
        ranges = self._norm_ranges(arr, ranges)
        if self._n == 1:
            return arr
        B = max(1, max(e - s for s, e in ranges))
        block = np.zeros(B, dtype=operand.dtype)
        s, e = ranges[self._rank]
        block[: e - s] = arr[s:e]
        rows = self._allgather_rows(block)
        for p, (ps, pe) in enumerate(ranges):
            arr[ps:pe] = rows[p, : pe - ps]
        return arr

    def gather_array(self, arr, operand: Operand = Operands.FLOAT,
                     root: int = 0, ranges=None):
        self._assert_open()
        self._check_root(root)
        arr, _, _ = self._norm_range(arr, operand, 0, None)
        ranges = self._norm_ranges(arr, ranges)
        if self._n == 1:
            return arr
        B = max(1, max(e - s for s, e in ranges))
        block = np.zeros(B, dtype=operand.dtype)
        s, e = ranges[self._rank]
        block[: e - s] = arr[s:e]
        rows = self._allgather_rows(block)
        if self._rank == root:
            for p, (ps, pe) in enumerate(ranges):
                arr[ps:pe] = rows[p, : pe - ps]
        return arr

    def scatter_array(self, arr, operand: Operand = Operands.FLOAT,
                      root: int = 0, ranges=None):
        self._assert_open()
        self._check_root(root)
        arr, _, _ = self._norm_range(arr, operand, 0, None)
        ranges = self._norm_ranges(arr, ranges)
        if self._n == 1:
            return arr
        lo, hi = ranges[0][0], ranges[-1][1]
        full = self._bcast(np.ascontiguousarray(arr[lo:hi]), root)
        s, e = ranges[self._rank]
        arr[s:e] = full[s - lo: e - lo]
        return arr

    def reduce_scatter_array(self, arr, operand: Operand = Operands.FLOAT,
                             operator: Operator = Operators.SUM,
                             ranges=None):
        self._assert_open()
        arr, _, _ = self._norm_range(arr, operand, 0, None)
        ranges = self._norm_ranges(arr, ranges)
        if self._n == 1:
            return arr
        s, e = ranges[self._rank]
        if operator is Operators.SUM:  # identity: custom "SUM" is host
            # device psum_scatter over the (possibly uneven) ranges:
            # pack each range into an identity-padded equal block so
            # shard r's scattered segment IS range r
            B = max(1, max(re - rs for rs, re in ranges))
            blocks = np.full(self._n * B, operator.identity(arr.dtype),
                             dtype=arr.dtype)
            for r, (rs, re) in enumerate(ranges):
                blocks[r * B: r * B + (re - rs)] = arr[rs:re]
            mine = self._device_rows_collective("reduce_scatter", blocks,
                                                operator.lax_collective)
            arr[s:e] = mine[: e - s]
            return arr
        if self._device_reduce_ok(operator):
            # no pmax/pmin-scatter primitive: device allreduce + slice
            lo, hi = ranges[0][0], ranges[-1][1]
            merged = self._device_rows_collective(
                "allreduce", np.ascontiguousarray(arr[lo:hi]),
                operator.lax_collective)
            arr[s:e] = merged[s - lo: e - lo]
            return arr
        lo, hi = ranges[0][0], ranges[-1][1]
        rows = self._allgather_rows(np.ascontiguousarray(arr[lo:hi]))
        merged = self._reduce_rows(rows, operator)
        arr[s:e] = merged[s - lo: e - lo]
        return arr

    # -- map collectives -----------------------------------------------
    # Two planes. The DEVICE plane (numeric operands): key<->code
    # vocabularies kept IDENTICAL on every process (only novel keys
    # ride a small pickled exchange — near-empty once a gradient
    # stream's vocabulary stabilizes) and the values ride ONE device
    # sparse allreduce over the per-process mesh, like the dense plane.
    # The HOST plane (object values, or 64-bit without x64): the
    # pickled whole-map exchange, the reference's Kryo analogue.
    @staticmethod
    def _merge_maps(operator: Operator, acc: dict, src: dict) -> dict:
        # plain per-key loop by measurement — see
        # process_comm._merge_maps
        for k, v in src.items():
            acc[k] = operator.np_fn(acc[k], v) if k in acc else v
        return acc

    def _job_x64(self) -> bool:
        """jax_enable_x64 agreed JOB-WIDE (AND over ranks, pinned):
        a per-host flag divergence would otherwise route ranks onto
        different planes — mismatched programs, a hang. Pinned like
        ``_agreed_native``: flip the config before first use."""
        if self._x64_all is None:
            flag = bool(jax.config.jax_enable_x64)
            self._x64_all = (all(self._exchange_obj(flag))
                             if self._n > 1 else flag)
        return self._x64_all

    def _map_device_ok(self, operand: Operand,
                       operator: Operator) -> bool:
        if not operand.is_numeric:
            return False
        if operator not in (Operators.SUM, Operators.MAX,
                            Operators.MIN, Operators.PROD):
            # a custom operator's fn may be host-only python (legal on
            # the per-scalar merge loop); only the BUILTIN objects
            # (equality, not name — a custom named "MAX" is not MAX)
            # are known jit-safe, so customs keep the pickled plane
            return False
        if operand.dtype.itemsize == 8 and not self._job_x64():
            return False
        return True

    def _union_device(self, d: dict, operand: Operand,
                      operator: Operator):
        """The job-wide reduced union via the device plane as
        ``(codec, codes, values)``, or None when every rank's map is
        empty. Codec synchronization: each call, every rank's NOVEL
        keys (plus its entry count, value shape, key kind and any LOCAL
        validation error) ride one pickled exchange; all ranks then
        grow their codec with the same union in the same order, so
        codes agree job-wide without ever exchanging full maps again.

        All local validation (key kinds, value cast/shape) happens
        BEFORE the exchange and its outcome rides it: a bad map on one
        rank must raise on EVERY rank, not error on one while its peers
        block in the device collective."""
        from ytk_mp4j_tpu.comm import keycodec
        from ytk_mp4j_tpu.ops import sparse as sparse_ops

        k0 = next(iter(d)) if d else None
        kind = None if k0 is None else keycodec.kind_of(k0)
        vshape = None if not d else np.shape(d[k0])
        codec = self._codecs_by_kind.get(kind) if kind else None
        if kind and codec is None:
            codec = self._codecs_by_kind[kind] = (
                keycodec.codec_for_kind(kind))
        c = len(d)
        err = None
        novel: list = []
        v = None
        if c:
            try:
                novel = codec.novel(d.keys(), c)
                v = keycodec.pack_values(d.values(), c, vshape,
                                         operand.dtype)
            except Mp4jError as e:
                err = str(e)
        infos = self._exchange_obj((kind, novel, c, vshape, err))
        errs = [i[4] for i in infos if i[4]]
        if errs:
            raise Mp4jError(f"map collective invalid on some rank: "
                            f"{errs[0]}")
        kinds = {i[0] for i in infos if i[0] is not None}
        if len(kinds) > 1:
            raise Mp4jError(
                f"map key kinds differ across ranks: {sorted(kinds)}")
        vshapes = {i[3] for i in infos if i[3] is not None}
        if len(vshapes) > 1:
            raise Mp4jError(
                f"map values must share a shape across ranks; got "
                f"{sorted(vshapes)}")
        total = sum(i[2] for i in infos)
        if total == 0:
            return None
        job_kind = next(iter(kinds))
        vshape = next(iter(vshapes))
        if codec is None:   # this rank was empty: adopt the job's kind
            codec = self._codecs_by_kind.get(job_kind)
            if codec is None:
                codec = self._codecs_by_kind[job_kind] = (
                    keycodec.codec_for_kind(job_kind))
        union_novel = [k for i in infos for k in i[1]]
        if union_novel:
            codec.encode(union_novel, len(union_novel))
        Lmax = keycodec.pow2_bucket(max(1, max(i[2] for i in infos)))
        ident = operator.identity(operand.dtype)
        idx = np.full(Lmax, sparse_ops.SENTINEL, np.int32)
        val = np.full((Lmax,) + vshape, ident, dtype=operand.dtype)
        if c:
            idx[:c] = codec.encode(d.keys(), c)
            val[:c] = v
        cap = keycodec.pow2_bucket(min(codec.size, total))
        oi, ov = self._device_sparse_allreduce(idx, val, cap, operand,
                                               operator)
        live = oi != sparse_ops.SENTINEL
        return codec, oi[live], ov[live]

    def _device_sparse_allreduce(self, idx, val, capacity: int,
                                 operand: Operand, operator: Operator):
        from functools import partial
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ytk_mp4j_tpu.ops import sparse as sparse_ops

        mesh = self._proc_mesh()
        vshape = val.shape[1:]
        key = ("sparse", idx.shape[0], capacity, vshape,
               val.dtype.str, operator.name, id(operator))
        fn = self._djits.get(key)
        if fn is None:
            def body(i, v):
                return sparse_ops.sparse_allreduce(
                    i[0], v[0], capacity, operator, "proc")

            fn = jax.jit(partial(
                jax.shard_map, mesh=mesh, check_vma=False,
                in_specs=(P("proc"), P("proc")),
                out_specs=(P(None), P(None)))(body))
            self._djits[key] = fn
        n = self._n
        gi = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("proc")), idx[None, :],
            (n,) + idx.shape)
        gv = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("proc")), val[None],
            (n,) + val.shape)
        oi, ov = fn(gi, gv)
        # two fetches is fine HERE, unlike the driver backend's
        # single-fetch rule (tpu_comm._union_codes): each process reads
        # its own LOCAL device — no ~100 ms tunnel RTT per asarray —
        # and deriving the union host-side would mean shipping every
        # rank's full code list through the pickled exchange, the O(K)
        # per-call cost this plane exists to avoid
        return (np.asarray(oi.addressable_data(0)),
                np.asarray(ov.addressable_data(0)))

    def _merged_union(self, d: dict, operand: Operand,
                      operator: Operator) -> dict | None:
        """The job-wide merged union dict via whichever plane applies;
        None when the device plane saw every rank empty."""
        if self._map_device_ok(operand, operator):
            out = self._union_device(d, operand, operator)
            if out is None:
                return None
            codec, codes, vals = out
            return dict(zip(codec.decode(codes), list(vals)))
        merged: dict = {}
        for m in self._exchange_obj(d):
            self._merge_maps(operator, merged, m)
        return merged

    def reset_map_vocabularies(self) -> None:
        """Drop the synchronized key<->code vocabularies (see
        ``TpuCommCluster.reset_map_vocabularies`` for why). COLLECTIVE
        in effect: every rank must call it at the same program point —
        a one-sided reset would silently desynchronize codes (this rank
        would re-insert keys its peers already hold under old codes)."""
        self._assert_open()
        self._codecs_by_kind.clear()

    def allreduce_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                      operator: Operator = Operators.SUM) -> dict:
        self._assert_open()
        if self._n == 1:
            return d
        merged = self._merged_union(d, operand, operator)
        if merged is None:
            return d
        d.clear()
        d.update(merged)
        return d

    def reduce_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                   operator: Operator = Operators.SUM, root: int = 0) -> dict:
        self._assert_open()
        self._check_root(root)
        if self._n == 1:
            return d
        merged = self._merged_union(d, operand, operator)
        if merged is None:
            return d
        if self._rank == root:
            d.clear()
            d.update(merged)
        return d

    def broadcast_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                      root: int = 0) -> dict:
        self._assert_open()
        self._check_root(root)
        if self._n == 1:
            return d
        src = self._exchange_obj(d)[root]
        d.clear()
        d.update(src)
        return d

    def gather_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                   root: int = 0) -> dict:
        self._assert_open()
        self._check_root(root)
        if self._n == 1:
            return d
        maps = self._exchange_obj(d)
        union = self._disjoint_union(maps, "gather_map")
        if self._rank == root:
            d.clear()
            d.update(union)
        return d

    @staticmethod
    def _disjoint_union(maps, what: str) -> dict:
        """Disjoint union of per-rank maps; a duplicate raises naming
        the key and both owner ranks (contract parity with the socket
        backend's gather_map; the conflict hunt runs only on the error
        path)."""
        total = sum(len(m) for m in maps)
        union: dict = {}
        for m in maps:
            union.update(m)
        if len(union) != total:
            seen: dict = {}
            for r, m in enumerate(maps):
                for k in m:
                    if k in seen:
                        raise Mp4jError(
                            f"{what}: duplicate key {k!r} owned by "
                            f"ranks {seen[k]} and {r}; use reduce_map "
                            f"to combine")
                    seen[k] = r
        return union

    def allgather_map(self, d: dict,
                      operand: Operand = Operands.DOUBLE) -> dict:
        self._assert_open()
        if self._n == 1:
            return d
        maps = self._exchange_obj(d)
        union = self._disjoint_union(maps, "allgather_map")
        d.clear()
        d.update(union)
        return d

    def scatter_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                    root: int = 0, partitioner=None) -> dict:
        """``partitioner(key) -> rank`` overrides the placement rule
        (contract parity with ``ProcessCommSlave.scatter_map``); it must
        be the same function on every rank."""
        self._assert_open()
        self._check_root(root)
        if self._n == 1:
            return d
        if partitioner is None:
            partitioner = lambda k: meta.key_partition(k, self._n)  # noqa: E731
        src = self._exchange_obj(d)[root]
        mine = {}
        for k, v in src.items():
            if meta.check_partition_rank(partitioner(k), self._n,
                                         k) == self._rank:
                mine[k] = v
        d.clear()
        d.update(mine)
        return d

    def reduce_scatter_map(self, d: dict,
                           operand: Operand = Operands.DOUBLE,
                           operator: Operator = Operators.SUM) -> dict:
        self._assert_open()
        if self._n == 1:
            return d
        if self._map_device_ok(operand, operator):
            out = self._union_device(d, operand, operator)
            if out is None:
                return d
            codec, codes, vals = out
            # blake2b placement cached per code on the codec
            mask = codec.partition(codes, self._n) == self._rank
            mine = dict(zip(codec.decode(codes[mask]),
                            list(vals[mask])))
        else:
            acc: dict = {}
            for m in self._exchange_obj(d):
                self._merge_maps(operator, acc, m)
            mine = {k: v for k, v in acc.items()
                    if meta.key_partition(k, self._n) == self._rank}
        d.clear()
        d.update(mine)
        return d

    # ------------------------------------------------------------------
    # nonblocking collectives (ISSUE 11): the multi-host device plane
    # runs one jitted program per collective whose dispatch is already
    # asynchronous under JAX — the i* twins execute eagerly and return
    # resolved futures, keeping one API across all four backends.
    # ------------------------------------------------------------------
    def iallreduce(self, arr, operand: Operand = Operands.FLOAT,
                   operator: Operator = Operators.SUM,
                   from_: int = 0, to: int | None = None):
        """Eager nonblocking :meth:`allreduce_array` (resolved
        future)."""
        return progress_mod.eager_future(
            self, "allreduce_array", arr, operand, operator,
            from_=from_, to=to)

    def ireduce_scatter(self, arr, operand: Operand = Operands.FLOAT,
                        operator: Operator = Operators.SUM,
                        ranges=None):
        """Eager nonblocking :meth:`reduce_scatter_array`."""
        return progress_mod.eager_future(
            self, "reduce_scatter_array", arr, operand, operator,
            ranges=ranges)

    def iallgather(self, arr, operand: Operand = Operands.FLOAT,
                   ranges=None):
        """Eager nonblocking :meth:`allgather_array`."""
        return progress_mod.eager_future(
            self, "allgather_array", arr, operand, ranges=ranges)

    def igather(self, arr, operand: Operand = Operands.FLOAT,
                root: int = 0, ranges=None):
        """Eager nonblocking :meth:`gather_array`."""
        return progress_mod.eager_future(
            self, "gather_array", arr, operand, root=root,
            ranges=ranges)

    def iallreduce_map(self, d: dict,
                       operand: Operand = Operands.DOUBLE,
                       operator: Operator = Operators.SUM):
        """Eager nonblocking :meth:`allreduce_map`."""
        return progress_mod.eager_future(
            self, "allreduce_map", d, operand, operator)

    def wait_all(self, timeout: float | None = None) -> None:
        """Collective-boundary drain; the eager backend never has
        outstanding work — no-op, kept for portable code."""


# per-collective tracing (utils.trace; zero overhead when disabled)
trace.instrument(DistributedComm)
