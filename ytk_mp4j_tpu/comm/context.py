"""Abstract comm API surface.

Defines the per-rank slave contract shared by every backend, mirroring the
reference's slave API (SURVEY.md section 2: ``ProcessCommSlave`` /
``ThreadCommSlave`` expose rank/size, 7 collectives x {array, map},
``barrier()``, ``info()/error()``, ``close(code)``).

Backends (SURVEY.md section 7 build order):

- :class:`~ytk_mp4j_tpu.comm.tpu_comm.TpuCommCluster` — the TPU path; a
  single-controller SPMD driver rather than a per-rank object (idiomatic
  JAX), exposing cluster-level collectives over all ranks at once.
- ``comm.process_comm.ProcessCommSlave`` — CPU socket reference path
  (recursive halving/doubling, the reference's semantics); phase 3.
- ``comm.thread_comm.ThreadCommSlave`` — thread-level nesting over a
  process slave; phase 6.
"""

from __future__ import annotations

import abc
import sys
import time


class CommSlave(abc.ABC):
    """Per-rank communication endpoint."""

    @property
    @abc.abstractmethod
    def rank(self) -> int: ...

    @property
    @abc.abstractmethod
    def slave_num(self) -> int: ...

    @abc.abstractmethod
    def barrier(self) -> None: ...

    @abc.abstractmethod
    def close(self, code: int = 0) -> None: ...

    def reset_map_vocabularies(self) -> None:
        """Drop any persistent map key<->code vocabularies. No-op on
        backends without codecs (socket/thread merge host dicts
        directly) so periodic-reset code is portable across the slave
        contract; the device backends override. COLLECTIVE in effect
        where state exists: every rank must call it at the same program
        point."""

    # -- centralized logging (reference: info()/error() forwarded to the
    # master's console, SURVEY.md section 3e). Default: local stderr with a
    # rank prefix; socket backends override to forward to the master.
    def info(self, msg: str) -> None:
        print(self._fmt("INFO", msg), file=sys.stderr, flush=True)

    def error(self, msg: str) -> None:
        print(self._fmt("ERROR", msg), file=sys.stderr, flush=True)

    def _fmt(self, level: str, msg: str) -> str:
        ts = time.strftime("%H:%M:%S")
        return f"[{ts}][rank {self.rank}/{self.slave_num}][{level}] {msg}"
