"""Thread-level slave — hybrid process x thread parallelism.

The reference's ``ThreadCommSlave`` (SURVEY.md sections 2, 3d): each of
``thread_num`` threads in a process holds a slave object with a per-thread
rank; collectives synchronize on an in-process barrier, reduce into
thread 0's buffer through shared memory, run the process-level collective
on thread 0, then fan results back out to all threads.

Global rank layout is blocked: ``global_rank = proc_rank * thread_num +
thread_rank``, so a process owns a contiguous global-rank range and
segment collectives can coarsen thread ranges into per-process ranges for
the process-level step.

Construction: ``ThreadCommSlave.spawn_group(thread_num, master_host,
master_port)`` builds the ``thread_num`` slave objects sharing one
``ProcessCommSlave`` (or, with no master args, a standalone single-process
thread group — useful for tests and pure-thread jobs).

TPU mapping note (SURVEY.md 3d): this two-level hierarchy is the CPU
analogue of the device mesh's inter x intra axes — the device-side
equivalent is ``TpuCommCluster(mesh=make_hier_mesh(inter, intra))``.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm.context import CommSlave
from ytk_mp4j_tpu.comm import progress as progress_mod
from ytk_mp4j_tpu.comm.process_comm import ProcessCommSlave
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operand, Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.utils import native, trace
from ytk_mp4j_tpu.utils.stats import CommStats, merge_snapshots


class _ThreadGroup:
    """Shared state for the threads of one process."""

    def __init__(self, thread_num: int, proc: ProcessCommSlave | None):
        self.thread_num = thread_num
        self.proc = proc
        # intra-process counters (shared-memory merges); the process
        # slave keeps its own wire counters — stats() sums both
        self.comm_stats = CommStats()
        self.barrier = threading.Barrier(thread_num)
        self.slots: list = [None] * thread_num
        self.result = None
        self.lock = threading.Lock()
        # close bookkeeping: the underlying process slave closes when
        # every thread's slave has closed (or immediately if only one
        # close ever comes — see ThreadCommSlave.close)
        self.pending_closes = thread_num
        self.max_code = 0
        self.closed = False

    @property
    def proc_rank(self) -> int:
        return self.proc.rank if self.proc is not None else 0

    @property
    def proc_num(self) -> int:
        return self.proc.slave_num if self.proc is not None else 1


class ThreadCommSlave(CommSlave):
    """One thread's endpoint in a hybrid process x thread job."""

    def __init__(self, group: _ThreadGroup, thread_rank: int):
        self._g = group
        self._tr = thread_rank
        # trace.traced scopes this around every collective call so
        # intra-process merge time attributes to the right collective
        self._comm_stats = group.comm_stats

    # ------------------------------------------------------------------
    @classmethod
    def spawn_group(cls, thread_num: int, master_host: str | None = None,
                    master_port: int | None = None,
                    **proc_kwargs) -> list["ThreadCommSlave"]:
        """Create the ``thread_num`` slaves of this process. With master
        args, also joins the process-level job (one ProcessCommSlave
        shared by all threads, used from thread 0 only)."""
        if thread_num < 1:
            raise Mp4jError(f"thread_num must be >= 1, got {thread_num}")
        proc = None
        if master_host is not None:
            if master_port is None:
                raise Mp4jError("master_port required with master_host")
            proc = ProcessCommSlave(master_host, master_port, **proc_kwargs)
        g = _ThreadGroup(thread_num, proc)
        # intra-process spans (shared-memory merges) land on the
        # process rank's timeline track; per-thread tids distinguish
        # the threads within it
        g.comm_stats.rank = proc.rank if proc is not None else 0
        return [cls(g, t) for t in range(thread_num)]

    # ------------------------------------------------------------------
    # identity
    # ------------------------------------------------------------------
    @property
    def thread_rank(self) -> int:
        return self._tr

    @property
    def thread_num(self) -> int:
        return self._g.thread_num

    @property
    def rank(self) -> int:
        """Global rank across all processes x threads (blocked layout)."""
        return self._g.proc_rank * self._g.thread_num + self._tr

    @property
    def slave_num(self) -> int:
        """Global endpoint count (process count x thread count)."""
        return self._g.proc_num * self._g.thread_num

    # ------------------------------------------------------------------
    # control plane
    # ------------------------------------------------------------------
    def thread_barrier(self) -> None:
        """Intra-process barrier (the reference's ``threadBarrier()``)."""
        self._g.barrier.wait()

    def barrier(self) -> None:
        """Global barrier: threads sync, thread 0 joins the process-level
        barrier, threads sync again."""
        self.thread_barrier()
        # leader pattern: only thread 0 joins the process barrier; the
        # surrounding thread barriers keep every thread's schedule
        # aligned, so the rank-conditional collective cannot diverge
        # mp4j-lint: disable=R1 (leader collective bracketed by barriers)
        if self._tr == 0 and self._g.proc is not None:
            self._g.proc.barrier()
        self.thread_barrier()

    def info(self, msg: str) -> None:
        if self._g.proc is not None:
            with self._g.lock:
                self._g.proc.info(f"[t{self._tr}] {msg}")
        else:
            super().info(msg)

    def error(self, msg: str) -> None:
        if self._g.proc is not None:
            with self._g.lock:
                self._g.proc.error(f"[t{self._tr}] {msg}")
        else:
            super().error(msg)

    def stats(self) -> dict[str, dict[str, float]]:
        """Per-collective transport counters: the group's intra-process
        merge counters summed with the shared process slave's wire
        counters (schema: :mod:`ytk_mp4j_tpu.utils.stats`)."""
        snaps = [self._g.comm_stats.snapshot()]
        if self._g.proc is not None:
            snaps.append(self._g.proc.stats())
        return merge_snapshots(*snaps)

    def progress(self) -> dict:
        """The group's telemetry progress record (schema:
        obs.telemetry). ``seq`` counts outermost collective calls
        across ALL threads of the group — a per-group, still
        monotonically increasing, sequence number."""
        return self._g.comm_stats.progress()

    def audit_records(self) -> list[dict]:
        """The shared process slave's audit record ring (ISSUE 8).
        In a hybrid job every thread-level collective funnels through
        ONE process-level collective on thread 0, and THAT call is
        what the audit plane records (the process slave owns the wire)
        — so any thread may read/dump the group's audit state, exactly
        like :meth:`stats`. Standalone groups have no wire and no
        audit ring; they return []."""
        if self._g.proc is not None:
            return self._g.proc.audit_records()
        return []

    def dump_audit(self, root: str) -> str | None:
        """Write the group's replay bundle file (see
        ``ProcessCommSlave.dump_audit``); None for standalone groups
        or ``MP4J_AUDIT=off``. Idempotent across threads — every
        thread writes the same process-rank file."""
        if self._g.proc is not None:
            return self._g.proc.dump_audit(root)
        return None

    def _on_collective_error(self, name: str, exc: BaseException) -> None:
        """Forward a failed collective to the process slave's DIAGNOSE
        path so the master's hang diagnosis also covers hybrid jobs."""
        if self._g.proc is not None:
            self._g.proc._on_collective_error(
                f"{name}[t{self._tr}]", exc)

    def close(self, code: int = 0) -> None:
        """Close the process-level connection (idempotent; safe to call
        once per thread or once per process — no barrier, so a single
        thread closing sequentially cannot deadlock). The highest code
        seen before the underlying close wins."""
        with self._g.lock:
            self._g.max_code = max(self._g.max_code, int(code))
            self._g.pending_closes -= 1
            if self._g.pending_closes <= 0 and not self._g.closed:
                self._g.closed = True
                if self._g.proc is not None:
                    self._g.proc.close(self._g.max_code)

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    @staticmethod
    def _detach(buf):
        """Copy a deposited slot out of the caller's buffer (slots hold
        VIEWS of caller arrays until first write; merging in place
        would corrupt a sibling thread's input)."""
        return buf.copy() if isinstance(buf, np.ndarray) else list(buf)

    def _tree_reduce_slots(self, operator: Operator) -> None:
        """Pairwise-parallel intra-process reduction of the deposited
        slots into thread 0's slot: round k merges ``slot[t + k]`` into
        ``slot[t]`` for ``t % 2k == 0``, every eligible thread merging
        CONCURRENTLY (numpy's reduce loops release the GIL), so the
        intra-process reduce runs O(log T) rounds instead of the old
        leader-serial O(T) loop — the reference's simple pattern, but
        scalable past a handful of threads. Must be called by EVERY
        thread between deposit and the leader phase (each round ends on
        the shared barrier; all threads run the same barrier count).
        Thread 0's slot ends DETACHED from its input view, like the
        leader's copy did.

        Memory: round 1 detaches up to ceil(T/2) slots concurrently
        (the old serial leader held ONE working copy), so transient RSS
        for a [L] collective is ~T/2 x L elements — the price of the
        parallel merge; size thread groups accordingly on memory-tight
        hosts."""
        slots = self._g.slots
        T = self._g.thread_num
        tr = self._tr
        detached = False
        if tr == 0:
            # barrier-delimited: thread t writes only slot t, and reads
            # slot t+k only after the round barrier below has published it
            # mp4j-lint: disable=R3 (disjoint slot ownership per round)
            slots[0] = self._detach(slots[0])
            detached = True
        k = 1
        while k < T:
            if tr % (2 * k) == 0 and tr + k < T:
                acc = slots[tr]
                if not detached:
                    acc = self._detach(acc)
                    detached = True
                self._merge_into(operator, acc, slots[tr + k])
                # mp4j-lint: disable=R3 (disjoint slot ownership per round)
                slots[tr] = acc
            self.thread_barrier()
            k *= 2

    def _fan_in_out(self, deposit, leader, collect, tree_operator=None):
        """The hybrid pattern: all threads deposit, thread 0 runs
        ``leader`` (merging + process collective), all threads collect.
        With ``tree_operator`` the deposits are pre-reduced into slot 0
        by the pairwise tree above and ``leader`` gets merged slots."""
        # barrier-delimited: each thread writes only its own slot, and
        # no slot is read before the barrier below publishes them all
        # mp4j-lint: disable=R3 (own-slot write before the deposit barrier)
        self._g.slots[self._tr] = deposit()
        self.thread_barrier()
        if tree_operator is not None:
            self._tree_reduce_slots(tree_operator)
        if self._tr == 0:
            # thread 0 alone writes result, between the deposit barrier
            # and the publish barrier below — no concurrent reader exists
            # mp4j-lint: disable=R3 (leader write between barriers)
            self._g.result = leader(self._g.slots)
        self.thread_barrier()
        out = collect(self._g.result)
        # final barrier so thread 0 can't start the next collective and
        # overwrite shared state while others are still reading
        self.thread_barrier()
        return out

    def _coarse_ranges(self, ranges):
        """Merge per-global-rank ranges into per-process ranges (blocked
        layout makes each process's range contiguous)."""
        T = self._g.thread_num
        return [(ranges[p * T][0], ranges[p * T + T - 1][1])
                for p in range(self._g.proc_num)]

    def _merge_into(self, operator, acc, src):
        if isinstance(acc, np.ndarray):
            t0 = time.perf_counter()
            native.reduce_into(operator, acc, src)
            self._g.comm_stats.add("reduce_seconds",
                                   time.perf_counter() - t0)
        else:
            for i in range(len(acc)):
                acc[i] = operator.np_fn(acc[i], src[i])
        return acc

    @staticmethod
    def _copied_map(m: dict) -> dict:
        """Per-thread value copies: threads must never alias the same
        mutable value objects after a map collective (in-place updates on
        one thread would corrupt another's map)."""
        return {k: (v.copy() if isinstance(v, np.ndarray) else v)
                for k, v in m.items()}

    def _decompose_root(self, root: int):
        if not (0 <= root < self.slave_num):
            raise Mp4jError(f"root {root} out of range [0, {self.slave_num})")
        return divmod(root, self._g.thread_num)  # (proc, thread)

    # ------------------------------------------------------------------
    # dense-array collectives
    # ------------------------------------------------------------------
    def allreduce_array(self, arr, operand: Operand = Operands.FLOAT,
                        operator: Operator = Operators.SUM,
                        from_: int = 0, to: int | None = None,
                        algo: str = "auto"):
        """Intra-process tree into thread 0, process allreduce, fan out.

        ``algo`` selects the process-level algorithm, as on
        ProcessCommSlave: ``"auto"`` (default, size-aware selection),
        ``"tree"``, ``"rhd"``, or ``"ring"``."""
        hi = to if to is not None else len(arr)
        lo = from_

        def deposit():
            return arr[lo:hi]

        def leader(slots):
            acc = slots[0]          # tree-merged, detached
            if self._g.proc is not None:
                self._g.proc.allreduce_array(acc, operand, operator,
                                             algo=algo)
            # mp4j-lint: disable=R6 (slot 0 detached by _tree_reduce_slots)
            return acc

        def collect(result):
            arr[lo:hi] = result
            return arr

        return self._fan_in_out(deposit, leader, collect,
                                tree_operator=operator)

    def reduce_array(self, arr, operand: Operand = Operands.FLOAT,
                     operator: Operator = Operators.SUM, root: int = 0,
                     from_: int = 0, to: int | None = None):
        root_proc, root_thread = self._decompose_root(root)
        hi = to if to is not None else len(arr)
        lo = from_

        def deposit():
            return arr[lo:hi]

        def leader(slots):
            acc = slots[0]          # tree-merged, detached
            if self._g.proc is not None:
                self._g.proc.reduce_array(acc, operand, operator,
                                          root=root_proc)
            # mp4j-lint: disable=R6 (slot 0 detached by _tree_reduce_slots)
            return acc

        def collect(result):
            if (self._g.proc_rank == root_proc
                    and self._tr == root_thread):
                arr[lo:hi] = result
            return arr

        return self._fan_in_out(deposit, leader, collect,
                                tree_operator=operator)

    def broadcast_array(self, arr, operand: Operand = Operands.FLOAT,
                        root: int = 0, from_: int = 0,
                        to: int | None = None):
        root_proc, root_thread = self._decompose_root(root)
        hi = to if to is not None else len(arr)
        lo = from_

        def deposit():
            # only the root thread's payload matters
            return arr[lo:hi]

        def leader(slots):
            buf = self._detach(slots[root_thread]
                               if self._g.proc_rank == root_proc
                               else slots[0])
            if self._g.proc is not None:
                self._g.proc.broadcast_array(buf, operand, root=root_proc)
            return buf

        def collect(result):
            arr[lo:hi] = result
            return arr

        return self._fan_in_out(deposit, leader, collect)

    def allgather_array(self, arr, operand: Operand = Operands.FLOAT,
                        ranges=None, algo: str = "auto"):
        """``algo`` selects the process-level schedule ("auto"/"ring"/
        "tree"), as on ProcessCommSlave."""
        N = self.slave_num
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), N)
        if len(ranges) != N:
            raise Mp4jError(f"need {N} ranges, got {len(ranges)}")
        my_s, my_e = ranges[self.rank]

        def deposit():
            return (my_s, my_e, arr[my_s:my_e])

        def leader(slots):
            if isinstance(slots[0][2], np.ndarray):
                full = np.zeros(len(arr), dtype=operand.dtype)
            else:
                full = [None] * len(arr)
            for (s, e, seg) in slots:
                full[s:e] = seg
            if self._g.proc is not None:
                self._g.proc.allgather_array(
                    full, operand, ranges=self._coarse_ranges(ranges),
                    algo=algo)
            return full

        def collect(result):
            lo = ranges[0][0]
            hi = ranges[-1][1]
            arr[lo:hi] = result[lo:hi]
            return arr

        return self._fan_in_out(deposit, leader, collect)

    def gather_array(self, arr, operand: Operand = Operands.FLOAT,
                     root: int = 0, ranges=None):
        root_proc, root_thread = self._decompose_root(root)
        N = self.slave_num
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), N)
        my_s, my_e = ranges[self.rank]

        def deposit():
            return (my_s, my_e, arr[my_s:my_e])

        def leader(slots):
            if isinstance(slots[0][2], np.ndarray):
                full = np.zeros(len(arr), dtype=operand.dtype)
            else:
                full = [None] * len(arr)
            for (s, e, seg) in slots:
                full[s:e] = seg
            if self._g.proc is not None:
                self._g.proc.gather_array(
                    full, operand, root=root_proc,
                    ranges=self._coarse_ranges(ranges))
            return full

        def collect(result):
            if (self._g.proc_rank == root_proc
                    and self._tr == root_thread):
                lo, hi = ranges[0][0], ranges[-1][1]
                arr[lo:hi] = result[lo:hi]
            return arr

        return self._fan_in_out(deposit, leader, collect)

    def scatter_array(self, arr, operand: Operand = Operands.FLOAT,
                      root: int = 0, ranges=None):
        root_proc, root_thread = self._decompose_root(root)
        N = self.slave_num
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), N)

        def deposit():
            return arr

        def leader(slots):
            full = self._detach(slots[root_thread]
                                if self._g.proc_rank == root_proc
                                else slots[0])
            if self._g.proc is not None:
                self._g.proc.scatter_array(
                    full, operand, root=root_proc,
                    ranges=self._coarse_ranges(ranges))
            return full

        def collect(result):
            s, e = ranges[self.rank]
            arr[s:e] = result[s:e]
            return arr

        return self._fan_in_out(deposit, leader, collect)

    def reduce_scatter_array(self, arr, operand: Operand = Operands.FLOAT,
                             operator: Operator = Operators.SUM,
                             ranges=None, algo: str = "auto"):
        """``algo`` selects the process-level schedule ("auto"/"ring"/
        "tree"), as on ProcessCommSlave."""
        N = self.slave_num
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), N)

        def deposit():
            return arr

        def leader(slots):
            acc = slots[0]          # tree-merged, detached
            if self._g.proc is not None:
                self._g.proc.reduce_scatter_array(
                    acc, operand, operator,
                    ranges=self._coarse_ranges(ranges), algo=algo)
            # mp4j-lint: disable=R6 (slot 0 detached by _tree_reduce_slots)
            return acc

        def collect(result):
            s, e = ranges[self.rank]
            arr[s:e] = result[s:e]
            return arr

        return self._fan_in_out(deposit, leader, collect,
                                tree_operator=operator)

    # ------------------------------------------------------------------
    # map collectives
    # ------------------------------------------------------------------
    def allreduce_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                      operator: Operator = Operators.SUM) -> dict:
        def deposit():
            return dict(d)

        def leader(slots):
            acc: dict = {}
            for m in slots:
                for k, v in m.items():
                    acc[k] = operator.np_fn(acc[k], v) if k in acc else v
            if self._g.proc is not None:
                self._g.proc.allreduce_map(acc, operand, operator)
            return acc

        def collect(result):
            d.clear()
            d.update(self._copied_map(result))
            return d

        return self._fan_in_out(deposit, leader, collect)

    def reduce_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                   operator: Operator = Operators.SUM, root: int = 0) -> dict:
        root_proc, root_thread = self._decompose_root(root)

        def deposit():
            return dict(d)

        def leader(slots):
            acc: dict = {}
            for m in slots:
                for k, v in m.items():
                    acc[k] = operator.np_fn(acc[k], v) if k in acc else v
            if self._g.proc is not None:
                self._g.proc.reduce_map(acc, operand, operator,
                                        root=root_proc)
            return acc

        def collect(result):
            if (self._g.proc_rank == root_proc
                    and self._tr == root_thread):
                d.clear()
                d.update(self._copied_map(result))
            return d

        return self._fan_in_out(deposit, leader, collect)

    def broadcast_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                      root: int = 0) -> dict:
        root_proc, root_thread = self._decompose_root(root)

        def deposit():
            return dict(d)

        def leader(slots):
            buf = dict(slots[root_thread
                             if self._g.proc_rank == root_proc else 0])
            if self._g.proc is not None:
                self._g.proc.broadcast_map(buf, operand, root=root_proc)
            return buf

        def collect(result):
            d.clear()
            d.update(self._copied_map(result))
            return d

        return self._fan_in_out(deposit, leader, collect)

    def _disjoint_union_slots(self, slots, what: str) -> dict:
        """Disjoint union of the threads' deposited maps; a duplicate
        raises naming the key and BOTH owner GLOBAL ranks (contract
        parity with ProcessCommSlave.gather_map). The conflict hunt
        runs only on the error path — the fast path stays one
        update+len check per slot."""
        acc: dict = {}
        total = 0
        for m in slots:
            total += len(m)
            acc.update(m)
        if len(acc) != total:
            base = self._g.proc_rank * self._g.thread_num
            seen: dict = {}
            for t, m in enumerate(slots):
                for k in m:
                    if k in seen:
                        raise Mp4jError(
                            f"{what}: duplicate key {k!r} owned by "
                            f"global ranks {base + seen[k]} and "
                            f"{base + t}; use reduce_map to combine")
                    seen[k] = t
        return acc

    def gather_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                   root: int = 0) -> dict:
        root_proc, root_thread = self._decompose_root(root)

        def deposit():
            return dict(d)

        def leader(slots):
            acc = self._disjoint_union_slots(slots, "gather_map")
            if self._g.proc is not None:
                self._g.proc.gather_map(acc, operand, root=root_proc)
            return acc

        def collect(result):
            if (self._g.proc_rank == root_proc
                    and self._tr == root_thread):
                d.clear()
                d.update(self._copied_map(result))
            return d

        return self._fan_in_out(deposit, leader, collect)

    def allgather_map(self, d: dict,
                      operand: Operand = Operands.DOUBLE) -> dict:
        def deposit():
            return dict(d)

        def leader(slots):
            acc = self._disjoint_union_slots(slots, "allgather_map")
            if self._g.proc is not None:
                self._g.proc.allgather_map(acc, operand)
            return acc

        def collect(result):
            d.clear()
            d.update(self._copied_map(result))
            return d

        return self._fan_in_out(deposit, leader, collect)

    def scatter_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                    root: int = 0) -> dict:
        """Rank r keeps the subset of ``root``'s entries whose keys hash
        to global rank r (meta.key_partition over slave_num).

        Each process receives only its own threads' share over the wire
        (the process-level scatter places by ``global_rank // T``), then
        threads split it through shared memory."""
        root_proc, root_thread = self._decompose_root(root)
        N = self.slave_num
        T = self._g.thread_num

        def deposit():
            return dict(d)

        def leader(slots):
            buf = dict(slots[root_thread
                             if self._g.proc_rank == root_proc else 0])
            if self._g.proc is not None:
                self._g.proc.scatter_map(
                    buf, operand, root=root_proc,
                    partitioner=lambda k: meta.key_partition(k, N) // T)
            return buf

        def collect(result):
            mine = {k: v for k, v in result.items()
                    if meta.key_partition(k, N) == self.rank}
            d.clear()
            d.update(self._copied_map(mine))
            return d

        return self._fan_in_out(deposit, leader, collect)

    def reduce_scatter_map(self, d: dict,
                           operand: Operand = Operands.DOUBLE,
                           operator: Operator = Operators.SUM) -> dict:
        """Key-union reduce, keep this global rank's hash share. Tree
        reduce to global rank 0, then partitioned scatter (each process
        only receives its threads' share)."""
        self.reduce_map(d, operand, operator, root=0)
        return self.scatter_map(d, operand, root=0)

    # ------------------------------------------------------------------
    # nonblocking collectives (ISSUE 11): the thread backend's
    # collectives are shared-memory synchronous — every thread of the
    # group must enter the same call before any can leave — so the i*
    # twins execute eagerly and return resolved futures; the futures-
    # conformance contract (i*().wait() == blocking, bit-for-bit)
    # holds trivially, and portable code keeps one API across backends.
    # ------------------------------------------------------------------
    def iallreduce(self, arr, operand: Operand = Operands.FLOAT,
                   operator: Operator = Operators.SUM,
                   from_: int = 0, to: int | None = None,
                   algo: str = "auto"):
        """Eager nonblocking :meth:`allreduce_array` (resolved
        future)."""
        return progress_mod.eager_future(
            self, "allreduce_array", arr, operand, operator,
            from_=from_, to=to, algo=algo)

    def ireduce_scatter(self, arr, operand: Operand = Operands.FLOAT,
                        operator: Operator = Operators.SUM,
                        ranges=None, algo: str = "auto"):
        """Eager nonblocking :meth:`reduce_scatter_array`."""
        return progress_mod.eager_future(
            self, "reduce_scatter_array", arr, operand, operator,
            ranges=ranges, algo=algo)

    def iallgather(self, arr, operand: Operand = Operands.FLOAT,
                   ranges=None, algo: str = "auto"):
        """Eager nonblocking :meth:`allgather_array`."""
        return progress_mod.eager_future(
            self, "allgather_array", arr, operand, ranges=ranges,
            algo=algo)

    def igather(self, arr, operand: Operand = Operands.FLOAT,
                root: int = 0, ranges=None):
        """Eager nonblocking :meth:`gather_array`."""
        return progress_mod.eager_future(
            self, "gather_array", arr, operand, root=root,
            ranges=ranges)

    def iallreduce_map(self, d: dict,
                       operand: Operand = Operands.DOUBLE,
                       operator: Operator = Operators.SUM):
        """Eager nonblocking :meth:`allreduce_map`."""
        return progress_mod.eager_future(
            self, "allreduce_map", d, operand, operator)

    def wait_all(self, timeout: float | None = None) -> None:
        """Collective-boundary drain; the eager backend never has
        outstanding work — no-op, kept for portable code."""


# per-collective tracing (utils.trace; zero overhead when disabled)
trace.instrument(ThreadCommSlave)
