"""Process-level slave — the CPU socket reference path.

Faithful to the reference's design (SURVEY.md sections 2, 3a-3c): each
slave owns a listen socket plus lazily-established peer TCP connections,
registers with the rendezvous master to obtain its rank and the roster,
and implements all 7 collectives over {dense array, sparse map} operands
with in-place buffer semantics. ``info()/error()`` forward to the
master's console; ``barrier()``/``close(code)`` coordinate through the
master (SURVEY.md section 3e).

Algorithms: allreduce/reduce_scatter/allgather default to
``algo="auto"`` — size-aware selection (``utils.tuning``) between the
binomial tree (latency-bound small payloads), the reference's
MPICH-style Rabenseifner path — reduce-scatter by RECURSIVE HALVING +
allgather by RECURSIVE DOUBLING, with non-power-of-2 rank counts folded
in by a pre/post step (the "Kryo-socket recursive-halving path" of
BASELINE.json; SURVEY.md section 3b) — and the pipelined ring
(bandwidth-bound large payloads). Each step's transfer is split into
``MP4J_CHUNK_BYTES`` chunks so the merge of chunk k overlaps the wire
transfer of chunk k+1 (see ``_chunked_exchange``). Broadcast/reduce
are binomial trees; rooted gather/scatter are direct sends.

The per-round element-wise merge (the reference's CPU hot loop, SURVEY.md
section 3b step 2) runs through the native C++ kernel
(``utils.native.reduce_into``); receive scratch comes from a per-dtype
buffer pool, and ``stats()`` reports per-collective wire/reduce/
serialize phase counters (``utils.stats``).

This path is also the semantic oracle the TPU path is differentially
tested against, and the baseline the >=10x TPU bandwidth claim is
measured against (BASELINE.md).
"""

from __future__ import annotations

import copy
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm import keycodec
from ytk_mp4j_tpu.comm import master as master_mod
from ytk_mp4j_tpu.comm import progress as progress_mod
from ytk_mp4j_tpu.comm.context import CommSlave
from ytk_mp4j_tpu.obs import audit as audit_mod
from ytk_mp4j_tpu.obs import health as health_mod
from ytk_mp4j_tpu.obs import metrics as metrics_mod
from ytk_mp4j_tpu.obs import postmortem
from ytk_mp4j_tpu.obs import sink as sink_mod
from ytk_mp4j_tpu.obs import spans as spans_mod
from ytk_mp4j_tpu.ops import sparse as sparse_ops
from ytk_mp4j_tpu.exceptions import (
    Mp4jError, Mp4jFatalError, Mp4jSpareReleased, Mp4jTransportError)
from ytk_mp4j_tpu.operands import Operand, Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.resilience import faults as faults_mod
from ytk_mp4j_tpu.resilience import membership as membership_mod
from ytk_mp4j_tpu.resilience.recovery import RecoveryManager
from ytk_mp4j_tpu.transport import shm as shm_mod
from ytk_mp4j_tpu.transport import tcp as tcp_mod
from ytk_mp4j_tpu.transport.channel import Channel, _raw_view
from ytk_mp4j_tpu.transport.tcp import connect
from ytk_mp4j_tpu.utils import native, trace, tuning
from ytk_mp4j_tpu.utils import stats as stats_mod
from ytk_mp4j_tpu.utils import tuner as tuner_mod
from ytk_mp4j_tpu.utils.stats import CommStats

import functools


class _ScratchPool:
    """Per-dtype reusable scratch buffers for collective steps.

    ``take(dtype, n)`` returns a length-``n`` view of a pooled (or
    fresh) contiguous buffer; ``give(view)`` returns the underlying
    buffer for reuse. Reuse matters on the hot path: a fresh
    ``np.empty`` per round re-pays mmap + first-touch page faults for
    every MB received, a full extra memory pass.

    Discipline: take/give pairs are owned by the collective's calling
    thread (no locking — a slave runs one collective at a time); give
    only what was taken, after the last read of it. The free list is
    capped, so a one-off giant collective cannot pin more than a few
    peak-sized buffers per dtype.
    """

    _MAX_FREE = 4

    def __init__(self):
        self._free: dict[np.dtype, list[np.ndarray]] = {}

    def take(self, dtype, n: int) -> np.ndarray:
        dt = np.dtype(dtype)
        free = self._free.get(dt)
        if free:
            best = None
            for i, b in enumerate(free):
                if b.size >= n and (best is None
                                    or b.size < free[best].size):
                    best = i
            if best is not None:
                return free.pop(best)[:n]
        return np.empty(max(n, 1), dtype=dt)[:n]

    def give(self, arr: np.ndarray) -> None:
        base = arr.base if isinstance(arr.base, np.ndarray) else arr
        free = self._free.setdefault(base.dtype, [])
        if len(free) < self._MAX_FREE:
            free.append(base)
            return
        # full list: keep the PEAK-sized buffers (evict the smallest
        # for a larger incomer) — a handful of small early collectives
        # must not permanently defeat pooling for the MB-scale rounds
        # the pool exists for
        smallest = min(range(len(free)), key=lambda i: free[i].size)
        if free[smallest].size < base.size:
            free[smallest] = base


class ProcessCommSlave(CommSlave):
    """A rank in a multi-process (TCP) mp4j job.

    Construction blocks until all expected slaves have registered with
    the master (reference behavior, SURVEY.md section 3a).
    """

    def __init__(self, master_host: str, master_port: int,
                 listen_host: str = "127.0.0.1",
                 timeout: float | None = 120.0,
                 peer_timeout: float | None = None,
                 handshake_timeout: float | None = 30.0,
                 native_transport: bool = True,
                 shm: bool | None = None,
                 host_fp: str | None = None,
                 map_columnar: bool | None = None,
                 max_retries: int | None = None,
                 reconnect_backoff: float | None = None,
                 dead_rank_secs: float | None = None,
                 fault_plan=None,
                 postmortem_dir: str | None = None,
                 audit: str | None = None,
                 sink_dir: str | None = None,
                 elastic: str | None = None,
                 spare: bool = False,
                 async_collectives: bool | None = None,
                 health: bool | None = None,
                 tuner: str | None = None):
        """``timeout`` bounds rendezvous/connect; ``peer_timeout`` (None =
        the reference's fail-stop hang) bounds each peer receive during
        collectives, turning a dead peer into an Mp4jError.
        ``handshake_timeout`` bounds the rank exchange on each inbound
        peer connection so a stray/half-dead dial-in cannot wedge the
        accept loop that every healthy peer depends on.

        ``native_transport`` enables the raw (unframed) data plane for
        numeric uncompressed operands — the C++ poll loop when the
        native library builds, a wire-identical pure-Python raw path
        otherwise. It is a JOB-wide wire-protocol choice: every slave in
        a job must pass the same value (the raw/framed decision must
        match on both ends of every exchange). False keeps the fully
        framed Python path — the frozen reference baseline bench.py
        measures against.

        ``shm`` (None reads ``MP4J_SHM``, default on) lets rendezvous
        negotiate the intra-host shared-memory transport (ISSUE 7): a
        dialing slave whose host fingerprint matches the peer's roster
        entry creates a shm ring pair and names it in the peer
        handshake; every other pair keeps TCP. JOB-wide like
        ``native_transport`` — every slave must agree on whether shm
        may be offered (the per-pair decision then rides the
        handshake, so both ends of one channel always agree).
        ``host_fp`` overrides the detected host fingerprint (testing +
        ops seam: partition co-located ranks into virtual hosts, or
        pin two cells apart); ranks only pair over shm — and the
        topology-aware two-level schedule only groups them — when
        their fingerprints are EQUAL and non-empty.

        ``map_columnar`` selects the map-collective wire plane for
        numeric operands (None reads ``MP4J_MAP_COLUMNAR``, default
        on): the columnar (codes, values) data plane, or False for the
        pickled-dict reference path. JOB-wide like ``native_transport``
        — every slave must agree (see the map-collective section
        comment).

        Resilience (ISSUE 5, all None -> env): ``max_retries``
        (``MP4J_MAX_RETRIES``) bounds the epoch-fenced abort/retry
        rounds per failed collective — 0 restores the reference's
        fail-stop; ``reconnect_backoff`` (``MP4J_RECONNECT_BACKOFF``)
        is the base of the capped exponential re-dial backoff;
        ``dead_rank_secs`` (``MP4J_DEAD_RANK_SECS``) bounds every
        recovery wait before the job goes terminal. ``fault_plan``
        (``MP4J_FAULT_PLAN``; a grammar string or a
        :class:`~ytk_mp4j_tpu.resilience.faults.FaultPlan`) arms
        deterministic fault injection on this rank's data plane —
        chaos-test machinery, never on by default.

        ``postmortem_dir`` (None reads ``MP4J_POSTMORTEM_DIR``; empty
        disables) arms the flight recorder (ISSUE 6): on any terminal
        abort this rank dumps a postmortem bundle (span-ring Chrome
        trace, stats snapshot, metric histograms, epoch/retry log)
        there before raising.

        ``audit`` (ISSUE 8; None reads ``MP4J_AUDIT``, default
        ``digest``) selects the correctness-auditing mode —
        ``off|digest|verify|capture`` (:mod:`ytk_mp4j_tpu.obs.audit`).
        JOB-wide like ``native_transport``: cross-rank digest
        comparison assumes every rank digests the same schedule the
        same way.

        ``sink_dir`` (ISSUE 9; None reads ``MP4J_SINK_DIR``, gated by
        ``MP4J_SINK``; empty disables) arms the durable streaming
        telemetry sink: a background thread drains this rank's span/
        stats/metrics/audit/recovery rings into crc-framed rotating
        segment files under ``<sink_dir>/rank_NNNN/`` (per-rank disk
        budget ``MP4J_SINK_BYTES``, oldest-segment eviction), so
        ``mp4j-scope analyze``/``tail`` can reconstruct full-job
        cross-rank timelines and critical-path attribution — ring
        tails no longer bound history.

        ``elastic`` (ISSUE 10; None reads ``MP4J_ELASTIC``) is the
        job's elastic-membership mode, validated here like every other
        job-wide knob — including the fail-stop conflict rule: an
        elastic mode next to ``max_retries=0`` raises at construction
        (the fenced retry is the mechanism that re-runs the
        interrupted collective after a membership change).

        ``async_collectives`` (ISSUE 11; None reads ``MP4J_ASYNC``,
        default on) selects how the nonblocking ``i*`` methods
        execute: on the per-slave helper progression thread
        (``comm/progress.py`` — many outstanding collectives driven
        through one poll loop, with wire/reduce overlap across them),
        or — when False — eagerly on the caller's thread, returning
        already-resolved futures. A LOCAL execution-strategy choice
        (wire-identical either way), unlike the JOB-wide
        ``MP4J_COALESCE_USECS`` coalescing window also validated
        here.

        ``health`` (ISSUE 12; None reads ``MP4J_HEALTH``, default on)
        arms this rank's half of the streaming health plane: each
        heartbeat also carries the rank's completed per-ordinal span
        cells (``health_delta`` — the live feed the master's online
        dominator attribution consumes) and the control thread lands
        the master's health-alert pushes in the recovery log and the
        durable sink's ``alerts`` records. Run every rank with the
        same value — a rank with it off ships no cells, so the master
        can attribute nothing.

        ``tuner`` (ISSUE 15; None reads ``MP4J_TUNER``, default
        ``observe``) arms this rank's half of the self-tuning data
        plane (:mod:`ytk_mp4j_tpu.utils.tuner`): the heartbeat thread
        folds the rolling per-link wire stats into decision windows,
        and — in ``act`` mode — committed per-link ``(chunk_bytes,
        compress, socket-buffer)`` decisions apply at the NEXT
        outermost-collective boundary (never mid-collective). The
        framed wire format is receiver-auto-detected, so sender-side
        decisions cannot desync a pair; links with shm traffic keep
        the job-wide chunk schedule (it is part of the shm wire
        contract). Any cross-rank audit divergence trips the tuner
        back to static defaults for the job's lifetime.

        ``spare=True`` registers this slave as a WARM SPARE (ISSUE 10)
        instead of claiming a rank: construction blocks — pinging the
        master from a background thread — until the master adopts it
        into a dead rank's id (the constructor then returns a fully
        seeded member of the running job: the dead rank's id at the
        current epoch, the columnar keycodec vocabularies, the resume
        ordinal in :attr:`resume_seq` and barrier position in
        :attr:`resume_barrier_gen`, and the cross-rank-verified audit
        watermark) or releases it (``Mp4jSpareReleased`` — the job
        ended without needing this spare)."""
        self._timeout = timeout
        self._peer_timeout = peer_timeout
        self._handshake_timeout = handshake_timeout
        self._native_transport = native_transport
        # resilience knobs, env-validated up front like the transport
        # tuning below
        self._max_retries = (tuning.max_retries() if max_retries is None
                             else int(max_retries))
        if self._max_retries < 0:
            raise Mp4jError(f"max_retries={max_retries} must be >= 0")
        self._reconnect_backoff = (tuning.reconnect_backoff()
                                   if reconnect_backoff is None
                                   else float(reconnect_backoff))
        self._dead_rank_secs = tuning.dead_rank_secs(dead_rank_secs)
        # elastic membership (ISSUE 10): the master drives the
        # protocol, but the mode is validated on EVERY rank — the
        # fail-stop conflict (elastic + max_retries=0) must fail the
        # job at setup, never silently pick a winner
        self._elastic = tuning.elastic_mode(elastic,
                                            max_retries=self._max_retries)
        self._spare = bool(spare)
        if fault_plan is None:
            spec = tuning.fault_plan_spec()
            fault_plan = faults_mod.FaultPlan.parse(spec) if spec else None
        elif isinstance(fault_plan, str):
            fault_plan = faults_mod.FaultPlan.parse(fault_plan)
        self._fault_plan = fault_plan
        self._postmortem_dir = (tuning.postmortem_dir()
                                if postmortem_dir is None
                                else str(postmortem_dir))
        self._pm_done = False
        # durable sink (ISSUE 9): dir + enable validated up front like
        # every other knob; the writer itself starts after rendezvous
        # (it needs the rank)
        if sink_dir is None:
            self._sink_dir = (tuning.sink_dir()
                              if tuning.sink_enabled() else "")
        else:
            self._sink_dir = str(sink_dir)
        self._sink: sink_mod.SinkWriter | None = None
        # health plane (ISSUE 12): knob validated up front like every
        # other; the span folder itself starts after rendezvous (it
        # needs the rank), the alert log exists unconditionally so a
        # master running health against a health-off slave still
        # lands its pushes somewhere durable
        self._health_on = tuning.health_enabled(health)
        self._health_folder: health_mod.SpanFolder | None = None
        self._health_alerts = health_mod.AlertLog()
        # job-wide transport tuning (env-validated here, before any
        # connection exists, so a typo'd knob fails the job cleanly)
        # and pipeline state — all of it must exist BEFORE the accept
        # thread starts: an early peer dial-in races __init__
        self._chunk_bytes = tuning.chunk_bytes()
        self._algo_small, self._algo_large = tuning.algo_thresholds()
        self._shm = tuning.shm_enabled() if shm is None else bool(shm)
        self._shm_ring_bytes = tuning.shm_ring_bytes()
        # host fingerprint (ISSUE 7): rides registration into the
        # roster; "" (shm off) makes this rank fingerprint-match
        # nobody, so every pair it joins keeps TCP
        if not self._shm:
            self._fp = ""
        elif host_fp is not None:
            self._fp = str(host_fp)
        else:
            self._fp = shm_mod.host_fingerprint()
        self._map_columnar = (tuning.map_columnar_enabled()
                              if map_columnar is None
                              else bool(map_columnar))
        # nonblocking collectives (ISSUE 11): knobs validated up front
        # like every other; the scheduler itself starts lazily on the
        # first i* submission, so a fully blocking job pays nothing
        self._async_on = (tuning.async_enabled()
                          if async_collectives is None
                          else bool(async_collectives))
        self._coalesce_usecs = tuning.coalesce_usecs()
        self._max_outstanding = tuning.max_outstanding()
        # self-tuning data plane (ISSUE 15): mode + window validated
        # up front like every other knob; the policy core runs on the
        # heartbeat thread, decisions apply at outermost-collective
        # boundaries only (the recovery wrapper drains the queue)
        self._tuner_mode = tuning.tuner_mode(tuner)
        self._tuner_window = tuning.tuner_window_secs()
        self._so_buf_map = tuning.so_buf_map()
        self._tuner: tuner_mod.LinkTuner | None = (
            tuner_mod.LinkTuner(self._tuner_mode, self._chunk_bytes,
                                self._so_buf_map)
            if self._tuner_mode != "off" else None)
        self._tuner_next = 0.0   # heartbeat-thread pacing (monotonic)
        # fenced leader overrides (ISSUE 15): written only by the ctl
        # thread inside a master tuner fence (every rank parked at the
        # same boundary) and reset by _set_roster on any membership
        # change — two-level schedules read the derived _leaders list
        self._leader_overrides: dict[int, int] = {}
        self._async: progress_mod.ProgressScheduler | None = None
        self._async_lock = threading.Lock()
        self._eager_failed: list = []   # MP4J_ASYNC=0 failures for
        # wait_all's re-raise contract (caller thread only)
        # persistent key<->code vocabularies for the columnar map
        # plane, kept IDENTICAL across ranks (grown only inside the
        # synchronized novelty exchange — see _map_sync)
        self._map_codecs: dict[str, object] = {}
        # pre-attempt codec sizes of the collective in flight (set by
        # the recovery wrapper's preserve): the adoption manifest's
        # vocabulary export pins to these — a failed map attempt's
        # tentative growth must not reach a joining spare when every
        # survivor's retry is about to truncate it away (ISSUE 10)
        self._codec_pin: dict | None = None
        self._scratch = _ScratchPool()
        self._comm_stats = CommStats()
        # audit plane (ISSUE 8): mode validated up front like every
        # other job-wide knob; ``off`` keeps _audit None so the hot
        # path pays one attribute check
        audit_mode = tuning.audit_mode(audit)
        self._audit = (None if audit_mode == "off"
                       else audit_mod.AuditRing(audit_mode))
        self._comm_stats.audit = self._audit  # channels reach it here
        # own listen socket on an ephemeral port. Buffer-size knobs
        # apply BEFORE listen(): accepted peer sockets inherit them,
        # and the TCP window scale is fixed at the handshake.
        # sanctioned raw-socket site: the slave's own listen socket IS
        # the rendezvous surface peers negotiate transports over
        # (mp4j-lint R12 baseline)
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        tcp_mod.apply_socket_buf_sizes(self._server)
        self._server.bind((listen_host, 0))
        self._server.listen(64)
        self._listen_port = self._server.getsockname()[1]
        self._listen_host = listen_host

        # register with master; blocks until roster is complete.
        # ``timeout`` bounds the whole rendezvous exchange, not just the
        # TCP connect: a wedged master surfaces as Mp4jError, not a hang.
        self._master = connect(master_host, master_port, timeout=timeout)
        self._master.set_timeout(timeout)
        self._master.send_obj((master_mod.REGISTER, {
            "listen_port": self._listen_port, "host": listen_host,
            "fp": self._fp, "spare": self._spare}))
        reply = self._master.recv()
        adopt_info = None
        if self._spare:
            # blocks (pinging) until the master adopts this spare into
            # a dead rank's id — or releases it (ISSUE 10)
            reply, adopt_info = self._spare_wait(reply)
        self._rank = reply["rank"]
        self._roster_version = 0
        self._set_roster(reply["roster"])
        # job id namespaces this job's shm segment names
        self._job_id = str(reply.get("job") or "0")
        # after rendezvous the master channel is fail-stop (barrier
        # waits are unbounded by design, see barrier())
        self._master.set_timeout(None)
        # all further master-channel sends share one lock: the
        # heartbeat thread interleaving frame bytes with a barrier or
        # log send would corrupt the control plane
        self._master_lock = threading.Lock()
        # heartbeat delta state (ISSUE 6): the last stats/metrics
        # snapshots shipped to the master, so every beat carries only
        # what changed since. One lock serializes the heartbeat
        # thread, the DIAGNOSE hook and close's final flush; it NEVER
        # nests inside _master_lock (deadlock discipline: payload
        # first, then send). Created before _sync_identity — the rank
        # mirror publishes under it.
        self._tel_lock = threading.Lock()
        self._tel_last_stats: dict = {}
        self._tel_last_metrics: dict = {}
        self._sync_identity()

        # peer channels: canonical rule — the HIGHER rank connects to the
        # lower rank's listen socket; one duplex channel per pair.
        self._peers: dict[int, Channel] = {}
        self._peer_cv = threading.Condition()
        self._dead_channels: list[Channel] = []   # torn down, fd alive

        # recovery engine + control-plane receiver (ISSUE 5). The
        # control thread is the ONLY reader of the master channel from
        # here on: barrier releases, close acks and abort fan-outs are
        # demultiplexed through it, so an asynchronous abort push can
        # never interleave with a barrier reply.
        # (outermost collectives entered, one currently in flight) as
        # ONE tuple-valued attribute: the control thread samples it for
        # the abort ack, and a two-field sample could tear between the
        # ordinal bump and the in-flight flag — the master would read
        # "idle at m+1" next to in-flight ranks retrying m+1 as a
        # collective-boundary fault and kill a recoverable job
        self._progress_state = (0, False)
        self._faults = None
        if self._fault_plan is not None:
            inj = faults_mod.FaultInjector(self._fault_plan, self._rank)
            if not inj.empty:
                self._faults = inj
        self._recovery = RecoveryManager(
            rank=self._rank, max_retries=self._max_retries,
            dead_rank_secs=self._dead_rank_secs,
            send_ctl=lambda kind, payload: self._master_send(
                (kind, payload)),
            teardown=self._teardown_peers, stats=self._comm_stats,
            wake=self._ctl_wake, drain=self._drain_dead_channels,
            progress=lambda: self._progress_state,
            terminal_hook=self._on_terminal_abort)
        self._ctl_cv = threading.Condition()
        self._barrier_released: set[int] = set()
        self._closed_ack = threading.Event()
        self._closed = False    # before the ctl thread can observe it
        self._ctl_thread = threading.Thread(
            target=self._ctl_loop, daemon=True,
            name=f"mp4j-ctl-r{self._rank}")
        self._ctl_thread.start()

        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"mp4j-accept-r{self._rank}")
        self._accept_thread.start()
        # paired send/recv helper (avoids head-of-line deadlock on large
        # simultaneous exchanges)
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"mp4j-send-r{self._rank}")
        # outstanding helper-thread sends; only the collective thread
        # touches this (submit + the drain barrier), no lock needed
        self._send_futs: list = []
        self._barrier_gen = 0
        # barrier generations COMPLETED (vs. _barrier_gen = entered):
        # the adoption manifest ships this count so a joiner's next
        # barrier call pairs with the survivors' (ISSUE 10)
        self._barrier_done = 0
        # resize-point generations (ISSUE 13): entered / completed
        # counts mirror the barrier pair; the ctl thread parks results
        # per generation until resize_point() collects them
        self._resize_gen = 0
        self._resize_done = 0
        self._resize_results: dict[int, dict] = {}
        # adoption resume position (0 on ordinary members): the
        # application reads these to know where the job already is
        self.resume_seq = 0
        self.resume_barrier_gen = 0
        if adopt_info is not None:
            self._adopt_seed(adopt_info)
            # ack BEFORE the heartbeat thread exists: the master's
            # spare serve thread switches into the rank's serve loop
            # on this message, and a TELEMETRY frame arriving first
            # would hit the spare-side dispatch
            self._master_send((master_mod.ADOPT_ACK,
                               {"rank": self._rank}))
        # telemetry heartbeat (control plane only — never touches the
        # peer data channels, so it cannot block a collective): ships
        # {progress, stats} to the master every MP4J_HEARTBEAT_SECS
        # (0 disables), feeding the cluster skew table and giving hang
        # diagnosis a last-known position for THIS rank even when it is
        # the one that stalls
        self._hb_stop = threading.Event()
        self._hb_secs = tuning.heartbeat_secs()
        # health plane (ISSUE 12): the span folder needs the rank —
        # it filters the process-global ring (thread-backed multi-
        # slave processes share it) and folds completed ordinals into
        # the heartbeat's health_delta cells
        if self._health_on and spans_mod.enabled():
            # mp4j-lint: disable=R15 (retargeted by _sync_identity on renumbering)
            self._health_folder = health_mod.SpanFolder(self._rank)
        self._hb_thread: threading.Thread | None = None
        if self._hb_secs > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"mp4j-hb-r{self._rank}")
            self._hb_thread.start()
        # durable sink drain thread (ISSUE 9) — control plane only,
        # off the collective hot path entirely (the hot path pays the
        # ring appends it already paid)
        if self._sink_dir:
            self._sink = sink_mod.SinkWriter(
                self._sink_dir, self._rank, slave_num=self._n,
                stats=self._comm_stats, audit=self._audit,
                recovery=self._recovery,
                alerts=self._health_alerts).start()

    # ------------------------------------------------------------------
    # identity / control plane
    # ------------------------------------------------------------------
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def slave_num(self) -> int:
        return self._n

    def metrics_registry(self):
        """This rank's live :class:`~ytk_mp4j_tpu.obs.metrics.
        MetricsRegistry` — the sanctioned write surface for planes
        layered ON the comm (the serve frontend's latency/QPS/cache
        families ride the same heartbeat deltas as the collective
        stats; ISSUE 19)."""
        return self._comm_stats.metrics

    def _master_send(self, obj) -> None:
        """Serialized master-channel send (shared by the caller's
        control messages and the heartbeat thread)."""
        with self._master_lock:
            if self._closed:
                raise Mp4jError("slave is closed")
            self._master.send_obj(obj)

    def info(self, msg: str) -> None:
        self._master_send((master_mod.LOG, {"level": "INFO", "msg": msg}))

    def error(self, msg: str) -> None:
        self._master_send((master_mod.LOG, {"level": "ERROR", "msg": msg}))

    def barrier(self) -> None:
        # the collective-boundary drain (ISSUE 11): outstanding
        # nonblocking collectives complete before the barrier so the
        # job-wide collective order stays the submit order
        if self._async is not None:
            self._async.drain_for_blocking()
        gen = self._barrier_gen
        self._barrier_gen += 1
        self._master_send((master_mod.BARRIER, {"gen": gen}))
        with self._ctl_cv:
            # the release waits on the slowest rank indefinitely — the
            # reference's fail-stop contract, not a missing timeout —
            # but a terminal abort (dead rank, watchdog escalation)
            # breaks the wait with the cluster-wide error
            self._ctl_cv.wait_for(
                lambda: gen in self._barrier_released
                or self._recovery.fatal is not None)
            if gen in self._barrier_released:
                self._barrier_released.discard(gen)
                # completed-generation count: the adoption manifest's
                # barrier seed (ISSUE 10) — every rank that PASSED
                # this barrier agrees on it, waiting ranks still show
                # the previous value
                self._barrier_done = gen + 1
                return
        raise self._recovery.fatal_exc()

    def resize_point(self) -> list:
        """An explicit APP EPOCH BOUNDARY the roster may change at
        (ISSUE 13 grow mode): every rank calls this at the same point
        in its schedule (like :meth:`barrier`); under
        ``MP4J_ELASTIC=grow`` + ``MP4J_AUTOSCALE=act`` the master
        adopts registered warm spares into NEW rank ids here —
        EXPANDING ``slave_num`` between epochs — and every rank
        returns the (possibly grown) roster. The adopted joiners'
        constructors return fully seeded (``resume_seq`` names the
        collective ordinal the job is at), exactly like replacement
        adoption. With growth unavailable (mode off, no spares, rails
        closed) this is a no-op rendezvous returning the current
        roster.

        Rank 0's call donates the canonical columnar vocabulary for
        the joiners' seed — at a quiesced boundary every rank's codec
        tables are identical by construction (they only ever grow
        inside the synchronized novelty exchange)."""
        if self._async is not None:
            # the collective-boundary drain, like barrier(): the
            # roster must not change under outstanding futures
            self._async.drain_for_blocking()
        gen = self._resize_gen
        self._resize_gen += 1
        payload = {"gen": gen, "seq": self._progress_state[0],
                   "stats_seq": self._comm_stats.progress()["seq"],
                   "barrier_gen": self._barrier_done}
        if self._rank == 0:
            payload["vocab"] = self._vocab_export()
        self._master_send((master_mod.RESIZE, payload))
        with self._ctl_cv:
            # unbounded like barrier(): the release waits on the
            # slowest rank; a terminal abort (or an eviction) breaks
            # the wait with the cluster-wide error
            self._ctl_cv.wait_for(
                lambda: gen in self._resize_results
                or self._recovery.fatal is not None)
            if gen in self._resize_results:
                self._resize_results.pop(gen)
                self._resize_done = gen + 1
                return list(self._roster)
        raise self._recovery.fatal_exc()

    # -- control-plane receiver (ISSUE 5) -------------------------------
    @property
    def epoch(self) -> int:
        """The job-wide recovery epoch this rank has been released
        into (0 until the first abort round completes)."""
        return self._recovery.epoch

    def _ctl_wake(self) -> None:
        with self._ctl_cv:
            self._ctl_cv.notify_all()
        with self._peer_cv:
            self._peer_cv.notify_all()

    def _teardown_peers(self) -> None:
        """Invalidate every peer channel and forget it — the DRAIN of
        an abort round: in-flight frames of the old epoch die with
        their sockets (raw and framed planes alike), and any
        collective blocked on one of them unblocks with a transport
        error. The channels are only SHUT DOWN here, not closed: the
        fd release is deferred to the collective thread
        (:meth:`_drain_dead_channels`) so a native poll still
        unwinding cannot race a re-dial onto a recycled fd number.
        Idempotent; runs on the control thread."""
        with self._peer_cv:
            chans = list(self._peers.values())
            self._peers.clear()
            self._dead_channels.extend(chans)
            self._peer_cv.notify_all()
        for ch in chans:
            ch.invalidate()

    def _drain_dead_channels(self) -> None:
        """Release the fds of torn-down channels. Called from the
        COLLECTIVE thread between attempts (and at close): the
        previous attempt has fully unwound, so no native call can
        still hold these raw fd numbers — only now is fd reuse safe.

        "Fully unwound" must cover the send-helper thread too: a recv
        that raised first abandons its paired send future, and that
        worker may still be entering sendall on a torn fd — wait for
        every outstanding send (bounded: the teardown's shutdown()
        errors them out) before any fd is freed for reuse."""
        futs, self._send_futs = self._send_futs, []
        for f in futs:
            try:
                f.result(timeout=5.0)
            # Not a data path: these futures belong to a torn-down
            # attempt and are expected to error — the wait exists only
            # to fence fd reuse; the failure was already reported by
            # the recv that triggered the teardown.
            # mp4j-lint: disable=R5 (expected errors from torn-channel sends)
            except Exception:
                pass
        with self._peer_cv:
            chans = list(self._dead_channels)
            self._dead_channels.clear()
        for ch in chans:
            try:
                ch.close()
            except OSError:
                pass

    def _ctl_loop(self) -> None:
        """The single reader of the master channel after rendezvous:
        demultiplexes barrier releases, the close ack, and the
        recovery protocol's asynchronous abort pushes. Must stay alive
        while any collective blocks — delivering an abort is what
        unhangs it."""
        while True:
            try:
                msg = self._master.recv()
            except (Mp4jError, OSError, EOFError) as e:
                with self._master_lock:
                    closed = self._closed
                if not closed:
                    self._recovery.on_fatal(
                        f"master connection lost: {e!r}")
                    self._ctl_wake()
                return
            if msg == "closed":
                self._closed_ack.set()
                self._ctl_wake()
                return
            kind = msg[0] if isinstance(msg, tuple) and msg else None
            try:
                if kind == "barrier_release":
                    with self._ctl_cv:
                        self._barrier_released.add(msg[1])
                        self._ctl_cv.notify_all()
                elif kind == "abort":
                    self._recovery.on_abort(int(msg[1]))
                elif kind == "abort_go":
                    # a membership go (ISSUE 10) carries the roster
                    # change; it must land BEFORE the epoch release
                    # wakes any retry — the re-dials read the roster
                    if len(msg) > 2 and msg[2]:
                        self._apply_membership(msg[2])
                    self._recovery.on_go(int(msg[1]))
                elif kind == "manifest_req":
                    # the master needs this survivor's adoption
                    # manifest (ISSUE 10): vocabulary export + progress
                    # + barrier position, all quiescent while the
                    # collective thread waits out the round
                    with self._ctl_cv:
                        barrier_gen = self._barrier_done
                        resize_gen = self._resize_done
                    try:
                        self._master_send((master_mod.MANIFEST, {
                            "epoch": int(msg[1]),
                            "vocab": self._vocab_export(),
                            "seq": self._progress_state[0],
                            "inflight": self._progress_state[1],
                            "stats_seq": self._comm_stats.progress()[
                                "seq"],
                            "barrier_gen": barrier_gen,
                            "resize_gen": resize_gen,
                        }))
                    except (Mp4jError, OSError):
                        pass  # master gone; its watchdog owns this
                elif kind == "health_alert":
                    # a health-plane verdict transition naming this
                    # rank (or orphaned onto it): land it in the
                    # recovery log and the alert log the durable sink
                    # drains — the evidence must survive the process
                    ev = msg[1] if isinstance(msg[1], dict) else {}
                    self._health_alerts.note(ev)
                    if ev.get("kind") == "autoscale":
                        # controller action events (ISSUE 13) share
                        # the pipe: timelines interleave actions with
                        # the verdicts that caused them
                        self._recovery.note(
                            "autoscale",
                            f"{ev.get('event')} {ev.get('action')}: "
                            f"{ev.get('msg', '')}"[:160])
                    elif ev.get("kind") == "tuner":
                        # tuner controller events (ISSUE 15: demote /
                        # would_demote / trip) — same pipe, logged
                        # under their own kind so mp4j-scope tuner
                        # finds them (the health onset fallback would
                        # render them as "rank None onset (None)")
                        self._recovery.note(
                            "tuner",
                            f"{ev.get('event')}: "
                            f"{ev.get('msg', '')}"[:160])
                    else:
                        self._recovery.note(
                            "health",
                            f"rank {ev.get('rank')} {ev.get('from')}->"
                            f"{ev.get('to')} ({ev.get('detector')})"
                            if ev.get("kind") == "state" else
                            f"rank {ev.get('rank')} onset "
                            f"({ev.get('detector')})")
                elif kind == "tuner_leaders":
                    # fenced tuner topology update (ISSUE 15): lands
                    # while every rank is parked at the same boundary
                    # (the master releases the fence only after this
                    # push), so the leader switch is atomic job-wide
                    ov = msg[1] if isinstance(msg[1], dict) else {}
                    self._apply_leaders(ov)
                    self._recovery.note(
                        "tuner", f"leader overrides {ov or 'cleared'}")
                elif kind == "tuner_trip":
                    # audit divergence under adaptation: back to
                    # static defaults at the next boundary, policy
                    # frozen for the job's lifetime (ISSUE 15)
                    why = str(msg[1])[:300]
                    if self._tuner is not None:
                        self._tuner.trip(why)
                    self._recovery.note("tuner", f"TRIPPED: {why}")
                elif kind == "fence":
                    # eviction fence (ISSUE 13): park at the next
                    # outermost collective boundary, wire untouched
                    self._recovery.on_fence(int(msg[1]))
                elif kind == "fence_advance":
                    self._recovery.on_fence_advance(int(msg[1]),
                                                    int(msg[2]))
                elif kind == "fence_release":
                    self._recovery.on_fence_release(int(msg[1]))
                elif kind == "evicted":
                    # planned eviction (ISSUE 13): this rank's id now
                    # belongs to an adopted spare — every parked wait
                    # breaks with a clean Mp4jEvicted, close() skips
                    # the handshake the master already wrote off
                    self._recovery.on_evicted(str(msg[1]))
                elif kind == "resize_go":
                    # resize release (ISSUE 13): a grown roster lands
                    # BEFORE resize_point() wakes (its re-dials and
                    # the next collective's schedule read it); None
                    # info = no change this generation
                    info = msg[2] if len(msg) > 2 else None
                    if info and "roster" in info:
                        self._set_roster(info["roster"])
                        self._sync_identity()
                        self._recovery.note(
                            "grow",
                            f"roster grew to {self._n} rank(s) "
                            f"(new: {info.get('grown')}) @ resize "
                            f"{msg[1]}")
                    with self._ctl_cv:
                        self._resize_results[int(msg[1])] = info or {}
                        self._ctl_cv.notify_all()
                    self._ctl_wake()
                elif kind == "abort_fatal":
                    self._recovery.on_fatal(str(msg[1]))
                else:
                    # fail fast like the pre-ISSUE-5 barrier reply
                    # check: an unrecognized control frame means the
                    # two sides disagree about the protocol — waiting
                    # would hang
                    self._recovery.on_fatal(
                        f"control protocol violation: unexpected "
                        f"master message {msg!r}")
                    return
            except Exception as e:
                # a malformed-but-tuple frame (('abort',), ('abort',
                # 'x'), ...) must not kill the sole master-channel
                # reader silently: an untimed barrier wait would then
                # hang forever with nobody left to deliver the
                # master's eventual abort — turn it fatal instead
                self._recovery.on_fatal(
                    f"control protocol violation: malformed master "
                    f"message {msg!r} ({e!r})")
                return

    def _fault_kill(self, fault) -> None:
        """Fault-injected death (resilience.faults ``kill``): abruptly
        close every socket this rank owns, as a crashed process would.
        The master sees the control connection die and fans out the
        terminal abort to the survivors."""
        self._hb_stop.set()
        if self._sink is not None:
            self._sink.abort()   # a corpse flushes nothing
        with self._master_lock:
            self._closed = True
        self._teardown_peers()
        try:
            self._master.close()
        except OSError:
            pass
        self._server.close()

    # -- elastic membership: spare mode + roster updates (ISSUE 10) ----
    def _spare_wait(self, reg_reply):
        """Block as a registered warm spare until the master adopts or
        releases this process. A ping thread keeps the spare's
        liveness visible (a silently dead spare must not be the thing
        a replacement round discovers mid-adoption). Returns
        ``(reply, adopt_info)`` where ``reply`` has the shape of a
        normal rendezvous reply."""
        if not (isinstance(reg_reply, dict) and "spare" in reg_reply):
            raise Mp4jError(
                f"master did not accept the spare registration "
                f"(got {reg_reply!r}); is this master elastic-aware?")
        # spares idle indefinitely by design: the rendezvous timeout
        # bounds registration, not the wait for a fault that may
        # never come
        self._master.set_timeout(None)
        lock = threading.Lock()   # ping thread vs. nobody else yet
        stop = threading.Event()

        def ping():
            while not stop.wait(1.0):
                try:
                    with lock:
                        self._master.send_obj(
                            (master_mod.SPARE_PING, {}))
                except (Mp4jError, OSError):
                    return

        t = threading.Thread(target=ping, daemon=True,
                             name="mp4j-spare-ping")
        t.start()
        try:
            while True:
                try:
                    msg = self._master.recv()
                except (Mp4jError, OSError, EOFError) as e:
                    raise Mp4jSpareReleased(
                        f"master connection lost while idling as a "
                        f"spare: {e!r}") from e
                kind = (msg[0] if isinstance(msg, tuple) and msg
                        else None)
                if kind == "adopt":
                    info = msg[1]
                    break
                if kind in ("release", "abort_fatal"):
                    raise Mp4jSpareReleased(str(msg[1]))
                # anything else is master-side noise; keep waiting
        except BaseException:
            stop.set()
            try:
                self._master.close()
            except OSError:
                pass
            self._server.close()
            raise
        stop.set()
        t.join(2.0)
        reply = {"rank": int(info["rank"]), "roster": info["roster"],
                 "job": info.get("job")}
        return reply, info

    def _adopt_seed(self, info: dict) -> None:
        """Seed a just-adopted joiner from the master-held manifest
        (ISSUE 10): the released epoch, the resume ordinal (the
        joiner's next collective pairs with the survivors' retry), the
        barrier generation, the columnar keycodec vocabularies (code
        tables identical to every survivor's post-restore state), and
        the cross-rank-verified audit watermark."""
        epoch = int(info.get("epoch", 0))
        self._recovery.seed(epoch)
        seq = int(info.get("seq", 0))
        self._progress_state = (seq, False)
        self._comm_stats.seed_seq(int(info.get("stats_seq", seq)))
        gen = int(info.get("barrier_gen", 0))
        self._barrier_gen = gen
        self._barrier_done = gen
        # resize position (ISSUE 13): the joiner's next resize_point
        # pairs with the survivors' next one (grow adoptions seed
        # gen+1 of the round that adopted them)
        rz = int(info.get("resize_gen", 0))
        self._resize_gen = rz
        self._resize_done = rz
        self.resume_seq = seq
        self.resume_barrier_gen = gen
        membership_mod.import_vocab(self._map_codecs,
                                    info.get("vocab") or {})
        if self._audit is not None:
            self._audit.watermark = int(info.get("watermark", 0))
        self._comm_stats.add("replacements_seen", 1)
        self._recovery.note(
            "adopted",
            f"rank {self._rank} @ epoch {epoch} seq {seq}"
            + (" (grow)" if info.get("grow") else "")
            + f" ({info.get('why', '')})"[:160])

    def _vocab_export(self) -> dict[str, list]:
        """This rank's keycodec vocabularies for the adoption manifest,
        pinned at the in-flight collective's pre-attempt sizes (see
        ``_codec_pin``). Runs on the CONTROL thread while the
        collective thread is parked in the abort round — the codecs
        are quiescent."""
        return membership_mod.export_vocab(self._map_codecs,
                                           self._codec_pin)

    def _apply_membership(self, info: dict) -> None:
        """Apply a membership go's roster change (control thread, runs
        BEFORE the epoch release wakes any retry — the re-dials must
        see the new roster). Replacement swaps entries under the same
        ids; shrink renumbers this rank and every roster-derived
        quantity through the one sanctioned accessor."""
        shrink = info.get("shrink")
        if shrink is not None:
            mapping = {int(k): int(v)
                       for k, v in shrink["ranks"].items()}
            old_rank = self._rank
            # mp4j-lint: disable=R15 (the renumbering site itself)
            self._rank = mapping[self._rank]
            self._set_roster(shrink["roster"])
            self._sync_identity()
            self._comm_stats.add("shrinks_seen", 1)
            self._recovery.note(
                "shrink",
                f"rank {old_rank}->{self._rank} of {self._n} "
                f"(dropped {shrink.get('departed')}) @ epoch "
                f"{shrink.get('epoch')}")
        elif "roster" in info:
            self._set_roster(info["roster"])
            self._recovery.note(
                "replace",
                f"rank(s) {info.get('replaced')} replaced @ epoch "
                f"{info.get('epoch')}")

    # -- telemetry (control plane only) --------------------------------
    def _telemetry_payload(self) -> dict:
        """The heartbeat message: progress plus stats/metric DELTAS
        since the last payload (ISSUE 6 satellite — a long job's beat
        is bounded by recent activity, not by every collective family
        ever seen). Deltas are additive, so the master may fold them
        in any arrival order; the last-shipped state advances under
        ``_tel_lock`` so concurrent senders never drop or double-ship
        an interval."""
        with self._tel_lock:
            stats = self._comm_stats.snapshot()
            mets = self._comm_stats.metrics.snapshot()
            sd = stats_mod.diff_snapshots(stats, self._tel_last_stats)
            md = metrics_mod.diff_snapshot(mets, self._tel_last_metrics)
            self._tel_last_stats = stats
            self._tel_last_metrics = mets
        prog = self._comm_stats.progress()
        # the recovery epoch rides every beat (ISSUE 10): `mp4j-scope
        # live` renders it next to the membership badges
        prog["epoch"] = self._recovery.epoch
        payload = {"progress": prog,
                   "stats_delta": sd, "metrics_delta": md}
        if self._health_folder is not None:
            # completed per-ordinal span cells (ISSUE 12): the online
            # dominator's live feed — bounded per beat like every
            # other delta, overflow counted, never silent
            hd = self._health_folder.take()
            if hd is not None:
                payload["health_delta"] = hd
        if self._audit is not None:
            # verify/capture ship digest records as deltas (the audit
            # ring keeps its own cursor, bounded like the stats delta);
            # digest mode is record-only and ships nothing
            ad = self._audit.take_delta()
            if ad is not None:
                payload["audit_delta"] = ad
        tun = self._tuner
        if tun is not None:
            # tuner window fold (ISSUE 15): the policy core consumes
            # the per-link stats window here on the heartbeat thread —
            # off the collective hot path — and the committed (or, in
            # observe mode, would-be) decisions land in the recovery
            # log (-> durable sink) and the shipped status document
            # the payload builder runs on the heartbeat thread AND on
            # the terminal-abort hook's final flush: the window gate
            # must be claimed atomically or both fold the same window
            now = time.monotonic()
            with self._tel_lock:
                due = now >= self._tuner_next
                if due:
                    self._tuner_next = now + self._tuner_window
            if due:
                for peer, d in tun.observe(
                        self._comm_stats.link_snapshot()):
                    self._recovery.note(
                        "tuner",
                        f"link->{peer} decided chunk="
                        f"{d.get('chunk_bytes')} compress="
                        f"{d.get('compress')} ({tun.mode})")
            payload["tuner"] = tun.status()
        return payload

    def _heartbeat_loop(self) -> None:
        while True:
            try:
                self._master_send(
                    (master_mod.TELEMETRY, self._telemetry_payload()))
            except (Mp4jError, OSError):
                return  # closed or master gone; telemetry is best-effort
            if self._hb_stop.wait(self._hb_secs):
                return

    def _on_collective_error(self, name: str, exc: BaseException) -> None:
        """Fired by trace.traced when an outermost collective raises:
        best-effort DIAGNOSE to the master, which logs the cluster-wide
        hang diagnosis (who is behind the max sequence number, where,
        how stale) instead of leaving a bare per-rank Mp4jError."""
        try:
            self._master_send((master_mod.DIAGNOSE, {
                "collective": name, "error": repr(exc)[:300],
                **self._telemetry_payload()}))
        except (Mp4jError, OSError):
            pass  # diagnosis is best-effort; the original exc surfaces

    def _on_terminal_abort(self, msg: str) -> None:
        """Recovery's terminal hook (runs once, before the fatal flag
        wakes any waiter): flush the final telemetry delta — so the
        master's last heartbeat table is fresh in postmortems, not
        only after a clean close — then dump this rank's flight-
        recorder bundle."""
        try:
            self._master_send(
                (master_mod.TELEMETRY, self._telemetry_payload()))
        except (Mp4jError, OSError):
            pass  # master may be the thing that died
        if self._sink is not None:
            # the fatal path may never reach close(): drain the rings
            # NOW so the job's last interval is durable before anyone
            # raises (ISSUE 9)
            self._sink.flush()
        self._dump_postmortem(msg)

    def _dump_postmortem(self, reason: str) -> None:
        """Write this rank's postmortem bundle (once, best-effort)."""
        if not self._postmortem_dir or self._pm_done:
            return
        self._pm_done = True
        try:
            postmortem.write_bundle(
                self._postmortem_dir, self._rank, reason=reason,
                progress=self._comm_stats.progress(),
                stats=self._comm_stats.snapshot(),
                metrics=self._comm_stats.metrics.snapshot(),
                epoch=self._recovery.epoch,
                events=self._recovery.events(),
                audit=(self._audit.dump() if self._audit is not None
                       else None),
                sink=(self._sink.status() if self._sink is not None
                      else None))
        except OSError:
            pass  # the recorder must never worsen a dying job

    def close(self, code: int = 0) -> None:
        if self._closed:
            return
        # drain the nonblocking scheduler first (bounded): in-flight
        # futures either complete or fail with the terminal error —
        # close must never strand a waiter (mp4j-lint R16 flags the
        # un-awaited-future-before-close hazard statically)
        if self._async is not None:
            self._async.shutdown()
        self._hb_stop.set()
        # flush-on-close (ISSUE 9): the final collective's spans and
        # deltas reach the segment before the close handshake — a
        # clean job's sink is complete, not one interval short
        if self._sink is not None:
            self._sink.close()
        sent = False
        # final telemetry delta computed OUTSIDE _master_lock (the
        # heartbeat thread takes _tel_lock then _master_lock; nesting
        # them here in the other order would be a lock-order inversion)
        flush = self._telemetry_payload()
        # an EVICTED rank (ISSUE 13) skips the whole handshake: the
        # master already wrote this process off (its rank id belongs
        # to the adopted spare, inbound messages are dropped), so the
        # CLOSE would land nowhere and the "closed" ack would never
        # come — waiting it out would turn every clean eviction into
        # a 5 s shutdown stall
        evicted = self._recovery.evicted
        with self._master_lock:
            if self._closed:
                return
            # final telemetry flush so the master's skew table covers
            # the whole run, then the close handshake
            if not evicted:
                try:
                    self._master.send_obj(
                        (master_mod.TELEMETRY, flush))
                except (Mp4jError, OSError):
                    pass  # master may already be gone; close proceeds
            self._closed = True
            if not evicted:
                try:
                    self._master.send_obj(
                        (master_mod.CLOSE, {"code": code}))
                    sent = True
                except (Mp4jError, OSError):
                    pass
        if sent:
            # the "closed" ack arrives on the control thread; bounded —
            # a vanished master must not wedge shutdown
            self._closed_ack.wait(5.0)
        self._master.close()
        with self._peer_cv:
            peers = list(self._peers.values())
        for ch in peers:
            # graceful: a peer recovering from a late abort round may
            # still be draining our final collective's bytes
            ch.close(graceful=True)
        self._drain_dead_channels()
        self._server.close()
        self._pool.shutdown(wait=False)

    def stats(self) -> dict[str, dict[str, float]]:
        """Per-collective transport counters: ``{collective: {calls,
        bytes_sent, bytes_recv, chunks, wire_seconds, reduce_seconds,
        serialize_seconds}}`` (schema: :mod:`ytk_mp4j_tpu.utils.stats`).
        Always on; phase seconds are busy times and may overlap in wall
        time (pipelining is the point)."""
        return self._comm_stats.snapshot()

    def progress(self) -> dict:
        """This rank's telemetry progress record — the per-slave
        collective sequence number plus current/last collective and
        phase (schema: :mod:`ytk_mp4j_tpu.obs.telemetry`). The same
        record the heartbeat ships to the master."""
        return self._comm_stats.progress()

    def audit_records(self) -> list[dict]:
        """This rank's audit record ring (ISSUE 8; empty when
        ``MP4J_AUDIT=off``): one record per outermost collective —
        ordinal, family, operand signature, input/output digests,
        wire folds (verify) and captured payloads (capture)."""
        return [] if self._audit is None else self._audit.records()

    def dump_audit(self, root: str) -> str | None:
        """Write this rank's ``rank_NNNN/audit.json`` under ``root``
        — the replay-bundle layout (``mp4j-scope replay``); the same
        file joins the postmortem bundle automatically on a terminal
        abort. Returns the path, or None with auditing off."""
        if self._audit is None:
            return None
        return audit_mod.write_rank_audit(root, self._rank,
                                          self._audit.dump())

    def sink_status(self) -> dict | None:
        """The durable sink's health record (ISSUE 9; None when the
        sink is disarmed): segment dir, bytes/records written,
        dropped-record count, eviction count, budget."""
        return None if self._sink is None else self._sink.status()

    def link_stats(self) -> dict[int, dict]:
        """Per-peer-link rolling wire evidence (ISSUE 15): cumulative
        bytes/seconds/frames (split per transport), compression
        outcomes (raw vs wire bytes), and the APPLIED per-link socket
        buffer sizes — the substrate the tuner's decisions are made
        from, and the record of what the transport actually did."""
        return self._comm_stats.link_snapshot()

    def tuner_status(self) -> dict | None:
        """The self-tuning data plane's document (ISSUE 15; None with
        ``MP4J_TUNER=off``): mode, trip state, decision count, and the
        per-link decisions currently applied (or, in observe mode,
        that WOULD apply)."""
        return None if self._tuner is None else self._tuner.status()

    # ------------------------------------------------------------------
    # peer transport
    # ------------------------------------------------------------------
    @staticmethod
    def _derive_host_groups(roster) -> list[list[int]]:
        """Rank groups sharing a host fingerprint (delegates to the
        shared pure function in :mod:`ytk_mp4j_tpu.utils.tuner` —
        ISSUE 15 moved it there so the master's tuner controller and
        the slaves derive topology from ONE implementation)."""
        return tuner_mod.host_groups(roster)

    def _set_roster(self, roster) -> None:
        """THE roster-versioned topology update (mp4j-lint R15's
        sanctioned site): every roster-derived quantity — rank count,
        host groups, this rank's host members, the leader sets — is
        (re)derived here and ONLY here, so a membership change
        (ISSUE 10: replacement swaps a roster entry, shrink renumbers
        the survivors) updates ALL of them atomically with one call.
        Code elsewhere must read these attributes, never re-derive and
        cache its own copy — a long-lived private cache survives the
        renumbering silently wrong (that is rule R15)."""
        # mp4j-lint: disable=R15 (the sanctioned derivation site itself)
        self._roster = list(roster)
        self._n = len(self._roster)
        self._host_groups = tuner_mod.host_groups(self._roster)
        # a membership change invalidates any tuner leader override:
        # the demotion was evidence about the OLD topology (the master
        # re-issues it through a fresh fence if still warranted) — and
        # stale per-link evidence AND decisions must not inherit a
        # renumbered (or replaced) peer id: the LinkTuner resets too,
        # so a fresh process addressed by an old id starts from static
        # defaults, not the old occupant's committed adaptation
        self._leader_overrides = {}
        if self._roster_version > 0:
            stats = getattr(self, "_comm_stats", None)
            if stats is not None:
                stats.forget_links()
            tun = getattr(self, "_tuner", None)
            if tun is not None:
                tun.reset()
        self._members = next(g for g in self._host_groups
                             if self._rank in g)
        self._leader = self._members[0]
        self._leaders = [g[0] for g in self._host_groups]
        self._roster_version += 1

    def _apply_leaders(self, overrides: dict) -> None:
        """Apply a fenced tuner topology update (ISSUE 15): the master
        pushed ``tuner_leaders`` while EVERY rank is parked at the
        same collective boundary, so switching the effective leader
        set here — on the ctl thread, before the fence release wakes
        the collective thread — is atomic job-wide. Derivation rides
        the same pure functions as ``_set_roster``; an override that
        no longer names a member of its group falls back to the
        default leader rather than desyncing the schedule."""
        # mp4j-lint: disable=R15 (fenced job-wide update; reset by _set_roster)
        self._leader_overrides = {int(k): int(v)
                                  for k, v in (overrides or {}).items()}
        leaders = tuner_mod.leaders_for(self._host_groups,
                                        self._leader_overrides)
        gi = next(i for i, g in enumerate(self._host_groups)
                  if self._rank in g)
        self._leaders = leaders
        self._leader = leaders[gi]

    def _sync_identity(self) -> None:
        """Mirror the current (rank, slave_num) into the attached
        observability/recovery planes — the ONE place those mirrors
        are written, so a shrink renumbering cannot strand one of
        them on the old id (mp4j-lint R15 baseline)."""
        with self._tel_lock:
            self._comm_stats.rank = self._rank  # tags spans + heartbeats
        if self._audit is not None:
            self._audit.rank = self._rank   # tags the audit bundle
            self._audit.slave_num = self._n  # replay's dead-rank guard
        rec = getattr(self, "_recovery", None)
        if rec is not None:
            rec.rank = self._rank           # names this rank in aborts
        folder = getattr(self, "_health_folder", None)
        if folder is not None:
            # the span folder filters the process-global ring by this
            # rank's id — a shrink renumbering must retarget it or it
            # ships the OLD occupant's cells (ISSUE 12)
            folder._rank = self._rank

    def _accept_loop(self):
        while True:
            try:
                sock, _ = self._server.accept()
            except OSError:
                return  # server closed
            ch = None
            try:
                # sanctioned channel-construction site: the inbound
                # peer handshake must be read over SOME transport
                # before the pair's negotiated transport exists (R12
                # baseline, like the rendezvous sites)
                ch = tcp_mod.TcpChannel(sock)
                # bound the rank exchange: a stray connection that never
                # sends must not wedge the accept loop every healthy
                # peer depends on. The handshake carries (rank, epoch)
                # — the dialer pins the channel's job-wide epoch here,
                # the frame-level half of the epoch fence — plus, for a
                # same-host pair, the shm segment name + ring size the
                # dialer created (ISSUE 7 transport negotiation).
                ch.set_timeout(self._handshake_timeout)
                # sanctioned pre-fence receive: the handshake decides
                # which epoch the channel BELONGS to, so the fence
                # cannot apply yet (mp4j-lint R10 baseline)
                hs = ch.recv()
                if len(hs) == 2:
                    peer_rank, peer_epoch = hs
                    seg_token, ring_bytes = None, 0
                else:
                    peer_rank, peer_epoch, seg_token, ring_bytes = hs
                    tok_ok = (isinstance(seg_token, tuple)
                              and len(seg_token) >= 2
                              and seg_token[0] in ("memfd", "shm"))
                    # floor mirrors the MP4J_SHM_RING_BYTES validator
                    # (ONE constant — mp4j-lint R22's knob-drift class)
                    if not (tok_ok and isinstance(ring_bytes, int)
                            and not isinstance(ring_bytes, bool)
                            and ring_bytes >= tuning.SHM_RING_FLOOR):
                        raise TypeError(
                            f"malformed shm handshake {hs!r}")
                # strict integer types, no coercion: int('2')/int(2.7)
                # would let a stray dial-in claim a healthy rank's
                # peer slot (bool is an int subclass — reject it too)
                if (isinstance(peer_rank, bool)
                        or not isinstance(peer_rank, int)
                        or isinstance(peer_epoch, bool)
                        or not isinstance(peer_epoch, int)):
                    raise TypeError(f"malformed peer handshake {hs!r}")
                if peer_rank >= self._n:
                    # a freshly grown joiner dials the moment its
                    # constructor returns, which can beat the master's
                    # resize_go to this rank by one control push
                    # (ISSUE 13): wait briefly for the roster to grow
                    # instead of rejecting a healthy peer
                    with self._peer_cv:
                        self._peer_cv.wait_for(
                            lambda: peer_rank < self._n
                            or self._recovery.fatal is not None,
                            timeout=self._handshake_timeout)
                if seg_token is not None:
                    # only a fingerprint-matched peer may offer a shm
                    # segment (a stray dial-in must not make us mmap
                    # arbitrary names/fds); attach and upgrade the
                    # channel — the TCP socket stays as the carrier
                    entry = (self._roster[peer_rank]
                             if 0 <= peer_rank < self._n else ())
                    # gate on the REGISTERED fingerprint, not the live
                    # _shm flag: a rank that fell back to TCP after a
                    # local segment-creation failure must still honor
                    # inbound offers (attaching costs no creation
                    # resources), or the offering dialer would loop
                    # against its rejections forever
                    if not (self._fp and len(entry) > 2
                            and entry[2] == self._fp):
                        raise TypeError(
                            f"unsolicited shm offer from {peer_rank}")
                    seg = shm_mod.attach_segment(seg_token)
                    ch = shm_mod.ShmChannel(sock, seg, ring_bytes,
                                            owner=False)
            except Exception:
                # a peer (or stray connection) died mid-handshake; the
                # accept loop must survive to serve the healthy peers.
                # Close the CHANNEL when one got as far as wrapping the
                # socket (an shm upgrade owns a segment the raw socket
                # close would strand), else the socket itself.
                if ch is not None:
                    ch.close()
                else:
                    sock.close()
                continue
            try:
                with self._peer_cv:
                    # a dialer can be ahead of us by one abort round
                    # (its go arrived first): wait for our own go
                    # instead of rejecting a healthy reconnect
                    if peer_epoch > self._recovery.epoch:
                        self._peer_cv.wait_for(
                            lambda: self._recovery.epoch >= peer_epoch
                            or self._recovery.fatal is not None,
                            timeout=self._handshake_timeout)
                    # only a well-formed, novel rank dialing at the
                    # CURRENT epoch may claim a peer slot: a stray
                    # dial-in — or a stale one from a torn-down epoch —
                    # must not hijack (or orphan) a healthy peer's
                    # channel. abort_pending closes the announce->go
                    # window, where the epoch number still matches but
                    # the teardown may already have drained _peers (a
                    # registration after it would never be invalidated)
                    if (not 0 <= peer_rank < self._n
                            or peer_rank == self._rank
                            or peer_rank in self._peers
                            or peer_epoch != self._recovery.epoch
                            or self._recovery.abort_pending()):
                        ch.close()
                        continue
                    ch.set_timeout(self._peer_timeout)
                    ch.stats = self._comm_stats  # books wire time
                    ch.peer_rank = peer_rank     # tags wire spans
                    ch.faults = self._faults     # fault-injection hook
                    ch.epoch = peer_epoch        # pinned for the fence
                    # per-link socket buffers (ISSUE 15 satellite): the
                    # accept side learns the peer only now, so the map
                    # applies post-handshake (no window-scale effect —
                    # documented; the dial side applies before connect)
                    if peer_rank in self._so_buf_map \
                            and ch.transport == "tcp":
                        try:
                            tcp_mod.set_so_bufs(
                                ch.sock, *self._so_buf_map[peer_rank])
                        except OSError:
                            pass
                    self._peers[peer_rank] = ch
                    self._peer_cv.notify_all()
            except Exception:
                # the epoch gate raising (fatal mid-wait, interpreter
                # teardown) must not strand the accepted channel's fd
                ch.close()
                raise
            self._tuner_register_channel(peer_rank, ch)
            if peer_epoch > 0:
                self._comm_stats.add("reconnects", 1)

    def _fenced(self, peer: int) -> Channel:
        """THE epoch-fence wrapper: every peer data-plane operation
        must acquire its channel here (mp4j-lint R10). One flag check
        on the hot path — when an abort round is in flight this raises
        immediately instead of dialing into (or writing to) a torn
        epoch, so every rank converges on the retry barrier instead of
        manufacturing fresh wire errors."""
        self._recovery.poll()
        ch = self._channel(peer)
        # the channel's pinned epoch must also match the attempt's: a
        # full abort round can complete while _channel blocks waiting
        # for a peer dial-in, handing a fresh-epoch channel to a stale
        # attempt that already passed poll()
        self._recovery.check_channel(ch.epoch)
        return ch

    def _fenced_try(self, peer: int) -> "Channel | None":
        """Non-blocking :meth:`_fenced` for the async engine's
        incremental arming. When this rank is the ACCEPT side (peer >
        rank) and the higher rank has not dialed in yet, returns None
        instead of parking in the peer cv: a blocked progression
        thread stops pumping every OTHER leg it owns, and the dial it
        waits for may itself be cursor-gated behind bytes those legs
        owe — a mixed establishment/byte-dependency deadlock (seen on
        the n=5 shm engine grid). Dial-side establishment stays
        synchronous: the peer's accept loop is always responsive, so
        the connect is bounded and cannot join a cycle."""
        self._recovery.poll()
        if peer > self._rank:
            with self._peer_cv:
                ch = self._peers.get(peer)
            if ch is None:
                return None
        else:
            ch = self._channel(peer)
        self._recovery.check_channel(ch.epoch)
        return ch

    def _channel(self, peer: int) -> Channel:
        if peer == self._rank or not (0 <= peer < self._n):
            raise Mp4jError(f"bad peer {peer}")
        with self._peer_cv:
            ch = self._peers.get(peer)
            if ch is not None:
                return ch
        if peer < self._rank:
            # Dial OUTSIDE the cv: only the collective thread ever
            # dials (helper-thread sends bind their channel at submit
            # time), so no serialization is needed — and a connect()
            # blocked on an unreachable host must not hold the lock
            # the control thread's abort teardown and the accept loop
            # both depend on (a held cv would stall this rank's
            # ABORT_ACK for the whole connect timeout and escalate a
            # recoverable fault to a terminal abort).
            ch = self._dial(peer)
            with self._peer_cv:
                if (ch.epoch != self._recovery.epoch
                        or self._recovery.abort_pending()):
                    # an abort round completed — or was announced and
                    # its teardown already ran (epoch unchanged until
                    # the go, so equality alone misses it) — while we
                    # were dialing: registering this channel would park
                    # it past the drain and wedge every retry behind
                    # it — discard and re-route through the recovery
                    # engine instead
                    ch.close()
                    self._recovery.poll()
                    raise Mp4jTransportError(
                        f"dial to peer {peer} landed in a torn-down "
                        f"epoch {ch.epoch}")
                ch.set_timeout(self._peer_timeout)
                ch.stats = self._comm_stats  # channels book wire time
                ch.peer_rank = peer          # tags wire spans
                ch.faults = self._faults     # fault-injection hook
                self._peers[peer] = ch
                self._peer_cv.notify_all()
            self._tuner_register_channel(peer, ch)
            if ch.epoch > 0:
                self._comm_stats.add("reconnects", 1)
            return ch
        with self._peer_cv:
            # lower rank waits for the higher rank to dial in; an abort
            # or terminal fan-out breaks the wait (the dial will never
            # come for a torn-down epoch)
            ok = self._peer_cv.wait_for(
                lambda: peer in self._peers
                or self._recovery.abort_pending(),
                timeout=self._timeout)
            if peer in self._peers:
                return self._peers[peer]
        self._recovery.poll()   # raises if that is why we woke
        if not ok:
            raise Mp4jTransportError(
                f"timeout waiting for peer {peer} to connect")
        raise Mp4jTransportError(
            f"peer {peer} never re-dialed after recovery")

    def _shm_peer(self, peer: int) -> bool:
        """Whether the (self, peer) pair negotiates shm: equal,
        non-empty host fingerprints in the shared roster — a pure
        function of job-wide state, so both ends agree before any
        byte moves."""
        entry = self._roster[peer]
        return bool(self._shm and self._fp and len(entry) > 2
                    and entry[2] == self._fp)

    def _dial(self, peer: int) -> Channel:
        """Dial a lower rank's listen socket with capped exponential
        backoff (``MP4J_RECONNECT_BACKOFF``): after an abort round the
        remote may still be tearing down, so the first attempt can see
        a refused/reset connect. Runs WITHOUT the peer cv (see
        _channel); the fence poll each iteration keeps the loop
        abort-aware. The channel's epoch is pinned HERE and rides the
        handshake — and for a same-host pair the dialer CREATES the
        shm segment and names it in the same handshake (ISSUE 7), so
        transport negotiation adds zero round trips."""
        host, port = self._roster[peer][0], self._roster[peer][1]
        use_shm = self._shm_peer(peer)
        deadline = (None if self._timeout is None
                    else time.monotonic() + self._timeout)
        backoff = max(self._reconnect_backoff, 0.001)
        while True:
            self._recovery.poll()
            epoch = self._recovery.epoch
            ch = None
            seg = None
            try:
                # per-link socket buffers (ISSUE 15 satellite): the
                # dialer knows the peer, so the override applies
                # BEFORE connect() — the TCP window scale is fixed at
                # the handshake
                ch = connect(host, port, timeout=self._timeout,
                             so_bufs=self._so_buf_map.get(peer))
                # sanctioned pre-fence send: the handshake pins the
                # epoch the fence will enforce (mp4j-lint R10 baseline)
                if use_shm:
                    lo, hi = min(self._rank, peer), max(self._rank, peer)
                    name = shm_mod.segment_name(self._job_id, lo, hi,
                                                epoch)
                    try:
                        seg = shm_mod.create_segment(
                            name, self._shm_ring_bytes)
                    except OSError as e:
                        # a LOCAL resource failure (fd limit, /dev/shm
                        # full on the fallback backing) would otherwise
                        # ride the backoff loop forever against a
                        # healthy peer — the accepter still takes the
                        # plain 2-tuple handshake, so stop offering shm
                        # and keep the job alive on TCP
                        self._shm = False
                        use_shm = False
                        try:
                            self.error(
                                f"shm segment creation failed ({e}); "
                                "this rank falls back to TCP for all "
                                "pairs")
                        except (Mp4jError, OSError):
                            pass   # pre-rendezvous error() cannot send
                if use_shm:
                    ch.send_obj((self._rank, epoch, seg.token,
                                 self._shm_ring_bytes))
                    ch = shm_mod.ShmChannel(ch.sock, seg,
                                            self._shm_ring_bytes,
                                            owner=True)
                else:
                    ch.send_obj((self._rank, epoch))
                ch.epoch = epoch
                return ch
            except (Mp4jTransportError, OSError):
                if seg is not None and not isinstance(ch,
                                                      shm_mod.ShmChannel):
                    # created but never wrapped: free the segment here
                    # (once wrapped, ch.close() below owns it)
                    seg.close()
                # OSError included: the remote can accept the TCP
                # connection and tear it down before our handshake
                # send lands (exactly the post-abort window this
                # backoff exists for) — a raw ECONNRESET/EPIPE must
                # back off locally, not burn a job-wide retry round
                if ch is not None:
                    ch.close()
                if (deadline is not None
                        and time.monotonic() + backoff > deadline):
                    raise
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)

    @staticmethod
    def _send_on(ch: Channel, data, compress: bool = False) -> None:
        if isinstance(data, np.ndarray):
            ch.send_array(data, compress=compress)
        else:
            ch.send_obj(data, compress=compress)

    # -- tuner decision consumption (ISSUE 15) -------------------------
    # Per-link decisions are SENDER-LOCAL by construction: the framed
    # wire format is receiver-auto-detected (frame tags), and chunk
    # granularity is local on a byte-stream transport — see the safety
    # argument in utils/tuner.py. Both helpers are one dict.get on the
    # hot path and collapse to the static default with the tuner off,
    # observing, or tripped.
    def _compress_for(self, peer: int, requested: bool) -> bool:
        tun = self._tuner
        if tun is None or tun.mode != "act":
            return requested
        return tun.effective_compress(peer, requested)

    def _chunk_for(self, peer: int) -> int:
        tun = self._tuner
        if tun is None or tun.mode != "act" or self._shm_peer(peer):
            # shm pairs keep the job-wide schedule: the raw plane's
            # per-exchange ring/carrier routing makes it wire contract
            return self._chunk_bytes
        return tun.effective_chunk(peer, self._chunk_bytes)

    def _tuner_register_channel(self, peer: int, ch: Channel) -> None:
        """Channel-setup half of the tuner wiring: record the link's
        transport + applied socket buffer sizes in the per-link stats
        (the ISSUE 15 satellite), and re-apply any live chunk decision
        to the fresh channel (a recovery re-dial must not silently
        reset an adapted link)."""
        if ch.transport == "tcp":
            try:
                snd, rcv = tcp_mod.applied_buf_sizes(ch.sock)
                self._comm_stats.note_link(peer, transport="tcp",
                                           so_sndbuf=snd, so_rcvbuf=rcv)
            except OSError:
                self._comm_stats.note_link(peer, transport="tcp")
            tun = self._tuner
            if tun is not None and tun.mode == "act":
                ch.set_chunk_bytes(
                    tun.effective_chunk(peer, self._chunk_bytes))
        else:
            self._comm_stats.note_link(peer, transport=ch.transport)

    def _tuner_apply(self, tun) -> None:
        """Drain the tuner's pending decisions at an OUTERMOST
        collective boundary (the recovery wrapper calls this before
        any wire byte of the collective moves — decisions never change
        mid-collective). Also executes the audit-trip revert: every
        adapted link snaps back to the static defaults."""
        pending, revert = tun.take_pending()
        with self._peer_cv:
            chans = dict(self._peers)
        if revert:
            for peer, ch in chans.items():
                if ch.transport == "tcp":
                    ch.set_chunk_bytes(self._chunk_bytes)
            self._recovery.note(
                "tuner", "reverted all links to static defaults")
        for peer, d in pending.items():
            ch = chans.get(peer)
            cb = d.get("chunk_bytes")
            if cb and ch is not None and ch.transport == "tcp":
                ch.set_chunk_bytes(cb)
            if ch is not None and ch.transport == "tcp" and (
                    d.get("so_sndbuf") or d.get("so_rcvbuf")):
                try:
                    tcp_mod.set_so_bufs(ch.sock, d.get("so_sndbuf"),
                                        d.get("so_rcvbuf"))
                    snd, rcv = tcp_mod.applied_buf_sizes(ch.sock)
                    self._comm_stats.note_link(
                        peer, so_sndbuf=snd, so_rcvbuf=rcv)
                except OSError:
                    pass   # a refused resize keeps the old buffers
            self._comm_stats.metrics.inc("tuner/decisions")
            self._recovery.note(
                "tuner",
                f"link->{peer} applied chunk={d.get('chunk_bytes')} "
                f"compress={d.get('compress')}")

    def _send(self, peer: int, data, compress: bool = False) -> None:
        if isinstance(data, np.ndarray):
            self._comm_stats.add_transfer(peer, data.nbytes)
        self._send_on(self._fenced(peer), data,
                      self._compress_for(peer, compress))

    def _submit_send(self, peer: int, data, compress: bool = False):
        """Helper-thread send with the channel resolved NOW, under the
        epoch fence — a queued send job from an attempt the recovery
        engine has since aborted must error on its own (closed) channel,
        never late-resolve a fresh one and write stale-epoch bytes into
        the retry's stream."""
        if isinstance(data, np.ndarray):
            self._comm_stats.add_transfer(peer, data.nbytes)
        fut = self._pool.submit(self._send_on, self._fenced(peer),
                                 data, self._compress_for(peer, compress))
        # tracked so _drain_dead_channels can wait for abandoned
        # futures (a recv that raises first orphans its paired send)
        # before it frees fds; pruned opportunistically so a healthy
        # run never grows the list
        self._send_futs.append(fut)
        if len(self._send_futs) > 32:
            self._send_futs = [f for f in self._send_futs
                               if not f.done()]
        return fut

    def _recv(self, peer: int):
        # peer channels carry ``peer_timeout`` from creation (_channel /
        # _accept_loop); None is the reference's fail-stop default
        # mp4j-lint: disable=R2 (peer_timeout is set at channel creation)
        return self._fenced(peer).recv()

    def _sendrecv(self, send_peer: int, recv_peer: int, data,
                  compress: bool = False):
        """Send and receive concurrently (paired exchange, ring step)."""
        fut = self._submit_send(send_peer, data, compress)
        out = self._recv(recv_peer)
        fut.result()
        return out

    # ------------------------------------------------------------------
    # raw (unframed) data plane
    #
    # The numeric fast path: segment sizes are derived from collective
    # metadata on both ends, so no framing travels on the wire (the
    # reference's primitive DataOutputStream path, SURVEY.md section 2).
    # Whether an exchange is raw must be a pure function of job-wide
    # call parameters — operand properties and the job's
    # native_transport flag — NEVER of local library availability, or
    # ranks would disagree about the wire format. The C++ poll loop
    # (csrc/mp4j_transport.cpp) moves the bytes when available; the
    # Python fallback produces identical wire bytes.
    # ------------------------------------------------------------------
    def _raw_ok(self, operand: Operand) -> bool:
        return (self._native_transport and operand.is_numeric
                and not operand.compress)

    def _exchange_raw(self, send_peer: int, recv_peer: int,
                      sarr: np.ndarray | None, rarr: np.ndarray | None):
        """Full-duplex raw exchange; either side may be absent (None)."""
        send_ch = self._fenced(send_peer) if sarr is not None else None
        recv_ch = self._fenced(recv_peer) if rarr is not None else None
        if self._faults is not None:
            # injector hook at exchange granularity: the native C++
            # poll loop moves the bytes without touching the Python
            # channel primitives, so the channel-level hooks alone
            # would silently skip the raw plane
            if send_ch is not None:
                self._faults.on_io(send_ch, "send")
            if recv_ch is not None and recv_ch is not send_ch:
                self._faults.on_io(recv_ch, "recv")
        if sarr is not None:
            sarr = np.ascontiguousarray(sarr)
        # audit wire folds at EXCHANGE granularity (ISSUE 8): the
        # native poll loop and the shm rings move raw bytes below the
        # Python channel primitives, so the raw plane digests whole
        # segments here — crc composability makes these folds
        # comparable with the peer's, whatever its chunking
        wire_audit = (self._audit if self._audit is not None
                      and self._audit.wire_on else None)
        if wire_audit is not None and sarr is not None:
            # fold BEFORE any injected corruption: the sender's record
            # describes what it meant to send (see resilience.faults)
            wire_audit.on_wire(send_peer, "send", (_raw_view(sarr),),
                               send_ch.transport)
        if self._faults is not None and sarr is not None:
            f = self._faults.take_corrupt(send_ch, sarr.nbytes)
            if f is not None:
                sarr = faults_mod.corrupt_copy(sarr)
        sides = " ".join(
            ([f"send->{send_peer}"] if sarr is not None else [])
            + ([f"recv<-{recv_peer}"] if rarr is not None else []))
        t0 = time.perf_counter()
        # the native C++ poll loop needs real socket fds on BOTH legs;
        # a shm leg (native_fd() is None) routes the whole exchange
        # through the Python raw primitives — the ring copy IS the
        # fast path there, and the wire bytes are identical either way
        # (the raw/framed decision stays the job-wide _raw_ok rule;
        # native-vs-python within raw is per-exchange local, exactly
        # like the pre-SPI fallback on hosts without the C++ build)
        fd_s = (send_ch or recv_ch).native_fd()
        fd_r = (recv_ch or send_ch).native_fd()
        both_shm = (isinstance(send_ch or recv_ch, shm_mod.ShmChannel)
                    and isinstance(recv_ch or send_ch,
                                   shm_mod.ShmChannel))
        if both_shm:
            # hybrid routing (transport/shm.py): per DIRECTION, bytes
            # ride the ring iff the transfer clears _RING_MIN — a pure
            # function of the segment size both ends share. When BOTH
            # directions are carrier-bound, the exchange is exactly a
            # socket exchange, so hand the carrier fds to the same
            # native poll loop TCP uses (kernel wakeups; wire bytes
            # identical to the shm carrier path)
            s_small = sarr is None or sarr.nbytes < shm_mod._RING_MIN
            r_small = rarr is None or rarr.nbytes < shm_mod._RING_MIN
            if s_small and r_small:
                both_shm = False
                fd_s = (send_ch or recv_ch).sock.fileno()
                fd_r = (recv_ch or send_ch).sock.fileno()
        try:
            if both_shm:
                # single-threaded cooperative duplex — the ring
                # analogue of the native poll loop (a helper-thread
                # send would ping-pong the GIL around user-space
                # memcpys and pay a pool-future handoff per chunk)
                shm_mod.duplex_exchange(send_ch, sarr, recv_ch, rarr)
            else:
                done = False
                if fd_s is not None and fd_r is not None:
                    done = native.sendrecv_raw(fd_s, fd_r, sarr, rarr,
                                               self._peer_timeout)
                if not done:
                    # pure-Python fallback (no native build, or a
                    # MIXED shm+tcp step): helper thread sends while
                    # we receive — sockets park in the kernel, so a
                    # second thread is what keeps both directions
                    # moving
                    fut = (self._pool.submit(send_ch.send_raw, sarr)
                           if sarr is not None else None)
                    if rarr is not None:
                        recv_ch.recv_raw_into(rarr)
                    if fut is not None:
                        fut.result()
        except Exception as e:
            # also catches the fallback's raw socket errors (BrokenPipe,
            # socket.timeout from the helper-thread send) so the "dead
            # peer becomes Mp4jError" contract holds on every path —
            # typed TRANSPORT so the recovery engine may retry it
            raise Mp4jTransportError(
                f"raw exchange ({sides}) failed: {e}") from None
        dt = time.perf_counter() - t0
        if wire_audit is not None and rarr is not None:
            wire_audit.on_wire(recv_peer, "recv", (_raw_view(rarr),),
                               recv_ch.transport)
        sbytes = 0 if sarr is None else sarr.nbytes
        rbytes = 0 if rarr is None else rarr.nbytes
        if (send_ch is not None and recv_ch is not None
                and send_ch.transport != recv_ch.transport):
            # a mixed-transport full-duplex step (e.g. a ring rank
            # with one shm and one TCP neighbor): book each direction
            # on the plane it actually rode
            self._comm_stats.add_wire(sbytes, 0, dt, chunks=1,
                                      peer=send_peer,
                                      transport=send_ch.transport)
            self._comm_stats.add_wire(0, rbytes, 0.0, chunks=0,
                                      peer=recv_peer,
                                      transport=recv_ch.transport)
        else:
            self._comm_stats.add_wire(
                sbytes, rbytes, dt, chunks=1,
                peer=recv_peer if rarr is not None else send_peer,
                transport=(recv_ch or send_ch).transport)

    def _recv_buf(self, operand: Operand, n: int) -> np.ndarray:
        """A pooled scratch buffer (give back via ``_give_buf`` after
        the last read — see :class:`_ScratchPool`)."""
        return self._scratch.take(operand.dtype, n)

    def _give_buf(self, buf: np.ndarray) -> None:
        self._scratch.give(buf)

    # ------------------------------------------------------------------
    # pipelined chunked engine
    #
    # Each per-step segment splits into MP4J_CHUNK_BYTES chunks:
    # full-duplex exchange of chunk k, then merge of chunk k, repeated.
    # The double buffer is the KERNEL socket buffer: while we merge
    # chunk k, the peer's chunk k+1 is already streaming into our
    # receive buffer (and our own chunk k+1 drains from the send
    # buffer), so the wire transfer of k+1 overlaps the reduce of k
    # without any thread handoff — and the merge runs on cache-hot
    # bytes instead of re-reading the whole segment cold. Measured on
    # the bench host at MB-scale segments: ~1.6x over the monolithic
    # exchange; an explicit worker-thread double buffer was measured
    # SLOWER there (per-chunk future/GIL handoff beats the overlap on
    # a single core), hence the sequential loop.
    #
    # The chunk schedule is a pure function of the job-wide call
    # parameters (segment size, dtype, MP4J_CHUNK_BYTES) — never of
    # rank-local state (mp4j-lint R8) — so ranks always agree on it;
    # chunks merge in ascending offset order, which preserves the
    # unchunked per-element merge order bit-for-bit.
    # ------------------------------------------------------------------
    def _chunked_exchange(self, send_peer: int, recv_peer: int,
                          sarr: np.ndarray | None,
                          rarr: np.ndarray | None, on_chunk=None) -> None:
        """Raw full-duplex exchange in pipeline chunks; ``on_chunk(lo,
        hi)`` runs after ``rarr[lo:hi]`` has arrived, while the next
        chunk is in flight in the kernel buffers."""
        itemsize = (rarr if rarr is not None else sarr).dtype.itemsize
        n_send = 0 if sarr is None else sarr.size
        n_recv = 0 if rarr is None else rarr.size
        # bulk-transfer granularity evidence for the tuner's chunk
        # policy (ISSUE 15): the original segment sizes, which the
        # per-chunk wire bookings below cannot recover
        if sarr is not None:
            self._comm_stats.add_transfer(send_peer, sarr.nbytes)
        if rarr is not None and recv_peer != send_peer:
            self._comm_stats.add_transfer(recv_peer, rarr.nbytes)
        # per-link chunk size (ISSUE 15): each direction uses ITS
        # link's decided granularity — chunk boundaries are local on a
        # byte-stream transport, so asymmetric schedules cannot desync
        # (shm links always resolve to the job default, see _chunk_for)
        sch = tuning.chunk_ranges(n_send, itemsize,
                                  self._chunk_for(send_peer))
        rch = tuning.chunk_ranges(n_recv, itemsize,
                                  self._chunk_for(recv_peer))
        steps = max(len(sch), len(rch))
        if steps <= 1:
            self._exchange_raw(send_peer, recv_peer, sarr, rarr)
            if on_chunk is not None and n_recv:
                on_chunk(0, n_recv)
            return
        if sarr is not None:
            sarr = np.ascontiguousarray(sarr)
        for k in range(steps):
            sc = sarr[sch[k][0]:sch[k][1]] if k < len(sch) else None
            rc = rarr[rch[k][0]:rch[k][1]] if k < len(rch) else None
            self._exchange_raw(send_peer, recv_peer, sc, rc)
            if rc is not None and on_chunk is not None:
                on_chunk(*rch[k])

    def _reduce_into(self, operator: Operator, acc: np.ndarray,
                     src: np.ndarray) -> None:
        """``acc = op(acc, src)`` via the native kernel, booking
        reduce-phase time."""
        t0 = time.perf_counter()
        native.reduce_into(operator, acc, src)
        self._comm_stats.add("reduce_seconds", time.perf_counter() - t0)

    def _send_reduce_contrib(self, peer: int, chunk,
                             operand: Operand) -> None:
        """The send half that PAIRS with :meth:`_recv_reduce`: the
        receiver drains in ``MP4J_CHUNK_BYTES`` exchanges, so the raw
        sender must ship the same exchange schedule — on the shm plane
        the ring/carrier routing is a per-EXCHANGE size rule, and a
        monolithic send against a chunked receive deadlocks the moment
        a segment exceeds one chunk with a sub-``_RING_MIN`` tail (the
        tail rides the ring on one side and the carrier on the other).
        A pure function of the same job-wide sizes as the receiver's
        schedule, so both ends always agree (mp4j-lint R8
        discipline)."""
        if self._raw_ok(operand) and isinstance(chunk, np.ndarray):
            self._chunked_exchange(peer, peer, chunk, None)
        else:
            self._send_segment(peer, chunk, operand)

    def _recv_reduce(self, peer: int, acc: np.ndarray, operator: Operator,
                     operand: Operand) -> None:
        """Receive a segment the size of ``acc`` and merge it in,
        chunk-by-chunk (merge of chunk k overlaps the wire transfer of
        chunk k+1); raw or framed per the job-wide wire decision.
        Paired senders must use :meth:`_send_reduce_contrib` — the
        chunked exchange schedule is part of the wire contract on the
        shm plane (see there)."""
        rbuf = self._recv_buf(operand, acc.size)
        try:
            def merge(lo, hi):
                self._reduce_into(operator, acc[lo:hi], rbuf[lo:hi])

            if self._raw_ok(operand):
                self._chunked_exchange(peer, peer, None, rbuf,
                                       on_chunk=merge)
            else:
                self._fenced(peer).recv_array_into(rbuf, on_chunk=merge)
        finally:
            self._give_buf(rbuf)

    def _exchange_reduce(self, peer: int, send_view: np.ndarray,
                         acc: np.ndarray, operator: Operator,
                         operand: Operand) -> None:
        """Full-duplex partner exchange: ship ``send_view`` while
        receiving ``acc.size`` elements, merging arrivals into ``acc``
        chunk-by-chunk (the halving-round hot path)."""
        rbuf = self._recv_buf(operand, acc.size)
        try:
            def merge(lo, hi):
                self._reduce_into(operator, acc[lo:hi], rbuf[lo:hi])

            if self._raw_ok(operand):
                self._chunked_exchange(peer, peer, send_view, rbuf,
                                       on_chunk=merge)
            else:
                fut = self._submit_send(
                    peer, np.ascontiguousarray(send_view),
                    operand.compress)
                self._fenced(peer).recv_array_into(rbuf, on_chunk=merge)
                fut.result()
        finally:
            self._give_buf(rbuf)

    def _send_segment(self, peer: int, chunk, operand: Operand) -> None:
        """One-directional segment send for the tree/rooted collectives:
        raw when the job+operand allow, framed otherwise."""
        if self._raw_ok(operand):
            self._exchange_raw(peer, peer, chunk, None)
        else:
            self._send(peer, np.ascontiguousarray(chunk)
                       if isinstance(chunk, np.ndarray) else chunk,
                       compress=operand.compress)

    def _recv_segment_into(self, peer: int, arr, s: int, e: int,
                           operand: Operand) -> None:
        """Receive a segment directly into ``arr[s:e]`` — in place on
        the raw path AND the framed ndarray path (no temp buffer or
        copy); list containers assign through the container.

        The raw/framed decision must mirror :meth:`_send_segment`
        exactly — both are pure functions of ``_raw_ok(operand)`` — or
        sender and receiver would disagree on the wire format.
        """
        if self._raw_ok(operand):
            # check_array coerces numeric operands to ndarray; the raw
            # path is therefore always receivable in place.
            assert isinstance(arr, np.ndarray), \
                "numeric operand implies ndarray container (check_array)"
            self._exchange_raw_into(peer, peer, None, arr[s:e], operand)
        elif operand.is_numeric and isinstance(arr, np.ndarray):
            # framed numeric: stream the array frame straight into the
            # destination view (decompressing chunk-wise if compressed)
            view = arr[s:e]
            if view.flags.c_contiguous and view.flags.writeable:
                self._fenced(peer).recv_array_into(view)
            else:
                arr[s:e] = self._recv(peer)
        else:
            arr[s:e] = self._recv(peer)

    def _exchange_raw_into(self, send_peer: int, recv_peer: int,
                           sarr: np.ndarray | None, rview: np.ndarray,
                           operand: Operand) -> np.ndarray:
        """Raw exchange receiving into ``rview`` (via a pooled temp when
        the view is not directly receivable — contiguity is a LOCAL
        detail and must not influence the shared raw/framed decision)."""
        direct = rview.flags.c_contiguous and rview.flags.writeable
        if direct:
            self._exchange_raw(send_peer, recv_peer, sarr, rview)
            return rview
        rbuf = self._recv_buf(operand, rview.size)
        try:
            self._exchange_raw(send_peer, recv_peer, sarr, rbuf)
            rview[:] = rbuf
        finally:
            self._give_buf(rbuf)
        return rview

    # ------------------------------------------------------------------
    # dense-array helpers
    # ------------------------------------------------------------------
    @staticmethod
    def _merge(operator: Operator, operand: Operand, acc, src):
        """acc = op(acc, src), element-wise; native fast path for numeric."""
        if isinstance(acc, np.ndarray) and isinstance(src, np.ndarray):
            native.reduce_into(operator, acc, src)
            return acc
        return [operator.np_fn(a, b) for a, b in zip(acc, src)]

    def _norm_range(self, arr, operand: Operand, lo: int, hi: int | None):
        if operand.is_numeric:
            arr = operand.check_array(arr)
            if arr.ndim != 1:
                raise Mp4jError("socket path supports 1-D arrays")
        length = len(arr)
        if hi is None:
            hi = length
        if not (0 <= lo <= hi <= length):
            raise Mp4jError(f"range [{lo}, {hi}) out of bounds for {length}")
        return arr, lo, hi

    # ------------------------------------------------------------------
    # collectives: dense arrays
    # ------------------------------------------------------------------
    def allreduce_array(self, arr, operand: Operand = Operands.FLOAT,
                        operator: Operator = Operators.SUM,
                        from_: int = 0, to: int | None = None,
                        algo: str = "auto"):
        """Allreduce over ``arr[from_:to]``, in place on every rank.

        ``algo="auto"`` (default) picks by payload size — a pure
        function of the job-wide call parameters (bytes, rank count,
        ``MP4J_ALGO_*_BYTES`` thresholds), so every rank derives the
        same schedule: binomial ``"tree"`` (reduce+broadcast) for
        latency-bound small payloads, ``"rhd"`` for the middle,
        pipelined ``"ring"`` for bandwidth-bound large payloads.

        ``algo="rhd"`` (the reference's path): reduce-scatter by
        recursive halving + allgather by recursive doubling over the
        largest power-of-2 rank group, extra ranks folded in by a
        pre/post exchange. ``algo="ring"``: ring reduce-scatter + ring
        allgather. Both pipeline each step in ``MP4J_CHUNK_BYTES``
        chunks (merge of chunk k overlaps the wire transfer of k+1).

        Non-numeric (STRING/OBJECT list) operands take the rank-ordered
        binomial tree always: halving/ring merge order varies per
        segment, which is only equivalent for commutative operators;
        list reductions (e.g. concatenation) deserve deterministic rank
        order and are latency- not bandwidth-bound anyway.

        ``algo="twolevel"`` (ISSUE 7; what ``"auto"`` picks whenever
        the roster spans multiple hosts with co-located ranks): the
        classic topology-aware schedule — binomial reduce to each
        host's leader over the intra-host (shm) pairs, recursive
        halving/doubling among the leaders over TCP, binomial
        broadcast back out — so the inter-host wire carries each byte
        once per HOST instead of once per RANK.
        """
        if algo not in ("auto", "rhd", "ring", "tree", "twolevel"):
            raise Mp4jError(f"unknown allreduce algo {algo!r}")
        arr, lo, hi = self._norm_range(arr, operand, from_, to)
        if self._n == 1 or hi == lo:
            return arr
        if not operand.is_numeric:
            algo = "tree"
        elif algo == "auto":
            if self._use_twolevel():
                algo = "twolevel"
            else:
                algo = tuning.select_allreduce_algo(
                    (hi - lo) * operand.dtype.itemsize, self._n,
                    self._algo_small, self._algo_large)
        if algo == "twolevel":
            return self._twolevel_allreduce(arr, operand, operator,
                                            lo, hi)
        if algo == "tree":
            self.reduce_array(arr, operand, operator, root=0,
                              from_=from_, to=to)
            return self.broadcast_array(arr, operand, root=0,
                                        from_=from_, to=to)
        if algo == "rhd":
            return self._rhd_allreduce(arr, operand, operator, lo, hi)
        segs = meta.partition_range(lo, hi, self._n)
        self._ring_reduce_scatter(arr, segs, operand, operator)
        self._ring_allgather(arr, segs, operand)
        return arr

    # -- recursive halving/doubling (Rabenseifner), SURVEY.md 3b --------
    def _rhd_allreduce(self, arr, operand, operator, lo, hi,
                       group=None):
        """MPICH-style allreduce: fold extra ranks into the largest
        power-of-2 group, reduce-scatter by recursive halving, allgather
        by recursive doubling, unfold.

        Round structure (p = 2^floor(log2 n) participants):
        - fold: ranks >= p ship their whole range to ``rank - p``, which
          merges it; folded ranks then idle until unfold.
        - halving: log2(p) exchanges; each round partner = vr ^ dist with
          dist halving from p/2, exchanging half of the active segment
          window and merging the received half (native hot loop).
        - doubling: the mirror image; window doubles until every
          participant holds the full reduced range.
        - unfold: participants send the finished range back to their
          folded partner.

        ``group`` (a sorted rank subset containing this rank) runs the
        SAME schedule among just those ranks — the two-level engine's
        inter-host leg (ISSUE 7: one leader per host).
        """
        if group is None:
            n, r = self._n, self._rank
            gmap = range(n)
        else:
            n, r = len(group), group.index(self._rank)
            gmap = group
        raw = self._raw_ok(operand)
        p = 1
        while p * 2 <= n:
            p *= 2
        extra = n - p

        if r >= p:  # folded rank: contribute, then wait for the result
            fold = gmap[r - p]
            if raw:
                # chunked to mirror the fold partner's _recv_reduce
                # schedule (the shm routing contract — see
                # _send_reduce_contrib)
                self._chunked_exchange(fold, fold, arr[lo:hi], None)
                self._exchange_raw_into(fold, fold, None, arr[lo:hi],
                                        operand)
            else:
                self._send(fold, np.ascontiguousarray(arr[lo:hi]),
                           compress=operand.compress)
                self._recv_segment_into(fold, arr, lo, hi, operand)
            return arr
        if r < extra:  # fold partner: merge the extra rank's data
            self._recv_reduce(gmap[r + p], arr[lo:hi], operator, operand)

        vr = r
        segs = meta.partition_range(lo, hi, p)

        def span(a, b):  # byte range of segment window [a, b)
            return segs[a][0], segs[b - 1][1]

        # reduce-scatter: recursive halving (pipelined chunked merge)
        dist = p >> 1
        while dist >= 1:
            partner = gmap[vr ^ dist]
            block0 = (vr // (2 * dist)) * (2 * dist)
            if vr & dist:
                keep = (block0 + dist, block0 + 2 * dist)
                give = (block0, block0 + dist)
            else:
                keep = (block0, block0 + dist)
                give = (block0 + dist, block0 + 2 * dist)
            gs, ge = span(*give)
            ks, ke = span(*keep)
            self._exchange_reduce(partner, arr[gs:ge], arr[ks:ke],
                                  operator, operand)
            dist >>= 1

        # allgather: recursive doubling (no merge to overlap; the raw
        # exchange is already full-duplex and lands in place)
        dist = 1
        while dist < p:
            pv = vr ^ dist
            partner = gmap[pv]
            mb0 = (vr // dist) * dist
            tb0 = (pv // dist) * dist
            ms, me = span(mb0, mb0 + dist)
            ts, te = span(tb0, tb0 + dist)
            if raw:
                self._exchange_raw_into(partner, partner, arr[ms:me],
                                        arr[ts:te], operand)
            else:
                fut = self._submit_send(
                    partner, np.ascontiguousarray(arr[ms:me]),
                    operand.compress)
                self._recv_segment_into(partner, arr, ts, te, operand)
                fut.result()
            dist *= 2

        if r < extra:  # unfold: ship the finished range back
            if raw:
                self._exchange_raw(gmap[r + p], gmap[r + p], arr[lo:hi],
                                   None)
            else:
                self._send(gmap[r + p], np.ascontiguousarray(arr[lo:hi]),
                           compress=operand.compress)
        return arr

    # -- topology-aware two-level collectives (ISSUE 7) -----------------
    # Group ranks by roster host fingerprint; run the intra-host legs
    # over the (shm) member pairs and ONE inter-host leg per host
    # leader over TCP. Every schedule below is a pure function of the
    # shared roster + call parameters (R1/R8 discipline). Numeric
    # operands only — the callers route non-numeric operands to the
    # rank-ordered tree before ever selecting these.
    def _use_twolevel(self) -> bool:
        return tuning.select_twolevel(
            [len(g) for g in self._host_groups])

    def _group_tree_reduce(self, acc, group, operand, operator,
                           root: int | None = None) -> None:
        """Binomial reduce of ``acc`` toward ``root`` (default: the
        group's smallest rank), merging IN PLACE into ``acc`` —
        callers pass either a buffer that will be overwritten anyway
        (allreduce) or an explicit scratch copy (reduce_scatter). The
        two-level legs pass the EFFECTIVE leader (ISSUE 15: a tuner
        demotion may root the walk at another member). One more
        client of THE shared binomial walk (see the map-plane
        comment): the merge mutates ``acc``, so the threaded value is
        just ``acc`` itself."""
        self._tree_reduce_walk(
            acc, group[0] if root is None else root,
            lambda peer, a: self._send_reduce_contrib(peer, a,
                                                      operand),
            lambda peer, a: (self._recv_reduce(peer, a, operator,
                                               operand), a)[1],
            group=group)

    def _group_tree_bcast(self, arr, lo, hi, group, operand,
                          root: int | None = None) -> None:
        """Binomial broadcast of the root's ``arr[lo:hi]`` to the
        group, received in place (the walk's threaded value is unused
        — receives land directly in ``arr[lo:hi]``). Root defaults to
        the group's smallest rank; the two-level legs pass the
        effective leader (ISSUE 15)."""
        def recv(peer):
            self._recv_segment_into(peer, arr, lo, hi, operand)

        self._tree_bcast_walk(
            None, group[0] if root is None else root,
            lambda peer, _: self._send_segment(peer, arr[lo:hi],
                                               operand),
            recv, group=group)

    def _twolevel_allreduce(self, arr, operand, operator, lo, hi):
        """Intra-host reduce -> leaders' inter-host allreduce (RHD) ->
        intra-host broadcast. All three legs land in ``arr[lo:hi]``
        directly: allreduce overwrites the whole range on every rank,
        so no scratch copy is needed anywhere."""
        members, leaders = self._members, self._leaders
        if len(members) > 1:
            self._group_tree_reduce(arr[lo:hi], members, operand,
                                    operator, root=self._leader)
        if self._rank == self._leader and len(leaders) > 1:
            self._rhd_allreduce(arr, operand, operator, lo, hi,
                                group=leaders)
        if len(members) > 1:
            self._group_tree_bcast(arr, lo, hi, members, operand,
                                   root=self._leader)
        return arr

    def _twolevel_reduce_scatter(self, arr, ranges, operand, operator):
        """Intra-host reduce of the full span into a pooled scratch
        accumulator (the caller's positions outside each rank's owned
        range must stay untouched), leaders' inter-host allreduce,
        then the leader hands every member exactly its range. The
        scratch copy mirrors the tree path's reduce_array acc copy —
        same budget, but the heavy legs ride shm."""
        members, leaders = self._members, self._leaders
        acc = self._recv_buf(operand, len(arr))
        try:
            np.copyto(acc, arr)
            if len(members) > 1:
                self._group_tree_reduce(acc, members, operand, operator,
                                        root=self._leader)
            if self._rank == self._leader and len(leaders) > 1:
                self._rhd_allreduce(acc, operand, operator, 0, len(acc),
                                    group=leaders)
            if self._rank == self._leader:
                for m in members:
                    s, e = ranges[m]
                    if m == self._rank:
                        arr[s:e] = acc[s:e]
                    else:
                        self._send_segment(m, acc[s:e], operand)
            else:
                s, e = ranges[self._rank]
                self._recv_segment_into(self._leader, arr, s, e,
                                        operand)
        finally:
            self._give_buf(acc)
        return arr

    def _twolevel_allgather(self, arr, ranges, operand):
        """Intra-host gather to the leader, ring over HOST BLOCKS among
        the leaders (step s ships host block (h-s) right while host
        block (h-1-s) arrives from the left — each member range is one
        transfer, so the inter-host wire carries every byte exactly
        once per host), then intra-host broadcast of the tiled span.
        Caller guarantees the ranges tile contiguously (the same
        precondition the tree path enforces)."""
        members, leaders = self._members, self._leaders
        groups = self._host_groups
        if len(members) > 1:
            if self._rank == self._leader:
                for m in members:
                    if m != self._rank:
                        s, e = ranges[m]
                        self._recv_segment_into(m, arr, s, e, operand)
            else:
                s, e = ranges[self._rank]
                self._send_segment(self._leader, arr[s:e], operand)
        if self._rank == self._leader and len(leaders) > 1:
            raw = (self._raw_ok(operand)
                   and isinstance(arr, np.ndarray))
            H = len(leaders)
            h = leaders.index(self._rank)
            right, left = leaders[(h + 1) % H], leaders[(h - 1) % H]
            for step in range(H - 1):
                sblock = groups[(h - step) % H]
                rblock = groups[(h - 1 - step) % H]
                for i in range(max(len(sblock), len(rblock))):
                    sseg = ranges[sblock[i]] if i < len(sblock) else None
                    rseg = ranges[rblock[i]] if i < len(rblock) else None
                    sarr = (arr[sseg[0]:sseg[1]] if sseg is not None
                            else None)
                    if raw:
                        if rseg is not None:
                            self._exchange_raw_into(
                                right, left, sarr,
                                arr[rseg[0]:rseg[1]], operand)
                        elif sarr is not None:
                            self._exchange_raw(right, left, sarr, None)
                    else:
                        fut = (self._submit_send(
                            right, np.ascontiguousarray(sarr),
                            operand.compress)
                            if sarr is not None else None)
                        if rseg is not None:
                            self._recv_segment_into(left, arr, rseg[0],
                                                    rseg[1], operand)
                        if fut is not None:
                            fut.result()
        if len(members) > 1:
            lo, hi, _ = self._ranges_span(ranges)
            self._group_tree_bcast(arr, lo, hi, members, operand,
                                   root=self._leader)
        return arr

    @staticmethod
    def _ranges_span(ranges):
        """(lo, hi, contiguous): whether the per-rank ranges tile
        ``[lo, hi)`` without gaps — a pure function of the call's
        ``ranges`` argument, so every rank answers identically."""
        lo, hi = ranges[0][0], ranges[-1][1]
        contiguous = all(ranges[i][1] == ranges[i + 1][0]
                         for i in range(len(ranges) - 1))
        return lo, hi, contiguous

    def reduce_scatter_array(self, arr, operand: Operand = Operands.FLOAT,
                             operator: Operator = Operators.SUM,
                             ranges=None, algo: str = "auto"):
        """Rank r ends with segment ``ranges[r]`` of the reduction.

        ``algo="auto"`` (default): rank-ordered binomial tree
        (reduce + scatter) below the latency threshold, pipelined ring
        otherwise — the same job-wide size rule as allreduce; on a
        multi-host roster with co-located ranks it picks the two-level
        schedule instead (``"twolevel"``: intra-host reduce over shm,
        leaders' inter-host allreduce, leader scatters each member its
        range — ISSUE 7). ``"ring"`` / ``"tree"`` / ``"twolevel"``
        force a path; non-numeric operands always take the tree
        (deterministic rank order, see allreduce_array)."""
        if algo not in ("auto", "ring", "tree", "twolevel"):
            raise Mp4jError(f"unknown reduce_scatter algo {algo!r}")
        arr, lo, hi = self._norm_range(arr, operand, 0, None)
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), self._n)
        if self._n == 1:
            return arr
        if not operand.is_numeric:
            algo = "tree"
        elif algo == "auto":
            if self._use_twolevel():
                algo = "twolevel"
            else:
                algo = tuning.select_partitioned_algo(
                    len(arr) * operand.dtype.itemsize, self._n,
                    self._algo_small, self._algo_large)
        if algo == "twolevel":
            return self._twolevel_reduce_scatter(arr, ranges, operand,
                                                 operator)
        if algo == "tree":
            # rank-ordered tree + scatter (see allreduce_array). Rank
            # 0's buffer is the tree root, so its positions OUTSIDE its
            # owned range must be restored afterwards — every backend
            # promises "other positions unchanged".
            orig = None
            if self._rank == 0:
                orig = (arr.copy() if isinstance(arr, np.ndarray)
                        else list(arr))
            self.reduce_array(arr, operand, operator, root=0)
            self.scatter_array(arr, operand, root=0, ranges=ranges)
            if self._rank == 0:
                s, e = ranges[0]
                arr[:s] = orig[:s]
                arr[e:] = orig[e:]
            return arr
        self._ring_reduce_scatter(arr, ranges, operand, operator)
        return arr

    def allgather_array(self, arr, operand: Operand = Operands.FLOAT,
                        ranges=None, algo: str = "auto"):
        """Each rank owns ``arr[ranges[rank]]``; all segments everywhere.

        ``algo="auto"`` (default): rooted binomial tree
        (gather + broadcast) below the latency threshold when the
        ranges tile a contiguous span, pipelined ring otherwise; on a
        multi-host roster with co-located ranks (and contiguous
        ranges) it picks ``"twolevel"`` — intra-host gather over shm,
        a leaders' ring over whole HOST blocks, intra-host broadcast
        (ISSUE 7). ``"tree"``/``"twolevel"`` require contiguous ranges
        (their broadcast covers the tiled span exactly); ``"ring"``
        accepts any ranges."""
        if algo not in ("auto", "ring", "tree", "twolevel"):
            raise Mp4jError(f"unknown allgather algo {algo!r}")
        arr, _, _ = self._norm_range(arr, operand, 0, None)
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), self._n)
        if self._n == 1:
            return arr
        lo, hi, contiguous = self._ranges_span(ranges)
        if algo == "auto":
            if not contiguous or not operand.is_numeric:
                algo = "ring"
            elif self._use_twolevel():
                algo = "twolevel"
            else:
                algo = tuning.select_partitioned_algo(
                    (hi - lo) * operand.dtype.itemsize, self._n,
                    self._algo_small, self._algo_large)
        if algo == "twolevel" and not operand.is_numeric:
            # the two-level engine is numeric-only (it rides the raw
            # segment plane); the ring handles object operands
            algo = "ring"
        if algo == "twolevel":
            if not contiguous:
                raise Mp4jError(
                    "allgather algo='twolevel' needs contiguous ranges")
            return self._twolevel_allgather(arr, ranges, operand)
        if algo == "tree":
            if not contiguous:
                raise Mp4jError(
                    "allgather algo='tree' needs contiguous ranges")
            self.gather_array(arr, operand, root=0, ranges=ranges)
            return self.broadcast_array(arr, operand, root=0,
                                        from_=lo, to=hi)
        self._ring_allgather(arr, ranges, operand)
        return arr

    def _ring_reduce_scatter(self, arr, segs, operand, operator):
        """After n-1 ring steps, rank r holds segment r fully reduced.

        Step s: send segment (r-1-s) mod n (the one merged last step),
        receive segment (r-2-s) mod n from the left, merge with the
        local contribution — pipelined: the merge of chunk k runs while
        chunk k+1 is on the wire. Receive buffers rotate through the
        scratch pool (the carry stays live as next step's send source,
        so two pooled buffers alternate)."""
        n, r = self._n, self._rank
        numeric = isinstance(arr, np.ndarray)
        raw = self._raw_ok(operand) and numeric
        right, left = (r + 1) % n, (r - 1) % n
        carry = None       # accumulated segment in flight
        carry_buf = None   # pooled buffer backing the carry
        for s in range(n - 1):
            send_idx = (r - 1 - s) % n
            ss, se = segs[send_idx]
            out = carry if carry is not None else arr[ss:se]
            ri_s, ri_e = segs[(r - 2 - s) % n]
            local = arr[ri_s:ri_e]
            if numeric:
                rbuf = self._recv_buf(operand, ri_e - ri_s)

                def merge(a, b, rbuf=rbuf, local=local):
                    self._reduce_into(operator, rbuf[a:b], local[a:b])

                if raw:
                    self._chunked_exchange(right, left, out, rbuf,
                                           on_chunk=merge)
                else:
                    fut = self._submit_send(
                        right, np.ascontiguousarray(out),
                        operand.compress)
                    self._fenced(left).recv_array_into(rbuf,
                                                       on_chunk=merge)
                    fut.result()
                # the previous carry finished its last duty (this
                # step's send) — recycle its buffer
                if carry_buf is not None:
                    self._give_buf(carry_buf)
                carry = carry_buf = rbuf
            else:
                recv = self._sendrecv(right, left, out,
                                      compress=operand.compress)
                carry = [operator.np_fn(a, b)
                         for a, b in zip(recv, local)]
        # carry is now my fully-reduced segment (index r)
        ms, me = segs[r]
        arr[ms:me] = carry
        if carry_buf is not None:
            self._give_buf(carry_buf)
        return arr

    def _ring_allgather(self, arr, segs, operand: Operand):
        """After n-1 ring steps every rank holds all segments (no merge
        to overlap; raw exchanges are full-duplex and land in place,
        framed receives stream straight into the destination view)."""
        n, r = self._n, self._rank
        numeric = isinstance(arr, np.ndarray)
        raw = self._raw_ok(operand) and numeric
        right, left = (r + 1) % n, (r - 1) % n
        for s in range(n - 1):
            ss, se = segs[(r - s) % n]
            seg = arr[ss:se]
            rs, re = segs[(r - 1 - s) % n]
            if raw:
                self._exchange_raw_into(right, left, seg, arr[rs:re],
                                        operand)
            elif numeric and operand.is_numeric:
                fut = self._submit_send(
                    right, np.ascontiguousarray(seg),
                    operand.compress)
                self._recv_segment_into(left, arr, rs, re, operand)
                fut.result()
            else:
                recv = self._sendrecv(right, left, seg,
                                      compress=operand.compress)
                arr[rs:re] = recv
        return arr

    def reduce_array(self, arr, operand: Operand = Operands.FLOAT,
                     operator: Operator = Operators.SUM, root: int = 0,
                     from_: int = 0, to: int | None = None):
        """Binomial-tree reduce into ``root``'s buffer."""
        self._check_root(root)
        arr, lo, hi = self._norm_range(arr, operand, from_, to)
        if self._n == 1 or hi == lo:
            return arr
        vr = (self._rank - root) % self._n
        acc = arr[lo:hi]
        numeric = isinstance(acc, np.ndarray)
        if numeric:
            acc = acc.copy()
        else:
            # value-level copy (see _copy_value): the merge applies
            # the user operator to acc's elements, and an in-place op
            # must not reach the caller's objects — reduce_array is
            # _SNAPSHOT_FREE on the strength of this copy
            acc = [_copy_value(v) for v in acc]
        mask = 1
        while mask < self._n:
            if vr & mask:
                peer = ((vr - mask) + root) % self._n
                # the parent drains via _recv_reduce: chunk-matched
                # send (the shm routing contract)
                self._send_reduce_contrib(peer, acc, operand)
                break
            else:
                src_vr = vr + mask
                if src_vr < self._n:
                    peer = (src_vr + root) % self._n
                    if numeric:
                        # pipelined: merge chunk k while k+1 arrives
                        self._recv_reduce(peer, acc, operator, operand)
                    else:
                        recv = self._recv(peer)
                        acc = self._merge(operator, operand, acc, recv)
            mask <<= 1
        if self._rank == root:
            arr[lo:hi] = acc
        return arr

    def broadcast_array(self, arr, operand: Operand = Operands.FLOAT,
                        root: int = 0, from_: int = 0, to: int | None = None):
        """Binomial-tree broadcast of ``root``'s ``arr[from_:to]``."""
        self._check_root(root)
        arr, lo, hi = self._norm_range(arr, operand, from_, to)
        if self._n == 1 or hi == lo:
            return arr
        vr = (self._rank - root) % self._n
        mask = 1
        have = vr == 0
        while mask < self._n:
            if have:
                # every holder (vr < mask) sends to vr + mask this round
                dst_vr = vr + mask
                if dst_vr < self._n:
                    self._send_segment((dst_vr + root) % self._n,
                                       arr[lo:hi], operand)
            elif mask <= vr < 2 * mask:
                peer = ((vr - mask) + root) % self._n
                self._recv_segment_into(peer, arr, lo, hi, operand)
                have = True
            mask <<= 1
        return arr

    def gather_array(self, arr, operand: Operand = Operands.FLOAT,
                     root: int = 0, ranges=None):
        """Every rank's segment lands in ``root``'s buffer (direct sends)."""
        self._check_root(root)
        arr, _, _ = self._norm_range(arr, operand, 0, None)
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), self._n)
        if self._n == 1:
            return arr
        if self._rank == root:
            for peer in range(self._n):
                if peer == root:
                    continue
                s, e = ranges[peer]
                self._recv_segment_into(peer, arr, s, e, operand)
        else:
            s, e = ranges[self._rank]
            self._send_segment(root, arr[s:e], operand)
        return arr

    def scatter_array(self, arr, operand: Operand = Operands.FLOAT,
                      root: int = 0, ranges=None):
        """Rank r receives segment ``ranges[r]`` of ``root``'s buffer."""
        self._check_root(root)
        arr, _, _ = self._norm_range(arr, operand, 0, None)
        if ranges is None:
            ranges = meta.partition_range(0, len(arr), self._n)
        if self._n == 1:
            return arr
        if self._rank == root:
            for peer in range(self._n):
                if peer == root:
                    continue
                s, e = ranges[peer]
                self._send_segment(peer, arr[s:e], operand)
        else:
            s, e = ranges[self._rank]
            self._recv_segment_into(root, arr, s, e, operand)
        return arr


    # ------------------------------------------------------------------
    # collectives: sparse maps (reference: *Map methods, SURVEY.md 3c)
    #
    # Two wire planes, selected per call:
    #
    # - COLUMNAR (default for numeric operands with ufunc operators,
    #   ISSUE 4): each map is encoded ONCE through the persistent
    #   comm.keycodec vocabulary into a (codes:int32,
    #   values:[n, *vshape]) pair and shipped as a paired framed-array
    #   unit (Channel.send_map_columns) — inheriting the framed plane's
    #   streaming compression, no-zero-fill receives and comm.stats()
    #   wire/serialize attribution — and merged with vectorized
    #   sorted-union + segment-reduce kernels (ops.sparse numpy twins)
    #   instead of a per-key Python loop. Vocabulary sync is part of
    #   the collective: novel keys ride a small pickled header exchange
    #   (near-empty once a gradient stream's vocabulary stabilizes) and
    #   every rank grows its codec with the same canonical key list, so
    #   code->key tables stay IDENTICAL job-wide — the invariant every
    #   later call's codes rely on. Columnar merges compute in the
    #   operand dtype (the declared operand is load-bearing, matching
    #   the device path's pack_values cast).
    # - PICKLED dicts (the Kryo analogue; the frozen reference wire
    #   under map_columnar=False): STRING/OBJECT operands, non-ufunc
    #   (object) operators, and any call whose negotiated header
    #   reports un-codec-able content on some rank (mixed/unsortable
    #   key kinds, ragged or object values). The negotiation makes the
    #   fallback a JOB-wIDE decision carried on the wire — ranks can
    #   never disagree about the plane of one exchange.
    #
    # (History: an earlier in-line note here measured a packed merge as
    # a LOSS at 20k-200k int keys — but that variant re-paid a full
    # per-call sorted-union + Python pack, exactly the work the
    # grow-only codec amortizes away. The honest re-run is bench.py's
    # socket_map_allreduce_sweep columnar-vs-pickle A/B, BENCH extra.)
    #
    # In-place semantics on every plane: the caller's dict is mutated.
    # ------------------------------------------------------------------
    @staticmethod
    def _merge_maps(operator: Operator, acc: dict, src: dict) -> dict:
        # the pickled plane's per-key merge loop (dict ops are C-level;
        # the columnar plane replaces this wholesale, see above)
        for k, v in src.items():
            if k in acc:
                acc[k] = operator.np_fn(acc[k], v)
            else:
                acc[k] = v
        return acc

    # -- the map planes' shared binomial-tree walks ---------------------
    # ONE copy of each walk, parameterized by the per-plane send/recv
    # callables: a protocol tweak (rank math, timeouts) lands on every
    # plane at once instead of needing six synchronized edits.
    def _walk_coords(self, root: int, group) -> tuple[int, int, list]:
        """(n, vr, rankmap) for a binomial walk over ``group`` (None =
        all ranks): ``vr`` is this rank's virtual index relative to
        ``root``; ``rankmap[v]`` the global rank at virtual index v.
        Group walks are the two-level engine's substrate (ISSUE 7):
        the SAME walk code serves the whole job, one host's members,
        or the host-leader set — the mapping is the only difference."""
        if group is None:
            n = self._n
            vr = (self._rank - root) % n
            return n, vr, [(v + root) % n for v in range(n)]
        n = len(group)
        ri = group.index(root)
        vr = (group.index(self._rank) - ri) % n
        return n, vr, [group[(v + ri) % n] for v in range(n)]

    def _tree_reduce_walk(self, value, root: int, send, recv_merge,
                          group=None):
        """Up-sweep: ``value`` merges toward ``root``. ``send(peer,
        value)`` ships this rank's merged value to its parent;
        ``recv_merge(peer, value) -> value`` receives a child's
        contribution and merges it in. Returns the full merge at
        ``root`` (a partial merge elsewhere). ``group`` restricts the
        walk to a rank subset (this rank and ``root`` must belong)."""
        n, vr, rankmap = self._walk_coords(root, group)
        mask = 1
        while mask < n:
            if vr & mask:
                send(rankmap[vr - mask], value)
                break
            src_vr = vr + mask
            if src_vr < n:
                value = recv_merge(rankmap[src_vr], value)
            mask <<= 1
        return value

    def _tree_bcast_walk(self, value, root: int, send, recv,
                         group=None):
        """Down-sweep: ``root``'s ``value`` reaches every rank (of
        ``group``, when given). ``recv(peer) -> value`` replaces the
        local value on first receipt; holders forward with
        ``send(peer, value)``."""
        n, vr, rankmap = self._walk_coords(root, group)
        mask = 1
        have = vr == 0
        while mask < n:
            if have:
                dst_vr = vr + mask
                if dst_vr < n:
                    send(rankmap[dst_vr], value)
            elif mask <= vr < 2 * mask:
                value = recv(rankmap[vr - mask])
                have = True
            mask <<= 1
        return value

    # -- columnar plane: negotiation / codec plumbing -------------------
    def _map_columnar_ok(self, operand: Operand,
                         operator: Operator | None = None) -> bool:
        """Whether this call may negotiate the columnar plane — a pure
        function of job-wide call parameters (operand, operator, the
        job's map_columnar flag), NEVER of rank-local map content: both
        ends of every exchange must agree whether a negotiation header
        travels at all (R4 discipline). Map-content problems are
        handled by the negotiation itself."""
        if not (self._map_columnar and operand.columnar_maps):
            return False
        if operator is None:
            return True
        # segment-reduce needs a real binary ufunc (reduceat); object
        # operators (plain Python callables) keep the pickled plane
        return isinstance(operator.np_fn, np.ufunc) and \
            operator.np_fn.nin == 2

    def _map_codec(self, kind: str):
        codec = self._map_codecs.get(kind)
        if codec is None:
            codec = self._map_codecs[kind] = keycodec.codec_for_kind(kind)
        return codec

    def _map_local_header(self, d: dict, operand: Operand):
        """``((ok, kind, vshape, novel), packed_values)`` for THIS
        rank's map. All local validation happens here, BEFORE any wire
        exchange, and its outcome rides the header: a bad map on one
        rank must divert EVERY rank to the pickled plane, not error on
        one side of an exchange (cf. distributed._union_device)."""
        if not d:
            return (True, None, None, []), None
        k0 = next(iter(d))
        kind = keycodec.kind_of(k0)
        codec = self._map_codec(kind)
        t0 = time.perf_counter()
        try:
            novel = codec.novel(d.keys(), len(d))
            vshape = tuple(np.shape(d[k0]))
            vals = keycodec.pack_values(d.values(), len(d), vshape,
                                        operand.dtype)
        except Mp4jError:
            return (False, kind, None, []), None
        self._comm_stats.add("serialize_seconds",
                             time.perf_counter() - t0)
        return (True, kind, vshape, novel), vals

    @staticmethod
    def _merge_map_headers(a, b):
        """Associative header merge for the sync up-sweep."""
        ok = a[0] and b[0]
        kind = a[1] if a[1] is not None else b[1]
        if a[1] is not None and b[1] is not None and a[1] != b[1]:
            ok = False
        vshape = a[2] if a[2] is not None else b[2]
        if a[2] is not None and b[2] is not None and a[2] != b[2]:
            ok = False
        novel = a[3] if not b[3] else list(dict.fromkeys(a[3] + b[3]))
        return (ok, kind, vshape, novel)

    @staticmethod
    def _map_decision(header):
        """Root's plane decision from the merged header: ``("col",
        kind, vshape, canonical_novel)``, ``("nop",)`` (every map
        empty), or ``("obj",)`` (negotiated pickle fallback). The
        canonical novelty order is sorted — the one growth order every
        rank can derive identically; an unsortable key mix cannot be
        canonicalized and falls back."""
        ok, kind, vshape, novel = header
        if not ok:
            return ("obj",)
        if kind is None:
            return ("nop",)
        try:
            canonical = sorted(novel)
        except TypeError:
            return ("obj",)
        return ("col", kind, vshape, canonical)

    def _map_bcast_obj(self, obj, root: int):
        """Binomial-tree broadcast of one small pickled object (the
        decision header)."""
        return self._tree_bcast_walk(obj, root, self._send, self._recv)

    def _map_sync(self, header, root: int):
        """Vocabulary-sync + plane-negotiation round: headers merge up
        the binomial tree to ``root``, the decision broadcasts back
        down, and on ``"col"`` every rank (including this one) grows
        its codec with the same canonical novelty — so every rank
        returns the same decision over identical code->key tables."""
        header = self._tree_reduce_walk(
            header, root, self._send,
            lambda peer, h: self._merge_map_headers(
                h, self._recv(peer)))
        decision = (self._map_decision(header)
                    if self._rank == root else None)
        decision = self._map_bcast_obj(decision, root)
        if decision[0] == "col":
            self._grow_map_codec(decision)
        return decision

    def _grow_map_codec(self, decision) -> None:
        _, kind, _vshape, canonical = decision
        if canonical:
            t0 = time.perf_counter()
            self._map_codec(kind).encode(canonical, len(canonical))
            self._comm_stats.add("serialize_seconds",
                                 time.perf_counter() - t0)

    # -- columnar plane: data movement ----------------------------------
    def _encode_map_columns(self, d: dict, decision, vals,
                            operand: Operand):
        """This rank's code-sorted (codes, values) columns. Every key
        is already in the vocabulary (the sync grew it), so encode is a
        pure vectorized lookup."""
        _, kind, vshape, _ = decision
        t0 = time.perf_counter()
        if not d:
            codes = np.empty(0, np.int32)
            vals = np.empty((0,) + tuple(vshape), operand.dtype)
        else:
            codes = self._map_codec(kind).encode(d.keys(), len(d))
        order = np.argsort(codes)
        cols = (codes[order], vals[order])
        self._comm_stats.add("serialize_seconds",
                             time.perf_counter() - t0)
        self._comm_stats.add("keys", int(codes.size))
        return cols

    def _decode_map_columns(self, decision, codes, vals) -> dict:
        t0 = time.perf_counter()
        out = dict(zip(self._map_codec(decision[1]).decode(codes),
                       list(vals)))
        self._comm_stats.add("serialize_seconds",
                             time.perf_counter() - t0)
        return out

    def _send_map_columns(self, peer: int, cols, operand: Operand):
        self._fenced(peer).send_map_columns(
            cols[0], cols[1],
            compress=self._compress_for(peer, operand.compress))

    def _recv_map_columns(self, peer: int):
        # peer channels carry peer_timeout from creation
        # mp4j-lint: disable=R2 (peer_timeout is set at channel creation)
        return self._fenced(peer).recv_map_columns()

    def _merge_map_columns(self, acc, src, operator: Operator):
        """Vectorized sorted-union merge, acc side first — the same
        ``op(acc[k], src[k])`` operand order as the dict loop, so the
        two planes agree bit-for-bit (ops.sparse contract)."""
        t0 = time.perf_counter()
        out = sparse_ops.np_merge_sorted_columns(
            acc[0], acc[1], src[0], src[1], operator.np_fn)
        self._comm_stats.add("reduce_seconds", time.perf_counter() - t0)
        return out

    def _reduce_map_columns(self, d: dict, vals, operand: Operand,
                            operator: Operator, root: int, decision,
                            group=None, cols=None):
        """Binomial-tree columnar reduce (over ``group`` when given);
        the returned columns are the full union at ``root`` (partial
        elsewhere). ``cols`` skips the encode for callers chaining
        walks over already-encoded columns (the two-level legs)."""
        if cols is None:
            cols = self._encode_map_columns(d, decision, vals, operand)
        return self._tree_reduce_walk(
            cols, root,
            lambda peer, acc: self._send_map_columns(peer, acc, operand),
            lambda peer, acc: self._merge_map_columns(
                acc, self._recv_map_columns(peer), operator),
            group=group)

    def _bcast_map_columns(self, cols, root: int, operand: Operand,
                           group=None):
        """Binomial-tree broadcast of ``root``'s columns (over
        ``group`` when given)."""
        return self._tree_bcast_walk(
            cols, root,
            lambda peer, c: self._send_map_columns(peer, c, operand),
            self._recv_map_columns, group=group)

    def _twolevel_allreduce_map_columns(self, d: dict, vals,
                                        operand: Operand,
                                        operator: Operator, decision):
        """Two-level columnar map allreduce (ISSUE 7): merge columns to
        each host leader over the intra-host (shm) pairs, tree-
        allreduce among the leaders over TCP, broadcast back out —
        same merge operand order as the flat walk (acc side first), so
        results are bit-identical for order-insensitive operator/value
        combinations and the inter-host wire carries each column set
        once per host."""
        members, leaders = self._members, self._leaders
        cols = self._encode_map_columns(d, decision, vals, operand)
        if len(members) > 1:
            cols = self._reduce_map_columns(
                d, vals, operand, operator, self._leader, decision,
                group=members, cols=cols)
        if self._rank == self._leader and len(leaders) > 1:
            cols = self._reduce_map_columns(
                d, vals, operand, operator, leaders[0], decision,
                group=leaders, cols=cols)
            cols = self._bcast_map_columns(cols, leaders[0], operand,
                                           group=leaders)
        if len(members) > 1:
            cols = self._bcast_map_columns(cols, self._leader, operand,
                                           group=members)
        return cols

    # -- pickled plane (the sanctioned fallback) ------------------------
    def _send_map_obj(self, peer: int, d, operand: Operand) -> None:
        """The ONE sanctioned pickled-map send: STRING/OBJECT operands,
        object operators, and negotiated fallbacks route here (README
        "Sparse map collectives"; mp4j-lint R9 baseline entry)."""
        self._send(peer, d, compress=operand.compress)

    def _reduce_map_obj(self, d: dict, operand: Operand,
                        operator: Operator, root: int) -> dict:
        # value-level copy, not dict(d): _merge_maps runs the user
        # operator directly on acc's value objects, and an in-place
        # op would otherwise mutate the caller's values mid-protocol —
        # reduce_map is _SNAPSHOT_FREE on the strength of this copy
        acc = self._tree_reduce_walk(
            {k: _copy_value(v) for k, v in d.items()}, root,
            lambda peer, a: self._send_map_obj(peer, a, operand),
            lambda peer, a: self._merge_maps(operator, a,
                                             self._recv(peer)))
        if self._rank == root:
            d.clear()
            d.update(acc)
        return d

    def _broadcast_map_obj(self, d: dict, operand: Operand,
                           root: int) -> dict:
        out = self._tree_bcast_walk(
            d, root,
            lambda peer, m: self._send_map_obj(peer, m, operand),
            self._recv)
        if out is not d:
            d.clear()
            d.update(out)
        return d

    def _gather_map_obj(self, d: dict, operand: Operand,
                        root: int) -> dict:
        if self._rank == root:
            owners = {k: root for k in d}
            for peer in range(self._n):
                if peer == root:
                    continue
                recv = self._recv(peer)
                for k, v in recv.items():
                    if k in d:
                        raise Mp4jError(
                            f"gather_map: duplicate key {k!r} owned by "
                            f"ranks {owners[k]} and {peer}; use "
                            f"reduce_map to combine")
                    d[k] = v
                    owners[k] = peer
        else:
            self._send_map_obj(root, d, operand)
        return d

    # -- the map collective family --------------------------------------
    def allreduce_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                      operator: Operator = Operators.SUM) -> dict:
        """Key-union reduce; every rank ends with the merged map. On
        the columnar plane the union stays in (codes, values) form end
        to end: one encode, log2(n) vectorized merges, one column
        broadcast, one decode."""
        if self._n == 1:
            return d
        if self._map_columnar_ok(operand, operator):
            header, vals = self._map_local_header(d, operand)
            decision = self._map_sync(header, 0)
            if decision[0] == "nop":
                return d
            if decision[0] == "col":
                if self._use_twolevel():
                    cols = self._twolevel_allreduce_map_columns(
                        d, vals, operand, operator, decision)
                else:
                    cols = self._reduce_map_columns(d, vals, operand,
                                                    operator, 0,
                                                    decision)
                    cols = self._bcast_map_columns(cols, 0, operand)
                merged = self._decode_map_columns(decision, *cols)
                d.clear()
                d.update(merged)
                return d
        self._reduce_map_obj(d, operand, operator, 0)
        return self._broadcast_map_obj(d, operand, 0)

    def reduce_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                   operator: Operator = Operators.SUM, root: int = 0) -> dict:
        """Binomial-tree key-wise merge into ``root``'s map."""
        self._check_root(root)
        if self._n == 1:
            return d
        if self._map_columnar_ok(operand, operator):
            header, vals = self._map_local_header(d, operand)
            decision = self._map_sync(header, root)
            if decision[0] == "nop":
                return d
            if decision[0] == "col":
                cols = self._reduce_map_columns(d, vals, operand,
                                                operator, root, decision)
                if self._rank == root:
                    merged = self._decode_map_columns(decision, *cols)
                    d.clear()
                    d.update(merged)
                return d
        return self._reduce_map_obj(d, operand, operator, root)

    def broadcast_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                      root: int = 0) -> dict:
        """Binomial-tree broadcast of ``root``'s map. Columnar: only
        root's keys matter, so the decision (with root's canonical
        novelty) rides the broadcast tree itself — no up-sweep."""
        self._check_root(root)
        if self._n == 1:
            return d
        if self._map_columnar_ok(operand):
            vals = None
            decision = None
            if self._rank == root:
                header, vals = self._map_local_header(d, operand)
                decision = self._map_decision(header)
            decision = self._map_bcast_obj(decision, root)
            if decision[0] == "nop":
                d.clear()      # root's map is empty; every copy is
                return d
            if decision[0] == "col":
                self._grow_map_codec(decision)
                cols = (self._encode_map_columns(d, decision, vals,
                                                 operand)
                        if self._rank == root else None)
                cols = self._bcast_map_columns(cols, root, operand)
                if self._rank != root:
                    d.clear()
                    d.update(self._decode_map_columns(decision, *cols))
                return d
        return self._broadcast_map_obj(d, operand, root)

    def gather_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                   root: int = 0) -> dict:
        """Disjoint union into ``root``'s map. A duplicate key raises
        an Mp4jError naming the key and BOTH owner ranks."""
        self._check_root(root)
        if self._n == 1:
            return d
        if self._map_columnar_ok(operand):
            header, vals = self._map_local_header(d, operand)
            if self._rank != root:
                self._send(root, header)
                decision = self._recv(root)
                if decision[0] == "col":
                    self._grow_map_codec(decision)
                    self._send_map_columns(
                        root,
                        self._encode_map_columns(d, decision, vals,
                                                 operand),
                        operand)
                    return d
                if decision[0] == "nop":
                    return d
            else:
                for peer in range(self._n):
                    if peer != root:
                        header = self._merge_map_headers(
                            header, self._recv(peer))
                decision = self._map_decision(header)
                for peer in range(self._n):
                    if peer != root:
                        self._send(peer, decision)
                if decision[0] == "nop":
                    return d
                if decision[0] == "col":
                    self._grow_map_codec(decision)
                    return self._gather_map_columns(d, decision,
                                                    operand, root)
        return self._gather_map_obj(d, operand, root)

    def _gather_map_columns(self, d: dict, decision, operand: Operand,
                            root: int) -> dict:
        """Root side of the columnar gather: collect every peer's
        columns, then ONE stable sort over (code, owner) and an
        adjacent-equality scan detects duplicates (naming the key and
        both owner ranks — concat order root-then-peers-ascending, so
        the pair reads in rank order). ``d`` is only mutated once the
        whole union is proven disjoint."""
        codec = self._map_codec(decision[1])
        own = (codec.encode(d.keys(), len(d)) if d
               else np.empty(0, np.int32))
        cols = [(own, None, root)]      # root's values stay in d
        for peer in range(self._n):
            if peer != root:
                rc, rv = self._recv_map_columns(peer)
                cols.append((rc, rv, peer))
        codes = np.concatenate([c for c, _, _ in cols])
        owners = np.concatenate([np.full(c.size, p, np.int32)
                                 for c, _, p in cols])
        order = np.argsort(codes, kind="stable")
        sc, so = codes[order], owners[order]
        dup = np.flatnonzero(sc[1:] == sc[:-1])
        if dup.size:
            i = int(dup[0])
            key = codec.decode(sc[i:i + 1])[0]
            raise Mp4jError(
                f"gather_map: duplicate key {key!r} owned by ranks "
                f"{int(so[i])} and {int(so[i + 1])}; use reduce_map "
                f"to combine")
        for rc, rv, _peer in cols[1:]:
            d.update(zip(codec.decode(rc), list(rv)))
        return d

    def allgather_map(self, d: dict, operand: Operand = Operands.DOUBLE) -> dict:
        """Disjoint union everywhere (gather to 0 + broadcast)."""
        self.gather_map(d, operand, root=0)
        return self.broadcast_map(d, operand, root=0)

    def scatter_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                    root: int = 0, partitioner=None) -> dict:
        """Rank r keeps the subset of ``root``'s entries whose keys hash
        to r (meta.key_partition — matches the TPU backend).

        ``partitioner(key) -> rank`` overrides the placement rule (the
        thread backend uses this to place by GLOBAL thread rank while
        shipping each process only its threads' share). The columnar
        plane's default placement rides the codec's cached per-code
        blake2b partition — the per-key hash is paid once per key ever,
        not once per call."""
        self._check_root(root)
        if self._n == 1:
            return d
        if self._map_columnar_ok(operand):
            return self._scatter_map_negotiated(d, operand, root,
                                                partitioner)
        return self._scatter_map_obj(d, operand, root, partitioner)

    def _scatter_map_negotiated(self, d: dict, operand: Operand,
                                root: int, partitioner) -> dict:
        """Scatter under the columnar gate: root decides the plane from
        its own map (only its keys travel) and prefixes every share
        with the decision; placement (and its validation) runs BEFORE
        any send so a bad partitioner raises without wedging peers
        mid-protocol."""
        if self._rank != root:
            decision = self._recv(root)
            if decision[0] == "col":
                self._grow_map_codec(decision)
                cols = self._recv_map_columns(root)
                d.clear()
                d.update(self._decode_map_columns(decision, *cols))
            elif decision[0] == "nop":
                d.clear()
            else:
                recv = self._recv(root)
                d.clear()
                d.update(recv)
            return d
        header, vals = self._map_local_header(d, operand)
        decision = self._map_decision(header)
        if decision[0] == "obj":
            for peer in range(self._n):
                if peer != root:
                    self._send(peer, decision)
            return self._scatter_map_obj(d, operand, root, partitioner,
                                         _negotiated=True)
        if decision[0] == "nop":
            for peer in range(self._n):
                if peer != root:
                    self._send(peer, decision)
            return d
        self._grow_map_codec(decision)
        codec = self._map_codec(decision[1])
        codes = (codec.encode(d.keys(), len(d)) if d
                 else np.empty(0, np.int32))
        if partitioner is None:
            part = codec.partition(codes, self._n)
        else:
            part = np.fromiter(
                (meta.check_partition_rank(partitioner(k), self._n, k)
                 for k in d.keys()), np.int32, len(d))
        for peer in range(self._n):
            if peer == root:
                continue
            self._send(peer, decision)
            m = part == peer
            self._send_map_columns(peer, (codes[m], vals[m]), operand)
        self._comm_stats.add("keys", int(codes.size))
        mine = part == root
        merged = self._decode_map_columns(decision, codes[mine],
                                          vals[mine])
        d.clear()
        d.update(merged)
        return d

    def _scatter_map_obj(self, d: dict, operand: Operand, root: int,
                         partitioner, _negotiated: bool = False) -> dict:
        if partitioner is None:
            partitioner = lambda k: meta.key_partition(k, self._n)  # noqa: E731
        if self._rank == root:
            shares: list[dict] = [{} for _ in range(self._n)]
            for k, v in d.items():
                shares[meta.check_partition_rank(
                    partitioner(k), self._n, k)][k] = v
            for peer in range(self._n):
                if peer != root:
                    self._send_map_obj(peer, shares[peer], operand)
            d.clear()
            d.update(shares[root])
        elif _negotiated:
            raise Mp4jError("scatter_map protocol error: non-root "
                            "reached the fallback sender")  # unreachable
        else:
            recv = self._recv(root)
            d.clear()
            d.update(recv)
        return d

    def reduce_scatter_map(self, d: dict, operand: Operand = Operands.DOUBLE,
                           operator: Operator = Operators.SUM) -> dict:
        """Key-union reduce, then each rank keeps its hash share."""
        self.reduce_map(d, operand, operator, root=0)
        return self.scatter_map(d, operand, root=0)

    # ------------------------------------------------------------------
    # nonblocking collectives (ISSUE 11) — see comm/progress.py
    #
    # Each i* method submits to the per-slave helper progression
    # thread and returns a CollectiveFuture; the blocking twin is
    # exactly i*(...).wait() in semantics AND bytes (the engine mirrors
    # the blocking schedules bit-for-bit; ineligible submissions
    # execute the blocking method itself on the progression thread).
    # Blocking collectives, barrier() and close() drain outstanding
    # futures first — comm.wait_all() is the explicit drain.
    # ------------------------------------------------------------------
    def _sched(self) -> progress_mod.ProgressScheduler:
        sched = self._async
        if sched is None:
            with self._async_lock:
                sched = self._async
                if sched is None:
                    sched = progress_mod.ProgressScheduler(self)
                    self._async = sched
        return sched

    def _iclassify(self, name: str, args: tuple, kwargs: dict) -> str:
        if name == "allreduce_map":
            # the multi (count-negotiating) protocol is a JOB-wide
            # choice: selected purely by the coalescing knob and the
            # call's operand/operator — never by rank-local queue depth
            if self._coalesce_usecs > 0 \
                    and self._map_columnar_ok(args[1], args[2]):
                return "map"
            return "inline"
        if name == "allreduce_array" and self._coalesce_usecs > 0 \
                and self._array_multi_ok(args, kwargs):
            # the dense small-array twin of the map plane (ISSUE 17):
            # same job-wide protocol-selection rule as "map" above
            return "array"
        if progress_mod.engine_eligible(self, name, args, kwargs):
            return "engine"
        return "inline"

    def _isubmit(self, name: str, args: tuple,
                 kwargs: dict) -> progress_mod.CollectiveFuture:
        if not self._async_on:
            # MP4J_ASYNC=0: eager caller-thread execution behind the
            # same future contract (the A/B + frozen-leg knob);
            # failures nobody awaits still surface at wait_all — the
            # drain's re-raise contract must not depend on the knob
            fut = progress_mod.CollectiveFuture(
                name, epoch=self._recovery.epoch)
            try:
                fut._resolve(getattr(self, name)(*args, **kwargs))
            except Exception as e:
                fut._fail(e)
                self._eager_failed.append(fut)
            return fut
        return self._sched().submit(name, args, kwargs,
                                    self._iclassify(name, args, kwargs))

    def iallreduce(self, arr, operand: Operand = Operands.FLOAT,
                   operator: Operator = Operators.SUM,
                   from_: int = 0, to: int | None = None,
                   algo: str = "auto") -> progress_mod.CollectiveFuture:
        """Nonblocking :meth:`allreduce_array`; ``.wait()`` returns the
        in-place reduced array."""
        return self._isubmit("allreduce_array", (arr, operand, operator),
                             {"from_": from_, "to": to, "algo": algo})

    def ireduce_scatter(self, arr, operand: Operand = Operands.FLOAT,
                        operator: Operator = Operators.SUM,
                        ranges=None, algo: str = "auto"
                        ) -> progress_mod.CollectiveFuture:
        """Nonblocking :meth:`reduce_scatter_array`."""
        return self._isubmit("reduce_scatter_array",
                             (arr, operand, operator),
                             {"ranges": ranges, "algo": algo})

    def iallgather(self, arr, operand: Operand = Operands.FLOAT,
                   ranges=None, algo: str = "auto"
                   ) -> progress_mod.CollectiveFuture:
        """Nonblocking :meth:`allgather_array`."""
        return self._isubmit("allgather_array", (arr, operand),
                             {"ranges": ranges, "algo": algo})

    def igather(self, arr, operand: Operand = Operands.FLOAT,
                root: int = 0, ranges=None
                ) -> progress_mod.CollectiveFuture:
        """Nonblocking :meth:`gather_array`."""
        return self._isubmit("gather_array", (arr, operand),
                             {"root": root, "ranges": ranges})

    def iallreduce_map(self, d: dict,
                       operand: Operand = Operands.DOUBLE,
                       operator: Operator = Operators.SUM
                       ) -> progress_mod.CollectiveFuture:
        """Nonblocking :meth:`allreduce_map`. Under
        ``MP4J_COALESCE_USECS > 0``, submissions arriving within the
        window fuse into one negotiation + columnar frame train
        (:meth:`allreduce_map_multi`) and de-fuse on completion."""
        return self._isubmit("allreduce_map", (d, operand, operator),
                             {})

    def wait_all(self, timeout: float | None = None) -> None:
        """The collective-boundary drain: block until every
        outstanding nonblocking collective resolved; re-raises the
        first failure among futures nobody awaited (eager-mode
        failures included — the contract must not depend on
        ``MP4J_ASYNC``)."""
        if self._async is not None:
            self._async.wait_all(timeout)
        while self._eager_failed:
            fut = self._eager_failed.pop(0)
            if not fut._observed:
                fut._observed = True
                raise fut._exc

    def outstanding(self) -> int:
        """How many nonblocking collectives are queued or in flight."""
        return (0 if self._async is None
                else self._async.outstanding())

    # -- the fused (coalesced) map collective ---------------------------
    @staticmethod
    def _merge_map_headers_multi(a, b):
        """Header merge for the count-negotiating sync: the classic
        4-field merge plus the fused-batch count, combined with MIN —
        the largest batch every rank can serve this round."""
        return ProcessCommSlave._merge_map_headers(
            a[:4], b[:4]) + (min(a[4], b[4]),)

    def _map_sync_multi(self, header, root: int):
        """Count-negotiating variant of :meth:`_map_sync` (ISSUE 11
        coalescing): the 5-field header ``(ok, kind, vshape, novel,
        count)`` merges up the tree, the root's decision gains the
        agreed batch size m = min(counts), and every rank grows its
        codec with the same canonical novelty. Novelty may cover maps
        beyond m (a deep coalescer offered more than the round
        serves): the growth is identical job-wide — harmless, and the
        next round's novelty exchange is near-empty for it."""
        header = self._tree_reduce_walk(
            header, root, self._send,
            lambda peer, h: self._merge_map_headers_multi(
                h, self._recv(peer)))
        decision = None
        if self._rank == root:
            decision = self._map_decision(header[:4]) + (header[4],)
        decision = self._map_bcast_obj(decision, root)
        if decision[0] == "col":
            self._grow_map_codec(decision[:-1])
        return decision

    def allreduce_map_multi(self, dicts: list,
                            operand: Operand = Operands.DOUBLE,
                            operator: Operator = Operators.SUM) -> int:
        """Fused key-union allreduce of SEVERAL maps under ONE
        vocabulary-sync negotiation (the small-message coalescing
        engine, ISSUE 11): each rank offers ``len(dicts)`` maps, the
        sync header negotiates the agreed batch ``m = min`` over every
        rank's offer, and the first ``m`` maps ship as ``m``
        back-to-back columnar frame pairs per tree exchange — one
        negotiation round trip amortized over the whole batch, merged
        per slot (same acc-first operand order as the classic plane,
        so each map's result is bit-identical to its own
        ``allreduce_map``). Returns ``m``; callers re-offer the
        remainder. In-place on every merged map; maps past ``m`` are
        untouched."""
        if not isinstance(dicts, list) or not dicts:
            raise Mp4jError(
                "allreduce_map_multi needs a non-empty list of dicts")
        if self._n == 1:
            return len(dicts)
        offered = len(dicts)
        vals: list = [None] * offered
        if self._map_columnar_ok(operand, operator):
            ok, kind, vshape, novel = True, None, None, []
            for i, d in enumerate(dicts):
                h, vals[i] = self._map_local_header(d, operand)
                ok, kind, vshape, novel = self._merge_map_headers(
                    (ok, kind, vshape, novel), h)
            header = (ok, kind, vshape, novel, offered)
        else:
            # non-columnar operand/operator: negotiate the count all
            # the same, fuse over the pickled plane
            header = (False, None, None, [], offered)
        decision = self._map_sync_multi(header, 0)
        m = int(decision[-1])
        if decision[0] == "nop":
            return m
        if decision[0] == "col":
            cdec = decision[:-1]
            # per-slot encode (books its own serialize time)
            cols = [self._encode_map_columns(dicts[i], cdec, vals[i],
                                             operand)
                    for i in range(m)]

            def send(peer, cs):
                for c in cs:
                    self._send_map_columns(peer, c, operand)

            def recv_merge(peer, cs):
                # recv slot i then merge slot i, in slot order — the
                # peer sends its m pairs back-to-back in the same order
                return [self._merge_map_columns(
                    cs[i], self._recv_map_columns(peer), operator)
                    for i in range(m)]

            cols = self._tree_reduce_walk(cols, 0, send, recv_merge)

            def recv(peer):
                return [self._recv_map_columns(peer)
                        for _ in range(m)]

            cols = self._tree_bcast_walk(cols, 0, send, recv)
            for i in range(m):
                merged = self._decode_map_columns(cdec, *cols[i])
                dicts[i].clear()
                dicts[i].update(merged)
            if m > 1:
                self._comm_stats.add("coalesced_frames", 1)
            return m
        # negotiated pickled fallback, still fused: a list-of-dicts
        # payload per tree exchange, merged per slot (value-level
        # copies keep the caller's objects out of the user operator —
        # the _SNAPSHOT_FREE discipline of reduce_map)
        acc = [{k: _copy_value(v) for k, v in dicts[i].items()}
               for i in range(m)]

        def send_obj(peer, a):
            self._send_map_obj(peer, a, operand)

        def recv_merge_obj(peer, a):
            r = self._recv(peer)
            for i in range(m):
                self._merge_maps(operator, a[i], r[i])
            return a

        acc = self._tree_reduce_walk(acc, 0, send_obj, recv_merge_obj)
        acc = self._tree_bcast_walk(acc, 0, send_obj, self._recv)
        for i in range(m):
            dicts[i].clear()
            dicts[i].update(acc[i])
        if m > 1:
            self._comm_stats.add("coalesced_frames", 1)
        return m

    # -- the fused (coalesced) ARRAY collective (ISSUE 17) --------------
    @staticmethod
    def _merge_array_headers_multi(a, b):
        """Header merge for the array-plane count negotiation:
        ``(count, lengths, bad)`` — the agreed batch is the MIN count,
        and the per-slot lengths must agree over that prefix (ragged
        COUNTS are the protocol's whole point; ragged LENGTHS are a
        caller error surfaced job-wide)."""
        m = min(a[0], b[0])
        if a[1][:m] != b[1][:m]:
            return (m, a[1][:m], True)
        return (m, a[1][:m], a[2] or b[2])

    def _array_sync_multi(self, header, root: int):
        """Count-negotiating sync for :meth:`allreduce_array_multi`:
        the 3-field header merges up the binomial tree and the root's
        decision (agreed batch size m, or the length-mismatch error)
        broadcasts back — one small-object round trip amortized over
        the whole fused batch, exactly :meth:`_map_sync_multi`'s
        shape."""
        header = self._tree_reduce_walk(
            header, root, self._send,
            lambda peer, h: self._merge_array_headers_multi(
                h, self._recv(peer)))
        decision = header if self._rank == root else None
        return self._map_bcast_obj(decision, root)

    def allreduce_array_multi(self, arrs: list,
                              operand: Operand = Operands.FLOAT,
                              operator: Operator = Operators.SUM) -> int:
        """Fused allreduce of SEVERAL small dense arrays under ONE
        count negotiation (the ISSUE 11 map-coalescing engine ported
        to the array plane, ISSUE 17): each rank offers
        ``len(arrs)`` arrays, the sync negotiates the agreed batch
        ``m = min`` over every rank's offer, and the first ``m``
        arrays ship concatenated as ONE tree reduce + broadcast — the
        per-collective fixed cost (two tree walks of small frames,
        their syscalls and scheduler wakeups) amortizes across the
        batch.

        The fused exchange is pinned to the TREE schedule: each fused
        element's reduction association is the binomial-tree rank
        order regardless of array boundaries, which is exactly the
        schedule ``algo="auto"`` resolves for these arrays one at a
        time (small payloads -> "tree"), so every array's result is
        bit-identical to its own ``allreduce_array``. Returns ``m``;
        callers re-offer the remainder. In place on every merged
        array; arrays past ``m`` are untouched."""
        if not isinstance(arrs, list) or not arrs:
            raise Mp4jError(
                "allreduce_array_multi needs a non-empty list of arrays")
        if not operand.is_numeric:
            raise Mp4jError(
                "allreduce_array_multi is numeric-only (the dense "
                "small-array plane)")
        for a in arrs:
            if not (isinstance(a, np.ndarray) and a.ndim == 1
                    and a.flags.c_contiguous
                    and a.dtype == operand.dtype):
                raise Mp4jError(
                    "allreduce_array_multi needs 1-D contiguous "
                    f"arrays of dtype {operand.dtype}, got "
                    f"{type(a).__name__}"
                    + (f" {a.dtype} shape {a.shape}"
                       if isinstance(a, np.ndarray) else ""))
        if self._n == 1:
            return len(arrs)
        header = (len(arrs), tuple(int(a.size) for a in arrs), False)
        decision = self._array_sync_multi(header, 0)
        m, lengths, bad = decision
        if bad:
            raise Mp4jError(
                "allreduce_array_multi: ranks disagree on the fused "
                "arrays' lengths over the negotiated batch — every "
                "rank must offer identically-shaped slots")
        total = int(sum(lengths))
        if total:
            # one scratch buffer, one tree walk: the merge runs in
            # reduce_array's internal copy, the callers' arrays are
            # only READ until the final local scatter — snapshot-free
            # by the broadcast_map reasoning (_SNAPSHOT_FREE)
            scratch = np.empty(total, operand.dtype)
            off = 0
            for i in range(m):
                scratch[off:off + lengths[i]] = arrs[i]
                off += lengths[i]
            self.reduce_array(scratch, operand, operator, root=0)
            self.broadcast_array(scratch, operand, root=0)
            off = 0
            for i in range(m):
                arrs[i][:] = scratch[off:off + lengths[i]]
                off += lengths[i]
        if m > 1:
            self._comm_stats.add("coalesced_frames", 1)
            self._comm_stats.add("coalesced_elems", total)
        return m

    def _array_multi_ok(self, args: tuple, kwargs: dict) -> bool:
        """Whether an ``iallreduce`` submission may ride the fused
        array plane. A JOB-wide pure function of the call parameters
        (dtype/shape/size/knobs) — never of rank-local queue depth —
        so every rank classifies the same call sequence identically
        (the negotiated count then absorbs ragged coalescing depth)."""
        arr, operand = args[0], args[1]
        if not (isinstance(arr, np.ndarray) and arr.ndim == 1
                and arr.flags.c_contiguous
                and operand.is_numeric
                and arr.dtype == operand.dtype):
            return False
        if kwargs.get("from_", 0) != 0 or kwargs.get("to") is not None \
                or kwargs.get("algo", "auto") != "auto":
            return False
        if self._n <= 1 or self._use_twolevel():
            return False
        # only arrays whose auto schedule IS the tree (small payloads,
        # n >= 3): the fused walk is pinned to tree, and fused ==
        # sequential bit-exactness needs the blocking twin on the same
        # schedule
        return tuning.select_allreduce_algo(
            arr.nbytes, self._n, self._algo_small,
            self._algo_large) == "tree"

    # ------------------------------------------------------------------
    def _check_root(self, root: int):
        if not (0 <= root < self._n):
            raise Mp4jError(f"root {root} out of range [0, {self._n})")


# ----------------------------------------------------------------------
# epoch-fenced recovery wrapper (resilience.recovery, ISSUE 5)
#
# Installed UNDER trace.traced: a recovered retry stays inside the one
# traced/stats scope of its collective call (the wire cost of failed
# attempts books into the same bucket), and the DIAGNOSE hook fires
# only when recovery is exhausted — a successfully recovered fault
# never spams the master.
# ----------------------------------------------------------------------
# Collectives that are retry-idempotent WITHOUT an input snapshot —
# they never mutate the caller's buffer before their last wire
# operation, or mutate it only with pure overwrites a retry reproduces
# byte-for-byte:
#   broadcast/gather/scatter/allgather_array: receivers overwrite
#     segments with data the retry re-ships identically; senders read
#     intact data.
#   reduce_array / reduce_map: the merge runs in an internal copy; the
#     root writes back after its last receive, with no I/O after.
#   broadcast_map / scatter_map: d is rebuilt only after the walk (or
#     after the last share is sent) — no mid-protocol mutation.
# Everything else (allreduce: in-place halving merges; reduce_scatter:
# composed root mutation; gather/allgather/reduce_scatter_map: root's
# dict grows between receives) snapshots its input so a retry starts
# from the caller's original bytes. Keeping this set tight is a PERF
# decision: the snapshot memcpy is the resilience layer's only
# steady-state cost (bench.py socket_recovery steady_state).
_SNAPSHOT_FREE = frozenset({
    "broadcast_array", "gather_array", "scatter_array",
    "allgather_array", "reduce_array", "reduce_map", "broadcast_map",
    "scatter_map",
    # the fused map batch (ISSUE 11): merges run in internal column/
    # value copies; the caller's dicts mutate only after the last wire
    # operation of the walk — the broadcast_map reasoning, per slot
    "allreduce_map_multi",
    # the fused array batch (ISSUE 17): the tree walk runs on an
    # internal scratch concat; the callers' arrays are only read until
    # the final local scatter — same reasoning, per slot
    "allreduce_array_multi",
})

# Root-only mutators: every non-root rank only SENDS (both planes of
# gather_map go direct-to-root, no tree relay), so its payload is
# never touched and the retry snapshot copy is pure waste there. The
# map codec-size pin still applies on every rank.
_SNAPSHOT_ROOT_ONLY = frozenset({"gather_map"})


# immutable value types a container snapshot can share by reference
_IMMUTABLE_VALUES = (np.generic, int, float, complex, bool, str, bytes,
                     type(None))


def _copy_value(v):
    """Per-element snapshot copy for dict/list payloads. The dict-plane
    merge runs ``op(acc, src)`` directly on the caller's value objects,
    and a user operator may mutate ``acc`` in place — a shared
    reference would make the retry start from already-merged values.
    Immutables (the whole columnar numeric plane) stay zero-copy."""
    if isinstance(v, _IMMUTABLE_VALUES):
        return v
    if isinstance(v, np.ndarray):
        return v.copy()
    return copy.deepcopy(v)


def _preserve_payload(self, x):
    """Snapshot a collective's mutable input for retry idempotence.
    ndarray snapshots ride the slave's scratch pool — a fresh
    ``x.copy()`` per call would re-pay mmap + first-touch page faults
    for every MB, the exact cost the pool exists to amortize."""
    if isinstance(x, np.ndarray) and x.ndim == 1 and not x.dtype.hasobject:
        buf = self._scratch.take(x.dtype, x.size)
        np.copyto(buf, x)
        return buf
    if isinstance(x, np.ndarray):
        return x.copy()
    if isinstance(x, dict):
        return {k: _copy_value(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_copy_value(v) for v in x]
    return None


def _restore_payload(x, saved) -> None:
    """Put the snapshot back before a retry. Mutable container values
    are re-copied on EVERY restore so ``saved`` stays pristine — a
    second recovery round must not see the first retry's mutations.
    Dict elements of a list payload (the fused map batch, ISSUE 11)
    restore IN PLACE: the caller (the scheduler's futures) holds
    references to those exact dict objects."""
    if saved is None:
        return
    if isinstance(x, np.ndarray):
        x[:] = saved
    elif isinstance(x, dict):
        x.clear()
        x.update((k, _copy_value(v)) for k, v in saved.items())
    elif isinstance(x, list):
        for i, v in enumerate(saved):
            if isinstance(v, dict) and isinstance(x[i], dict):
                _restore_payload(x[i], v)
            else:
                x[i] = _copy_value(v)


def _recovered(fn, snapshot: bool):
    """Wrap a collective method with the abort/retry engine (outermost
    frame only — composed collectives recover as one unit) and, since
    ISSUE 8, with the audit plane's per-collective digest record: the
    input digests at entry (before any wire byte moves), the output at
    return, and every retry's restored snapshot is digest-compared
    against the original attempt's input — the snapshot-corruption
    class PR 5 fixed by hand is machine-checked here."""
    import inspect

    sig = inspect.signature(fn)
    params = list(sig.parameters)
    payload_name = params[1] if len(params) > 1 else None
    root_skip = None    # (index of root in *args, its default)
    if fn.__name__ in _SNAPSHOT_ROOT_ONLY and "root" in params:
        root_skip = (params.index("root") - 1,
                     sig.parameters["root"].default)
    # audit metadata extraction (replay needs operand/operator/root
    # by NAME): arg position + default per interesting param, plus the
    # length of the leading (payload, operand/operator/root...) run —
    # positional args past it (ranges, from_) mark the record
    # non-replayable rather than replaying a different call
    aud_params = {}
    for _nm in ("operand", "operator", "root", "algo"):
        if _nm in params:
            aud_params[_nm] = (params.index(_nm) - 1,
                               sig.parameters[_nm].default)
    lead = 1
    for _p in params[2:]:
        if _p in ("operand", "operator", "root"):
            lead += 1
        else:
            break
    _STD_KW = frozenset({"operand", "operator", "root", "algo",
                         payload_name})
    _defaults = {p: sig.parameters[p].default for p in params[1:]}

    def _aud_meta(args, kwargs) -> dict:
        def pick(nm):
            if nm not in aud_params:
                return None
            i, dflt = aud_params[nm]
            return args[i] if len(args) > i else kwargs.get(nm, dflt)

        meta: dict = {}
        operand = pick("operand")
        if operand is not None:
            meta["operand"] = operand.name
        operator = pick("operator")
        if operator is not None:
            meta["operator"] = operator.name
        if "root" in aud_params:
            meta["root"] = int(pick("root"))
        nonstd_kw = any(kwargs[k] is not _defaults.get(k, None)
                        and kwargs[k] != _defaults.get(k, None)
                        for k in set(kwargs) - _STD_KW)
        if len(args) > lead or nonstd_kw:
            meta["nonstd"] = True
        return meta

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        rec = getattr(self, "_recovery", None)
        if rec is None:
            return fn(self, *args, **kwargs)
        # collective-boundary drain (ISSUE 11): a blocking collective
        # entered while nonblocking futures are outstanding waits them
        # out first, so the job-wide collective order stays the submit
        # order (no-op on the progression thread itself — inline
        # execution runs the blocking methods there)
        sched = getattr(self, "_async", None)
        if sched is not None:
            sched.drain_for_blocking()
        outermost = rec.enter()
        try:
            if not outermost:
                return fn(self, *args, **kwargs)
            # tuner boundary application (ISSUE 15): pending per-link
            # decisions (and the audit-trip revert) land HERE, before
            # any wire byte of this collective moves — decisions never
            # change mid-collective. One attribute check when idle.
            tun = self._tuner
            if tun is not None and tun.dirty:
                self._tuner_apply(tun)
            ordinal = self._progress_state[0] + 1
            self._progress_state = (ordinal, True)
            if self._faults is not None:
                # retried attempts keep the first attempt's ordinal
                # (on_collective runs once per CALL), so a one-shot
                # fault cannot re-fire into its own recovery
                self._faults.on_collective(ordinal, self._fault_kill)
            # the audit payload is extracted unconditionally (digest
            # records cover every collective); the SNAPSHOT payload
            # below keeps its own tighter rules
            payload_a = args[0] if args else kwargs.get(payload_name)
            audit = self._audit
            arec = None
            if audit is not None:
                arec = audit.begin(ordinal, fn.__name__, payload_a,
                                   _aud_meta(args, kwargs))
            payload = None
            if snapshot:
                # by position OR keyword: a kwarg call must not skip
                # the snapshot and silently retry on mutated input
                payload = payload_a
                if root_skip is not None:
                    ri, rdefault = root_skip
                    root = (args[ri] if len(args) > ri
                            else kwargs.get("root", rdefault))
                    if root != self._rank:
                        payload = None   # see _SNAPSHOT_ROOT_ONLY
            is_map = (fn.__name__.endswith("_map")
                      or fn.__name__ == "allreduce_map_multi")
            saved_box = []

            def preserve():
                saved = _preserve_payload(self, payload)
                # map collectives also pin the key-codec sizes: a torn
                # decision broadcast can leave the vocabulary grown on
                # SOME ranks only, and a retry negotiating novelty
                # against half-grown codecs would desync code tables
                # job-wide — truncating back to the (identical)
                # pre-attempt sizes restores the invariant
                sizes = ({k: c.size for k, c in self._map_codecs.items()}
                         if is_map else None)
                # published for the adoption manifest (ISSUE 10): a
                # replacement round's vocabulary export must ship the
                # pre-attempt state every survivor rolls back to, not
                # this attempt's tentative growth
                self._codec_pin = sizes
                saved_box.append(saved)
                return (saved, sizes)

            def restore(pair):
                saved, sizes = pair
                if sizes is not None:
                    for k, c in self._map_codecs.items():
                        c.truncate(sizes.get(k, 0))
                _restore_payload(payload, saved)
                if arec is None:
                    return
                # failed attempt's wire folds died in the drain on the
                # peer side too — carrying them into the record would
                # false-diverge every recovered seq
                audit.reset_wire()
                if payload is not None and saved is not None:
                    # the machine check for PR 5's snapshot-corruption
                    # class: the restored input must digest exactly as
                    # the original attempt's input did — anything else
                    # means the snapshot was mutated (shared mutable
                    # values, a buggy operator) and a retry would
                    # produce silently wrong 'recovered' results
                    h, _sig = audit_mod.digest_payload(payload)
                    if h != arec["in"]:
                        raise Mp4jError(
                            f"audit: restored retry snapshot of "
                            f"'{fn.__name__}' (collective #{ordinal}) "
                            f"digests {h:#018x}, original input was "
                            f"{arec['in']:#018x} — the snapshot was "
                            "corrupted (in-place operator mutating "
                            "shared values?); refusing to retry from "
                            "tainted input")

            try:
                try:
                    out = rec.run(
                        fn.__name__,
                        lambda: fn(self, *args, **kwargs),
                        preserve, restore)
                except BaseException as e:
                    if arec is not None:
                        audit.abandon(arec, e)
                    raise
                if arec is not None:
                    audit.commit(arec, payload_a)
                return out
            finally:
                self._progress_state = (ordinal, False)
                self._codec_pin = None
                # pooled snapshot buffers go back for the next call
                if saved_box and isinstance(saved_box[0], np.ndarray) \
                        and saved_box[0].base is not None:
                    self._give_buf(saved_box[0])
        finally:
            rec.exit()

    return wrapper


_RECOVERED_METHODS = tuple(
    m for m in trace.COLLECTIVE_METHODS if m != "barrier")
# barrier is excluded: it rides the control plane only — its failure
# modes ARE the recovery machinery's failure modes (dead master, dead
# rank), both already terminal.
for _name in _RECOVERED_METHODS:
    _fn = ProcessCommSlave.__dict__.get(_name)
    if _fn is not None and callable(_fn):
        setattr(ProcessCommSlave, _name,
                _recovered(_fn, snapshot=_name not in _SNAPSHOT_FREE))

# per-collective tracing (utils.trace; zero overhead when disabled) —
# wraps OUTSIDE the recovery layer (see comment above)
trace.instrument(ProcessCommSlave)
