"""TPU cluster driver — the device-path backend.

Single-controller SPMD driver over a :class:`jax.sharding.Mesh`: the
reference's N socket slaves become N mesh devices, and each collective is
one jitted ``shard_map`` program whose body is an XLA ICI collective
(``ops.collectives``). Where the reference runs log2(n) Kryo-socket
rounds per collective (SURVEY.md section 3b), this backend emits a single
``psum`` / ``psum_scatter`` / ``all_gather`` and lets XLA schedule ICI DMA.

Driver-mode semantics: collective methods take a list of ``n`` per-rank
numpy arrays (the check-suite shape, SURVEY.md section 4), stage them onto
the mesh with the axis sharding, run the jitted collective, and write
results back IN PLACE into the per-rank arrays — matching the reference's
in-place buffer semantics. The per-shard functional layer
(``ops.collectives``) is the API for use inside user jit code.

Uneven ranges and sub-ranges ``[from, to)`` are handled by host-side
packing into equal static blocks padded with the operator identity, so
the jitted core sees only static shapes (XLA requirement).

Precision: device compute uses the operand dtype; 64-bit operands require
``jax.config.jax_enable_x64`` (the differential test rig enables it on
CPU). Without x64, 64-bit operands are rejected rather than silently
downcast.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ytk_mp4j_tpu.utils.compat import shard_map

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.comm import keycodec
from ytk_mp4j_tpu.comm import progress as progress_mod
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.operands import Operand, Operands
from ytk_mp4j_tpu.operators import Operator, Operators
from ytk_mp4j_tpu.ops import collectives as coll
from ytk_mp4j_tpu.ops import ring as ring_ops
from ytk_mp4j_tpu.ops import ring_kernel
from ytk_mp4j_tpu.ops import sparse as sparse_ops
from ytk_mp4j_tpu.parallel.mesh import make_mesh, DEFAULT_AXIS
from ytk_mp4j_tpu.utils import trace


def _x64_enabled() -> bool:
    return bool(jax.config.jax_enable_x64)


_pow2_bucket = keycodec.pow2_bucket


class PendingMap:
    """Deferred result of :meth:`TpuCommCluster.allreduce_map_async`.

    The device collective and the device->host copy are already in
    flight when this handle exists; :meth:`result` performs the single
    blocking fetch, decodes, and mutates the call's maps in place
    (identical post-state to the synchronous ``allreduce_map``).
    Chaining k dispatches before resolving any handle overlaps the k
    host encodes with device work and d2h transfers — the synchronous
    API instead pays one full dispatch+fetch round-trip per call, which
    on a remote-tunnel topology (~100 ms RTT) is the dominant cost
    (BASELINE.md round-5 chained A/B)."""

    def __init__(self, codec, codes, ov, maps):
        self._codec = codec
        self._codes = codes
        self._ov = ov
        self._maps = maps
        self._done = False

    def result(self):
        """Block, decode, and mutate the maps in place; idempotent."""
        if not self._done:
            if self._codec is not None:
                merged = TpuCommCluster._decode_union(
                    self._codec, self._codes, self._ov)
                for m in self._maps:
                    m.clear()
                    m.update(merged)
                self._ov = None   # release the device buffer
            self._done = True
        return self._maps


class TpuCommCluster:
    """SPMD collectives over ``n`` devices of a mesh.

    Parameters
    ----------
    n: number of ranks (devices); defaults to all devices. Non-powers-of-2
       are supported (mesh over a device subset).
    mesh: use an existing 1-D mesh instead.
    """

    def __init__(self, n: int | None = None, mesh: Mesh | None = None,
                 axis_name: str = DEFAULT_AXIS):
        if mesh is None:
            mesh = make_mesh(n, axis_name)
        self.mesh = mesh
        if len(mesh.axis_names) == 1:
            # flat cluster: ranks along one axis
            self.axis_name = mesh.axis_names[0]
            self.n = mesh.shape[self.axis_name]
        else:
            # hierarchical cluster (e.g. inter x intra, the device-side
            # analogue of process x thread nesting): ranks are row-major
            # over all axes; collectives run over the axis tuple and XLA
            # stages them across DCN/ICI
            self.axis_name = tuple(mesh.axis_names)
            self.n = 1
            for a in mesh.axis_names:
                self.n *= mesh.shape[a]
        self._row_sharding = NamedSharding(mesh, P(self.axis_name))
        self._jits: dict = {}
        # persistent key<->code vocabularies for the map collectives
        # (grow-only, one per key kind — see comm.keycodec)
        self._codecs: dict[str, object] = {}

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    @property
    def slave_num(self) -> int:
        return self.n

    def _check_operand(self, operand: Operand):
        if not operand.is_numeric:
            raise Mp4jError(
                f"{operand.name} operands are host-only; use the socket / "
                "in-process backend (SURVEY.md section 7 phase 4)")
        if operand.dtype.itemsize == 8 and not _x64_enabled():
            raise Mp4jError(
                f"{operand.name} needs jax_enable_x64 (64-bit dtypes are "
                "not enabled on this backend)")

    def _check_root(self, root: int):
        if not (0 <= root < self.n):
            raise Mp4jError(f"root {root} out of range [0, {self.n})")

    def _norm_arrays(self, arrs, operand: Operand, lo: int, hi: int | None):
        if len(arrs) != self.n:
            raise Mp4jError(f"expected {self.n} per-rank arrays, got {len(arrs)}")
        for a in arrs:
            if not isinstance(a, np.ndarray):
                raise Mp4jError(
                    "per-rank buffers must be numpy arrays (results are "
                    f"written back in place); got {type(a).__name__}")
        out = [operand.check_array(a) for a in arrs]
        shape0 = out[0].shape
        for a in out:
            if a.shape != shape0:
                raise Mp4jError("per-rank arrays must share a shape")
        if hi is None:
            hi = shape0[0] if out[0].ndim == 1 else out[0].size
        if lo != 0 or hi != (shape0[0] if out[0].ndim == 1 else out[0].size):
            if out[0].ndim != 1:
                raise Mp4jError("[from, to) ranges require 1-D arrays")
        if not (0 <= lo <= hi <= (shape0[0] if out[0].ndim == 1 else out[0].size)):
            raise Mp4jError(f"range [{lo}, {hi}) out of bounds")
        return out, lo, hi

    def _stack(self, blocks: list[np.ndarray]):
        """Stack per-rank equal blocks into a device array sharded by rank."""
        stacked = np.stack(blocks, axis=0)
        return jax.device_put(stacked, self._row_sharding)

    # -- algorithm selection (reference parity: ProcessCommSlave's
    # algo="auto"/"tree"/"rhd"/"ring"). "xla": one fused XLA collective
    # (default — the compiler schedules ICI DMA). "ring":
    # hand-scheduled ppermute ring (ops.ring). "rdma": the Pallas RDMA
    # ring kernel (ops.ring_kernel) — the explicit-transport path;
    # interpreted on non-TPU meshes, compiled (barrier + credit
    # backpressure) on TPU. "auto" — the host backends' size-aware
    # default — is accepted for dispatch consistency and resolves to
    # "xla": on device the compiler already schedules per topology, so
    # the fused collective IS the auto choice.
    _ALGOS = ("auto", "xla", "ring", "rdma")

    def _check_algo(self, algo: str) -> str:
        if algo not in self._ALGOS:
            raise Mp4jError(f"algo must be one of {self._ALGOS}, "
                            f"got {algo!r}")
        if algo == "auto":
            return "xla"
        if algo != "xla" and isinstance(self.axis_name, tuple):
            raise Mp4jError(
                f"algo={algo!r} rings over a single ICI axis; "
                "hierarchical meshes use the default 'xla' path")
        return algo

    def _interpret_kernels(self) -> bool:
        """Pallas kernels compile only on TPU meshes; interpret them on
        the virtual CPU meshes the tests and the driver dry-run use."""
        return self.mesh.devices.flat[0].platform != "tpu"

    def _jit(self, key, build):
        fn = self._jits.get(key)
        if fn is None:
            fn = build()
            self._jits[key] = fn
        return fn

    def _resolve_native(self, operator: Operator) -> bool | None:
        """The native pmax/pmin decision for THIS mesh's devices,
        resolved outside tracing (the trace-time probe can only see the
        default backend, which may differ from the mesh — e.g. a CPU
        dry-run mesh on a TPU-default machine). The value joins the jit
        cache key so a later ``set_native_reduce`` / env flip rebuilds
        instead of replaying a stale executable."""
        return coll.resolve_native_reduce(operator,
                                          list(self.mesh.devices.flat))

    # ------------------------------------------------------------------
    # dense collectives (reference: *Array methods, SURVEY.md section 2)
    # ------------------------------------------------------------------
    def allreduce_array(self, arrs, operand: Operand = Operands.FLOAT,
                        operator: Operator = Operators.SUM,
                        from_: int = 0, to: int | None = None,
                        algo: str = "xla"):
        """Element-wise reduce ``arr[from_:to]`` across ranks, in place.

        ``algo`` selects the schedule (see ``_ALGOS``): the fused XLA
        collective (default), the ppermute ring, or the Pallas RDMA
        ring kernel — all wire-identical in results."""
        self._check_operand(operand)
        algo = self._check_algo(algo)
        arrs, lo, hi = self._norm_arrays(arrs, operand, from_, to)
        if hi == lo:
            return arrs
        flat = [a[lo:hi] if a.ndim == 1 else a.reshape(-1) for a in arrs]
        L = flat[0].size
        # native only affects the xla build; resolving (and keying) it
        # on ring/rdma would probe needlessly and recompile identical
        # programs on a set_native_reduce flip
        native = self._resolve_native(operator) if algo == "xla" else None

        def build():
            if algo == "xla":
                @partial(shard_map, mesh=self.mesh,
                         in_specs=P(self.axis_name),
                         out_specs=P(self.axis_name))
                def f(x):  # x: [1, L]
                    return coll.allreduce(x, operator, self.axis_name,
                                          native)
                return jax.jit(f)

            axis = self.axis_name
            n = self.n
            interpret = self._interpret_kernels()

            # the pallas interpreter / the ring's data-dependent chunk
            # walk defeat static replication inference; differential
            # tests cover algo equivalence
            @partial(shard_map, mesh=self.mesh, check_vma=False,
                     in_specs=P(axis), out_specs=P(axis))
            def f(x):  # x: [1, L]
                v = x[0]
                if algo == "rdma":
                    return ring_kernel.ring_allreduce_kernel(
                        v, operator, axis, interpret=interpret)[None]
                padL = meta.padded_block(L, n) * n
                if padL != L:
                    ident = jnp.asarray(operator.identity(v.dtype),
                                        dtype=v.dtype)
                    v = jnp.concatenate(
                        [v, jnp.full((padL - L,), ident, v.dtype)])
                return ring_ops.ring_allreduce(v, operator, axis)[:L][None]
            return jax.jit(f)

        fn = self._jit(("allreduce", L, operand.dtype, operator, algo,
                        native), build)
        res = np.asarray(fn(self._stack(flat)))
        for r, a in enumerate(arrs):
            if a.ndim == 1:
                a[lo:hi] = res[r]
            else:
                np.copyto(a, res[r].reshape(a.shape))
        return arrs

    def reduce_array(self, arrs, operand: Operand = Operands.FLOAT,
                     operator: Operator = Operators.SUM, root: int = 0,
                     from_: int = 0, to: int | None = None):
        """Reduce into ``root``'s array; other ranks' buffers unchanged."""
        self._check_operand(operand)
        self._check_root(root)
        arrs, lo, hi = self._norm_arrays(arrs, operand, from_, to)
        if hi == lo:
            return arrs
        flat = [a[lo:hi] if a.ndim == 1 else a.reshape(-1) for a in arrs]
        L = flat[0].size
        native = self._resolve_native(operator)

        def build():
            @partial(shard_map, mesh=self.mesh,
                     in_specs=P(self.axis_name), out_specs=P(self.axis_name))
            def f(x):
                return coll.reduce(x, operator, root, self.axis_name,
                                   native)
            return jax.jit(f)

        fn = self._jit(("reduce", L, operand.dtype, operator, native),
                       build)
        res = np.asarray(fn(self._stack(flat)))
        a = arrs[root]
        if a.ndim == 1:
            a[lo:hi] = res[root]
        else:
            np.copyto(a, res[root].reshape(a.shape))
        return arrs

    def broadcast_array(self, arrs, operand: Operand = Operands.FLOAT,
                        root: int = 0, from_: int = 0, to: int | None = None):
        """Copy ``root``'s ``arr[from_:to]`` into every rank's array."""
        self._check_operand(operand)
        self._check_root(root)
        arrs, lo, hi = self._norm_arrays(arrs, operand, from_, to)
        if hi == lo:
            return arrs
        flat = [a[lo:hi] if a.ndim == 1 else a.reshape(-1) for a in arrs]
        L = flat[0].size

        def build():
            @partial(shard_map, mesh=self.mesh,
                     in_specs=P(self.axis_name), out_specs=P(self.axis_name))
            def f(x):
                return coll.broadcast(x, root, self.axis_name)
            return jax.jit(f)

        fn = self._jit(("broadcast", L, operand.dtype, root), build)
        res = np.asarray(fn(self._stack(flat)))
        for r, a in enumerate(arrs):
            if a.ndim == 1:
                a[lo:hi] = res[r]
            else:
                np.copyto(a, res[r].reshape(a.shape))
        return arrs

    # -- segment-based family. ``ranges`` gives each rank's owned segment
    # of a common full-length array (reference: per-rank from/to counts in
    # ArrayMetaData, SURVEY.md section 2). Default: block partition of the
    # whole array via meta.partition_range.
    def _norm_ranges(self, arrs, ranges):
        L = arrs[0].shape[0]
        if ranges is None:
            ranges = meta.partition_range(0, L, self.n)
        if len(ranges) != self.n:
            raise Mp4jError(f"need {self.n} ranges, got {len(ranges)}")
        prev = None
        for (s, e) in ranges:
            if not (0 <= s <= e <= L):
                raise Mp4jError(f"range ({s}, {e}) out of bounds for {L}")
            if prev is not None and s != prev:
                raise Mp4jError("ranges must be contiguous in rank order")
            prev = e
        return ranges

    @staticmethod
    def _max_block(ranges) -> int:
        return max(1, max(e - s for s, e in ranges))

    def _run_segment_gather(self, arrs, operand: Operand, ranges,
                            algo: str = "xla"):
        """Shared core of (all)gather: pad each rank's segment to the max
        block, all_gather on device, return the [n, B] result."""
        if arrs[0].ndim != 1:
            raise Mp4jError("segment collectives require 1-D arrays")
        algo = self._check_algo(algo)
        ranges = self._norm_ranges(arrs, ranges)
        B = self._max_block(ranges)
        if algo == "rdma":
            B = ring_kernel.round_up_chunk(B, operand.dtype,
                                           self._interpret_kernels())
        blocks = []
        for r, (s, e) in enumerate(ranges):
            b = np.zeros(B, dtype=operand.dtype)
            b[: e - s] = arrs[r][s:e]
            blocks.append(b)

        def build():
            if algo == "xla":
                @partial(shard_map, mesh=self.mesh, check_vma=False,
                         in_specs=P(self.axis_name),
                         out_specs=P(None, None))
                def f(x):  # x: [1, B] -> [n, B] replicated
                    return coll.allgather(x, self.axis_name, tiled=True)
                return jax.jit(f)

            axis = self.axis_name
            n = self.n
            interpret = self._interpret_kernels()

            @partial(shard_map, mesh=self.mesh, check_vma=False,
                     in_specs=P(axis), out_specs=P(None, None))
            def f(x):  # x: [1, B] -> [n, B] replicated
                if algo == "rdma":
                    y = ring_kernel.ring_allgather_kernel(
                        x[0], axis, interpret=interpret)
                else:
                    y = ring_ops.ring_allgather(x[0], axis)
                return y.reshape(n, B)
            return jax.jit(f)

        fn = self._jit(("allgather", B, operand.dtype, algo), build)
        return np.asarray(fn(self._stack(blocks))), ranges

    def allgather_array(self, arrs, operand: Operand = Operands.FLOAT,
                        ranges=None, algo: str = "xla"):
        """Each rank owns ``arr[ranges[rank]]``; afterwards every rank's
        array holds all segments. ``algo`` selects the schedule (see
        ``_ALGOS``)."""
        self._check_operand(operand)
        arrs, _, _ = self._norm_arrays(arrs, operand, 0, None)
        res, ranges = self._run_segment_gather(arrs, operand, ranges, algo)
        for a in arrs:
            for r, (s, e) in enumerate(ranges):
                a[s:e] = res[r, : e - s]
        return arrs

    def gather_array(self, arrs, operand: Operand = Operands.FLOAT,
                     root: int = 0, ranges=None):
        """Root's array receives every rank's segment; others unchanged."""
        self._check_operand(operand)
        self._check_root(root)
        arrs, _, _ = self._norm_arrays(arrs, operand, 0, None)
        res, ranges = self._run_segment_gather(arrs, operand, ranges)
        a = arrs[root]
        for r, (s, e) in enumerate(ranges):
            a[s:e] = res[r, : e - s]
        return arrs

    def scatter_array(self, arrs, operand: Operand = Operands.FLOAT,
                      root: int = 0, ranges=None):
        """Rank r receives segment ``ranges[r]`` of ``root``'s array."""
        self._check_operand(operand)
        self._check_root(root)
        arrs, _, _ = self._norm_arrays(arrs, operand, 0, None)
        if arrs[0].ndim != 1:
            raise Mp4jError("segment collectives require 1-D arrays")
        ranges = self._norm_ranges(arrs, ranges)
        # In the single-controller runtime every rank's buffer lives in
        # host memory, so scatter is a pure host copy of root's segments —
        # a device round-trip would move the same bytes twice for zero
        # effect. (The SPMD functional layer has a true in-jit scatter for
        # multi-host use inside jitted programs.)
        src = arrs[root]
        for r, (s, e) in enumerate(ranges):
            if r != root:
                arrs[r][s:e] = src[s:e]
        return arrs

    def reduce_scatter_array(self, arrs, operand: Operand = Operands.FLOAT,
                             operator: Operator = Operators.SUM, ranges=None,
                             algo: str = "xla"):
        """Every rank contributes its full array; rank r ends with segment
        ``ranges[r]`` of the element-wise reduction (other positions
        unchanged). ``algo`` selects the schedule (see ``_ALGOS``)."""
        self._check_operand(operand)
        algo = self._check_algo(algo)
        arrs, _, _ = self._norm_arrays(arrs, operand, 0, None)
        if arrs[0].ndim != 1:
            raise Mp4jError("segment collectives require 1-D arrays")
        ranges = self._norm_ranges(arrs, ranges)
        lo, hi = ranges[0][0], ranges[-1][1]
        B = meta.padded_block(hi - lo, self.n)
        if algo == "rdma":
            B = ring_kernel.round_up_chunk(B, operand.dtype,
                                           self._interpret_kernels())
        pad = self.n * B
        ident = operator.identity(operand.dtype)
        blocks = []
        for r in range(self.n):
            b = np.full(pad, ident, dtype=operand.dtype)
            b[: hi - lo] = arrs[r][lo:hi]
            blocks.append(b)
        native = self._resolve_native(operator) if algo == "xla" else None

        def build():
            if algo == "xla":
                @partial(shard_map, mesh=self.mesh,
                         in_specs=P(self.axis_name),
                         out_specs=P(self.axis_name))
                def f(x):  # x: [1, n*B]
                    y = coll.reduce_scatter(x[0], operator, self.axis_name,
                                            native)
                    return y[None]  # [1, B]
                return jax.jit(f)

            axis = self.axis_name
            n = self.n
            interpret = self._interpret_kernels()

            @partial(shard_map, mesh=self.mesh, check_vma=False,
                     in_specs=P(axis), out_specs=P(axis))
            def f(x):  # x: [1, n*B]
                if algo == "rdma":
                    y = ring_kernel.ring_reduce_scatter_kernel(
                        x[0], operator, axis, interpret=interpret)
                else:
                    # the ppermute ring leaves member r with chunk
                    # (r+1)%n; one further hop right restores the
                    # block-r-to-rank-r layout of the XLA path
                    y = ring_ops.ring_reduce_scatter(x[0], operator, axis)
                    y = lax.ppermute(y, axis,
                                     [(i, (i + 1) % n) for i in range(n)])
                return y[None]  # [1, B]
            return jax.jit(f)

        fn = self._jit(("reduce_scatter", pad, operand.dtype, operator,
                        algo, native), build)
        res = np.asarray(fn(self._stack(blocks)))  # [n, B]
        # Padded-block layout: device block r covers [lo + r*B, lo + (r+1)*B).
        # Write each rank's owned (uneven) range from the covering blocks.
        full = res.reshape(-1)[: hi - lo]
        for r, (s, e) in enumerate(ranges):
            arrs[r][s:e] = full[s - lo: e - lo]
        return arrs


    # ------------------------------------------------------------------
    # sparse map collectives (reference: *Map methods, SURVEY.md 3c)
    #
    # Keys live on the host (strings are not TPU-representable — the
    # reference likewise keeps them in Kryo land); values ride the device
    # as packed (code, value) buffers through ops.sparse. In-place
    # semantics: each rank's dict is mutated like the reference's maps.
    # ------------------------------------------------------------------
    def _norm_maps(self, maps, operand: Operand):
        if len(maps) != self.n:
            raise Mp4jError(f"expected {self.n} per-rank maps, got {len(maps)}")
        for m in maps:
            if not isinstance(m, dict):
                raise Mp4jError(
                    f"per-rank operands must be dicts, got {type(m).__name__}")
        self._check_operand(operand)
        return maps

    def _encode_maps(self, maps, operand: Operand, operator: Operator):
        """Pack each rank's entries into SENTINEL-padded (code, value)
        buffers of equal static length via the cluster's PERSISTENT key
        codec (``comm.keycodec``) — no per-call union sort, no per-entry
        Python loop. Returns ``(codec, idx, val, vshape, cap)`` with
        ``cap`` an upper bound on the union's unique-code count, or
        ``None`` when every map is empty.

        Round-2 history: this used to re-derive
        ``sorted(set().union(*maps))`` and pack entry-by-entry on every
        call, which made the device path LOSE to the socket dict loop at
        configs[2] (BASELINE.md round-3 A/B); a sparse-gradient stream's
        vocabulary is near-persistent, so key->code translation is now
        amortized across calls."""
        total = sum(len(m) for m in maps)
        if total == 0:
            return None
        for m in maps:
            if m:
                k0 = next(iter(m))
                vshape = np.shape(m[k0])
                break
        kind = keycodec.kind_of(k0)
        codec = self._codecs.get(kind)
        if codec is None:
            codec = self._codecs[kind] = keycodec.codec_for_kind(kind)
        # round the per-rank slot count up to a power of 2: real sparse
        # gradient streams drift in key count every step, and an exact
        # Lmax would join the jit key and recompile per step; padding is
        # SENTINEL/identity so the bucket rounding is semantically free
        # and bounds the compile count at O(log max-keys) programs
        Lmax = _pow2_bucket(max(len(m) for m in maps))
        ident = operator.identity(operand.dtype)
        idx = np.full((self.n, Lmax), sparse_ops.SENTINEL, dtype=np.int32)
        val = np.full((self.n, Lmax) + vshape, ident, dtype=operand.dtype)
        for r, m in enumerate(maps):
            c = len(m)
            if c == 0:
                continue
            idx[r, :c] = codec.encode(m.keys(), c)
            val[r, :c] = keycodec.pack_values(m.values(), c, vshape,
                                              operand.dtype)
        # every key of this call is in the vocabulary, so the union's
        # unique-code count is bounded by both the vocabulary size and
        # the total entry count
        return codec, idx, val, vshape, min(codec.size, total)

    @staticmethod
    def _decode_union(codec, codes, ov):
        """Host-known union codes + the device's value buffer -> one
        merged dict (bulk zip; map values are shared across ranks, as
        the round-2 decode's single ``merged`` dict already did).
        ``ov`` is a DEVICE array; the asarray here is the call's single
        round-trip."""
        vals = np.asarray(ov)[: codes.size]
        return dict(zip(codec.decode(codes), list(vals)))

    def _device_sparse_allreduce(self, idx, val, capacity, operator):
        # same bucket rounding as _encode_maps, for the union capacity:
        # the output is SENTINEL-padded past the true union, so callers
        # (which skip SENTINEL slots) see no semantic difference
        capacity = _pow2_bucket(capacity)
        Lmax = idx.shape[1]
        vshape = val.shape[2:]

        def build():
            @partial(shard_map, mesh=self.mesh, check_vma=False,
                     in_specs=(P(self.axis_name), P(self.axis_name)),
                     out_specs=(P(None), P(None)))
            def f(i, v):  # [1, L] / [1, L, *vshape] per shard
                return sparse_ops.sparse_allreduce(
                    i[0], v[0], capacity, operator, self.axis_name)
            return jax.jit(f)

        key = ("sparse_allreduce", Lmax, capacity, vshape,
               val.dtype.str, operator)
        fn = self._jit(key, build)
        # DEVICE arrays out: callers fetch only what they need — on the
        # tunnel every np.asarray is a full round-trip, and the map
        # family never fetches oi at all (see _union_codes)
        return fn(jax.device_put(idx, self._row_sharding),
                  jax.device_put(val, self._row_sharding))

    @staticmethod
    def _union_codes(idx: np.ndarray) -> np.ndarray:
        """The union's code list, host-side: ``segment_reduce_sorted``
        packs unique codes ascending with SENTINEL padding at the end —
        exactly ``np.unique`` of the staged buffers minus the sentinel.
        Computing it here makes the device's ``oi`` output redundant, so
        the map collectives pay ONE device fetch per call (ov), not two
        sequential round-trips (measured ~115 ms each on the tunnel)."""
        codes = np.unique(idx)
        if codes.size and codes[-1] == sparse_ops.SENTINEL:
            codes = codes[:-1]
        return codes

    def allreduce_map(self, maps, operand: Operand = Operands.DOUBLE,
                      operator: Operator = Operators.SUM):
        """Key-union reduce: every rank's dict becomes the union of all
        keys with shared keys reduced by ``operator``."""
        maps = self._norm_maps(maps, operand)
        enc = self._encode_maps(maps, operand, operator)
        if enc is None:
            return maps
        codec, idx, val, _vshape, cap = enc
        _oi, ov = self._device_sparse_allreduce(idx, val, cap, operator)
        merged = self._decode_union(codec, self._union_codes(idx), ov)
        for m in maps:
            m.clear()
            m.update(merged)
        return maps

    def allreduce_map_async(self, maps,
                            operand: Operand = Operands.DOUBLE,
                            operator: Operator = Operators.SUM
                            ) -> PendingMap:
        """Pipelined :meth:`allreduce_map`: dispatch the device
        collective and start the device->host value copy, but defer the
        blocking fetch/decode/mutation to the returned handle's
        ``result()``. Per-call work overlaps across chained dispatches,
        so a k-deep chain pays ~one round-trip, not k (the steady-state
        rate a real pod sees; measured in bench.py /
        BASELINE.md round 5). The input dicts must not be mutated
        between dispatch and ``result()``."""
        maps = self._norm_maps(maps, operand)
        enc = self._encode_maps(maps, operand, operator)
        if enc is None:
            return PendingMap(None, None, None, maps)
        codec, idx, val, _vshape, cap = enc
        _oi, ov = self._device_sparse_allreduce(idx, val, cap, operator)
        try:
            ov.copy_to_host_async()
        except (AttributeError, RuntimeError):  # pragma: no cover
            pass    # prefetch is best-effort; result() fetches anyway
        return PendingMap(codec, self._union_codes(idx), ov, maps)

    def reduce_map(self, maps, operand: Operand = Operands.DOUBLE,
                   operator: Operator = Operators.SUM, root: int = 0):
        """Key-union reduce into ``root``'s dict; others unchanged."""
        self._check_root(root)
        maps = self._norm_maps(maps, operand)
        enc = self._encode_maps(maps, operand, operator)
        if enc is None:
            return maps
        codec, idx, val, _vshape, cap = enc
        _oi, ov = self._device_sparse_allreduce(idx, val, cap, operator)
        merged = self._decode_union(codec, self._union_codes(idx), ov)
        maps[root].clear()
        maps[root].update(merged)
        return maps

    def reduce_scatter_map(self, maps, operand: Operand = Operands.DOUBLE,
                           operator: Operator = Operators.SUM):
        """Key-union reduce, then each rank keeps the keys hashing to it
        (meta.key_partition — identical placement on both backends; the
        codec caches the blake2b placement per key, which dominates the
        per-entry cost otherwise)."""
        maps = self._norm_maps(maps, operand)
        enc = self._encode_maps(maps, operand, operator)
        if enc is None:
            return maps
        codec, idx, val, _vshape, cap = enc
        _oi, ov = self._device_sparse_allreduce(idx, val, cap, operator)
        codes = self._union_codes(idx)
        vals = np.asarray(ov)[: codes.size]   # the single device fetch
        parts = codec.partition(codes, self.n)
        for r, m in enumerate(maps):
            mine = parts == r
            m.clear()
            m.update(zip(codec.decode(codes[mine]), list(vals[mine])))
        return maps

    def allgather_map(self, maps, operand: Operand = Operands.DOUBLE):
        """Disjoint union: every rank's dict becomes the union of all
        ranks' entries. Duplicate keys raise (ambiguous without an
        operator). Composition of gather + broadcast, like the socket
        backend."""
        self.gather_map(maps, operand, root=0)
        return self.broadcast_map(maps, operand, root=0)

    def gather_map(self, maps, operand: Operand = Operands.DOUBLE,
                   root: int = 0):
        """Disjoint union into ``root``'s dict; others unchanged. A
        duplicate key raises naming the key and both owner ranks
        (contract parity with the socket backend)."""
        self._check_root(root)
        maps = self._norm_maps(maps, operand)
        total = sum(len(m) for m in maps)
        union: dict = {}
        for m in maps:
            union.update(m)
        if len(union) != total:
            seen: dict = {}
            for r, m in enumerate(maps):
                for k in m:
                    if k in seen:
                        raise Mp4jError(
                            f"gather_map: duplicate key {k!r} owned by "
                            f"ranks {seen[k]} and {r}; use reduce_map "
                            f"to combine")
                    seen[k] = r
        maps[root].clear()
        maps[root].update(union)
        return maps

    def broadcast_map(self, maps, operand: Operand = Operands.DOUBLE,
                      root: int = 0):
        """Every rank's dict becomes a copy of ``root``'s."""
        self._check_root(root)
        maps = self._norm_maps(maps, operand)
        src = dict(maps[root])
        for r, m in enumerate(maps):
            if r != root:
                m.clear()
                m.update(src)
        return maps

    def scatter_map(self, maps, operand: Operand = Operands.DOUBLE,
                    root: int = 0, partitioner=None):
        """Rank r receives the subset of ``root``'s entries whose keys
        hash to r (meta.key_partition).

        ``partitioner(key) -> rank`` overrides the placement rule —
        contract parity with ``ProcessCommSlave.scatter_map`` (the
        thread backend's global-thread-rank placement relies on it)."""
        self._check_root(root)
        maps = self._norm_maps(maps, operand)
        if partitioner is None:
            partitioner = lambda k: meta.key_partition(k, self.n)  # noqa: E731
        src = dict(maps[root])
        shares: list[dict] = [{} for _ in range(self.n)]
        for k, v in src.items():
            shares[meta.check_partition_rank(partitioner(k), self.n,
                                             k)][k] = v
        for r, m in enumerate(maps):
            m.clear()
            m.update(shares[r])
        return maps

    def reset_map_vocabularies(self) -> None:
        """Drop the persistent key<->code vocabularies (and their cached
        partitions). The codecs are grow-only; on a long-lived cluster
        whose key space CHURNS (rather than stabilizes) they — and the
        union capacity buckets keyed on them — grow without bound.
        After a reset the next map collective rebuilds from the live
        keys. Compiled programs are kept (they are keyed on shapes, not
        vocabularies)."""
        self._codecs.clear()

    # ------------------------------------------------------------------
    # nonblocking collectives (ISSUE 11): the device path is a single-
    # controller SPMD driver whose dispatches are ALREADY asynchronous
    # under JAX's lazy execution — the dense i* twins execute eagerly
    # (the launch returns before the device finishes; materialization
    # blocks, exactly as for the blocking API) and return resolved
    # futures, while iallreduce_map rides the existing chained-
    # dispatch machinery (PendingMap) behind a lazily-resolving future
    # so k chained maps pay ~one device round trip, not k.
    # ------------------------------------------------------------------
    def iallreduce(self, arrs, operand: Operand = Operands.FLOAT,
                   operator: Operator = Operators.SUM,
                   from_: int = 0, to: int | None = None,
                   algo: str = "auto"):
        """Eager nonblocking :meth:`allreduce_array` (resolved
        future)."""
        return progress_mod.eager_future(
            self, "allreduce_array", arrs, operand, operator,
            from_=from_, to=to, algo=algo)

    def ireduce_scatter(self, arrs, operand: Operand = Operands.FLOAT,
                        operator: Operator = Operators.SUM,
                        ranges=None):
        """Eager nonblocking :meth:`reduce_scatter_array`."""
        return progress_mod.eager_future(
            self, "reduce_scatter_array", arrs, operand, operator,
            ranges=ranges)

    def iallgather(self, arrs, operand: Operand = Operands.FLOAT,
                   ranges=None):
        """Eager nonblocking :meth:`allgather_array`."""
        return progress_mod.eager_future(
            self, "allgather_array", arrs, operand, ranges=ranges)

    def igather(self, arrs, operand: Operand = Operands.FLOAT,
                root: int = 0, ranges=None):
        """Eager nonblocking :meth:`gather_array`."""
        return progress_mod.eager_future(
            self, "gather_array", arrs, operand, root=root,
            ranges=ranges)

    def iallreduce_map(self, maps, operand: Operand = Operands.DOUBLE,
                       operator: Operator = Operators.SUM):
        """Nonblocking :meth:`allreduce_map` riding
        :meth:`allreduce_map_async`: the device collective and the
        d2h copy are in flight when this returns; ``wait()`` performs
        the single blocking fetch + decode (identical post-state to
        the blocking twin)."""
        pending = self.allreduce_map_async(maps, operand, operator)
        return progress_mod.DeferredFuture("allreduce_map",
                                           pending.result)

    def wait_all(self, timeout: float | None = None) -> None:
        """Collective-boundary drain: the dense device path is eager
        and ``iallreduce_map`` futures resolve at ``wait()`` — no
        scheduler state to drain; kept for portable code."""

    # ------------------------------------------------------------------
    def barrier(self):
        """Synchronize: run a trivial device collective to completion."""
        def build():
            @partial(shard_map, mesh=self.mesh, in_specs=P(self.axis_name),
                     out_specs=P(self.axis_name))
            def f(x):
                return x + coll.barrier(self.axis_name)
            return jax.jit(f)
        fn = self._jit(("barrier",), build)
        tok = jax.device_put(np.zeros((self.n, 1), np.int32),
                             self._row_sharding)
        np.asarray(fn(tok))


# per-collective tracing (utils.trace; zero overhead when disabled)
trace.instrument(TpuCommCluster)
