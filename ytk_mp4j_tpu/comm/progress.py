"""Nonblocking collectives: futures + the helper-thread communication
scheduler (ISSUE 11).

The socket plane was synchronous per collective: every ``allreduce``
blocked the caller while the wire drained. This module generalizes the
two seeds already in-tree — PR 5's submit-time channel binding
(``_submit_send``) and PR 7's single-threaded shm ``duplex_exchange``
event loop — into ONE progression thread per slave that drives many
outstanding collectives through a single poll loop over the Channel
SPI, with per-collective state machines for the existing chunked
rhd/ring schedules, so chunk k+1's wire overlaps chunk k's reduce
across *different* outstanding collectives too.

Architecture
------------

- :class:`CollectiveFuture` — the handle ``ProcessCommSlave.iallreduce``
  / ``igather`` / ``iallgather`` / ``ireduce_scatter`` /
  ``iallreduce_map`` return. It carries its submit **epoch** and its
  collective **ordinal**; ``wait()`` blocks for the result (the same
  in-place mutated payload the blocking twin returns) and re-raises the
  collective's failure.

- :class:`ProgressScheduler` — one daemon progression thread per slave,
  started lazily on the first ``i*`` submission (a job that never goes
  async pays nothing). Submissions classify into three execution kinds,
  always consumed in submit order (submit order IS the job-wide
  collective order, exactly as for blocking calls):

  * **engine** — numeric raw-plane dense collectives (rhd/ring
    schedules, gather) run as *state machines*: each collective's
    schedule is enumerated up front into exchange ops; every op's send/
    recv legs enqueue tickets into per-``(peer, direction)`` FIFO
    queues at admission, and the poll loop moves bytes on whichever
    runnable leg's socket is ready (nonblocking TCP via ``select``;
    legs whose channel rides the shm rings pump the SPSC ring
    piece/sync-byte schedule chunk-granularly through
    ``transport.shm.SendPump``/``RecvPump`` — wire-identical to the
    blocking chunked exchange, never blocking the loop). Because every
    rank enqueues the SAME per-channel leg sequence (pure schedules ×
    identical submit order — the R1/R8 discipline), bytes always pair
    with the peer's matching leg whatever the local interleaving; and
    because each collective's ops arm strictly in order with the
    identical per-chunk merge boundaries, results are bit-exact with
    the blocking path.

  * **fused map** — under ``MP4J_COALESCE_USECS > 0``, consecutive
    ``iallreduce_map`` submissions fuse into one
    ``allreduce_map_multi`` call: ONE vocabulary-sync negotiation and
    one columnar frame train carry many tiny maps, and the negotiated
    batch size (the min of every rank's offered count, carried in the
    sync header) keeps ranks in lockstep however raggedly their
    schedulers coalesced. De-fused on completion; leftovers re-queue.

  * **inline** — everything else (framed/compressed/object operands,
    tree/twolevel schedules, the non-coalesced map plane) executes the
    ordinary blocking method on the progression thread: still
    asynchronous to the caller, FIFO-ordered, riding the existing
    recovery/audit/stats machinery unchanged.

Epoch-fence contract (the ISSUE 5/10 composition): an engine batch is
ONE recovery unit — every member's payload is snapshotted at admission
(through the same ``_preserve_payload`` pool machinery the blocking
wrapper uses), the batch publishes ``(base ordinal, in-flight)`` so the
master's per-collective release gate and the elastic ``joiner_seq``
rule see one coherent position, and an abort round restores EVERY
member (audit-digest-checked) and re-drives the whole batch at the new
epoch. Futures resolve only once their collective can no longer be
retried (batch completion), so a caller never observes a transiently
restored buffer. ``wait_all()`` is the collective-boundary drain;
blocking collectives, ``barrier()`` and ``close()`` drain outstanding
futures first so mixed async/blocking programs keep one job-wide
collective order (mp4j-lint R16 flags the un-awaited-future hazard
statically).
"""

from __future__ import annotations

import collections
import select
import threading
import time

import numpy as np

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.exceptions import (
    Mp4jError, Mp4jFatalError, Mp4jTransportError)
from ytk_mp4j_tpu.transport import shm as shm_mod
from ytk_mp4j_tpu.transport.channel import _raw_view
from ytk_mp4j_tpu.utils import native, tuning

# engine byte-moving granularity per socket syscall; the merge/pipeline
# chunking stays MP4J_CHUNK_BYTES (identical boundaries to the blocking
# engine — bit-exactness depends on it)
_IO_SLICE = 1 << 20


class CollectiveFuture:
    """Deferred result of a nonblocking collective (``i*`` methods).

    ``wait()`` blocks until the collective completes and returns the
    same (in-place mutated) payload the blocking twin returns — or
    re-raises the collective's failure. The payload buffer must not be
    read or mutated between submit and ``wait()``: the scheduler owns
    it, and a recovery retry may transiently restore it.

    Attributes: ``op`` (the blocking twin's name), ``epoch`` (the
    job-wide recovery epoch at submit — the fence the abort protocol
    validates retries against), ``seq`` (the collective ordinal,
    assigned when the scheduler admits the collective).
    """

    __slots__ = ("op", "epoch", "seq", "_done", "_result", "_exc",
                 "_observed")

    def __init__(self, op: str, epoch: int = 0):
        self.op = op
        self.epoch = epoch
        self.seq = 0
        self._done = threading.Event()
        self._result = None
        self._exc: BaseException | None = None
        self._observed = False    # wait()/exception() delivered it

    # -- caller side ----------------------------------------------------
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None):
        """Block until completion; returns the collective's result or
        re-raises its failure. A ``timeout`` expiry raises
        ``Mp4jError`` without consuming the future (wait again)."""
        if not self._done.wait(timeout):
            raise Mp4jError(
                f"future '{self.op}' not complete after {timeout}s")
        self._observed = True
        if self._exc is not None:
            raise self._exc
        return self._result

    # the concurrent.futures-familiar spelling
    def result(self, timeout: float | None = None):
        return self.wait(timeout)

    def exception(self, timeout: float | None = None):
        """The collective's failure (None on success); blocks like
        :meth:`wait`."""
        if not self._done.wait(timeout):
            raise Mp4jError(
                f"future '{self.op}' not complete after {timeout}s")
        self._observed = True
        return self._exc

    # -- scheduler side -------------------------------------------------
    def _resolve(self, value) -> None:
        self._result = value
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()


def completed_future(op: str, value) -> CollectiveFuture:
    """An already-resolved future — the eager backends' (thread /
    distributed, and the device dense paths) ``i*`` return value: the
    collective ran synchronously, the future API stays uniform."""
    fut = CollectiveFuture(op)
    fut._resolve(value)
    return fut


def eager_future(obj, name: str, *args, **kwargs) -> CollectiveFuture:
    """Run ``obj.<name>(*args)`` NOW and wrap the outcome in a
    resolved future — the backends whose collectives are inherently
    synchronous (thread barrier-aligned groups, the single-controller
    device paths, ``MP4J_ASYNC=0``) keep the uniform ``i*().wait()``
    contract, failures delivered at ``wait()`` like the scheduled
    path."""
    fut = CollectiveFuture(name)
    try:
        fut._resolve(getattr(obj, name)(*args, **kwargs))
    except Exception as e:
        fut._fail(e)
    return fut


class DeferredFuture(CollectiveFuture):
    """A future whose ``wait()`` lazily runs ``resolve()`` once, on the
    first waiter's thread — wraps the TPU path's ``PendingMap`` (the
    device collective is already in flight; only the blocking fetch +
    decode is deferred)."""

    __slots__ = ("_lock", "_fn")

    def __init__(self, op: str, fn):
        super().__init__(op)
        self._lock = threading.Lock()
        self._fn = fn

    def _force(self) -> None:
        with self._lock:
            if not self._done.is_set():
                try:
                    self._resolve(self._fn())
                except BaseException as e:
                    self._fail(e)

    def wait(self, timeout: float | None = None):
        self._force()
        return super().wait(timeout)

    def result(self, timeout: float | None = None):
        return self.wait(timeout)

    def exception(self, timeout: float | None = None):
        self._force()
        return super().exception(timeout)


# ----------------------------------------------------------------------
# submission records
# ----------------------------------------------------------------------
class _Item:
    __slots__ = ("future", "name", "args", "kwargs", "kind", "ordinal",
                 "snapshot", "arec", "ops", "cursor", "seq", "t0",
                 "payload", "wire", "resolved")

    def __init__(self, future, name, args, kwargs, kind):
        self.future = future
        self.name = name          # blocking twin's method name
        self.args = args          # (payload, operand[, operator])
        self.kwargs = kwargs
        self.kind = kind          # "engine" | "map" | "inline"
        self.ordinal = 0          # recovery ordinal (at admission)
        self.snapshot = None      # payload snapshot for retries
        self.arec = None          # audit record
        self.ops: list[_Op] = []
        self.cursor = 0           # index of the op currently in flight
        self.seq = 0              # CommStats sequence number
        self.t0 = 0.0
        self.payload = None
        self.resolved = False     # future resolved (engine: at its
        # collective's completion, so a rolling submit window
        # pipelines; a recovery retry re-runs even resolved members
        # bit-exactly — see the CollectiveFuture recovery caveat)
        # per-COLLECTIVE wire folds (verify mode): the shared audit
        # accumulators assume one collective at a time, but several of
        # ours interleave on the wire — each item folds its own legs
        # (sequential within a collective, so plain crc folds compose)
        # and installs them at commit, keeping the cross-rank pairwise
        # wire comparison exact whatever the local interleaving
        self.wire: dict = {}

    def fold(self, peer: int, direction: str, buf,
             transport: str) -> None:
        from ytk_mp4j_tpu.obs import audit as audit_mod
        key = (int(peer), direction)
        ent = self.wire.get(key)
        if ent is None:
            ent = self.wire[key] = [0, 0, transport]
        ent[0] = audit_mod.fold_wire(ent[0], buf)
        ent[1] += len(buf)


class _Op:
    """One exchange step of one engine collective: up to one send leg
    and one recv leg (full duplex), an optional per-chunk merge, and an
    ``on_done`` hook (ring carry rotation, final deposits).

    ``acc`` set => the receive rides pooled scratch (``rbuf``) and each
    completed chunk merges: ``acc = op(acc, rbuf)`` (the rhd shape), or
    with ``ring=True`` the inverse ``rbuf = op(rbuf, acc)`` (the ring
    reduce-scatter shape, where the scratch becomes the next carry) —
    both exactly the blocking engine's operand order.
    """

    __slots__ = ("item", "idx", "sp", "sarr", "rp", "rdst", "acc",
                 "operator", "ring", "on_done", "armed", "wait_since",
                 "legs", "pending_legs", "rbuf")

    def __init__(self, item, idx, sp=None, sarr=None, rp=None,
                 rdst=None, acc=None, operator=None, ring=False,
                 on_done=None):
        self.item = item
        self.idx = idx
        self.sp = sp
        self.sarr = sarr          # ndarray | callable -> ndarray | None
        self.rp = rp
        self.rdst = rdst          # in-place recv destination (ndarray)
        self.acc = acc            # merge counterpart (see class doc)
        self.operator = operator
        self.ring = ring
        self.on_done = on_done
        self.armed = False
        self.wait_since = None    # first deferred-arm tick (see _arm)
        self.legs: list[_Leg] = []
        if sp is not None:
            self.legs.append(_Leg(self, "send", sp))
        if rp is not None:
            self.legs.append(_Leg(self, "recv", rp))
        self.pending_legs = len(self.legs)
        self.rbuf = None          # pooled scratch (acc path)

    def merge_chunk(self, stats, bucket: str, lo: int, hi: int) -> None:
        t0 = time.perf_counter()
        if self.ring:
            native.reduce_into(self.operator, self.rbuf[lo:hi],
                               self.acc[lo:hi])
        else:
            native.reduce_into(self.operator, self.acc[lo:hi],
                               self.rbuf[lo:hi])
        stats.add("reduce_seconds", time.perf_counter() - t0,
                  bucket=bucket)


class _Leg:
    __slots__ = ("op", "dir", "peer", "ch", "view", "off", "n",
                 "chunks", "merged", "busy", "last_progress", "src",
                 "started", "pump")

    def __init__(self, op, dir_, peer):
        self.op = op
        self.dir = dir_           # "send" | "recv"
        self.peer = peer
        self.ch = None
        self.view = None          # memoryview (cast B) once armed
        self.off = 0
        self.n = 0
        self.chunks = ()          # element ranges (recv merge path)
        self.merged = 0           # chunks merged so far
        self.busy = 0.0           # seconds inside socket syscalls
        self.last_progress = 0.0
        self.src = None           # ndarray backing the view
        self.started = False      # first byte attempted (fold point)
        self.pump = None          # shm chunk pump (SendPump/RecvPump)


class ProgressScheduler:
    """The per-slave helper progression thread (see module docstring).

    Owned by :class:`~ytk_mp4j_tpu.comm.process_comm.ProcessCommSlave`;
    created lazily on the first ``i*`` submission.
    """

    def __init__(self, slave):
        self._s = slave
        # force the one-time native load/build attempt HERE, on the
        # constructing thread with no scheduler lock in existence yet:
        # _full_ok consults the cached verdict from under _cv, and a
        # lazy first load there would run g++ (subprocess, seconds)
        # inside the lock every submit()/wait() needs (R20)
        native.ensure_loaded()
        self._cv = threading.Condition()
        self._pending: collections.deque[_Item] = collections.deque()
        self._outstanding = 0
        self._busy = False        # a unit (batch/map/inline) active:
        # wait_all must not return between the last future's
        # resolution and the unit's EPILOGUE (progress-state
        # restoration, audit commits) — a caller racing into a
        # blocking collective there would claim a duplicate ordinal
        # and clobber the audit wire accumulators
        self._failed: list[CollectiveFuture] = []
        self._fatal: BaseException | None = None
        self._stop = False
        self._thread: threading.Thread | None = None
        self._max_out = slave._max_outstanding
        self._coalesce_s = slave._coalesce_usecs / 1e6
        # wake pipe: submit() taps it so the full-native batch driver
        # (blocked in its C++ poll) returns promptly to admit new
        # collectives into the running batch
        import os
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        # overlap accounting (the ovl% column in mp4j-scope live):
        # wall intervals with >=1 / >=2 collectives outstanding,
        # flushed into the "<async>" stats family at quiescent points
        self._n = 0
        self._peak_booked = 0
        self._last_t: float | None = None
        self._inflight_s = 0.0
        self._overlap_s = 0.0

    # ------------------------------------------------------------------
    # caller side
    # ------------------------------------------------------------------
    def submit(self, name: str, args: tuple, kwargs: dict,
               kind: str) -> CollectiveFuture:
        s = self._s
        # fail fast only on TERMINAL state: a pending (recoverable)
        # abort round must NOT surface here — the caller's submit is
        # not inside any retry scope, so raising the fence's
        # Mp4jAbortError would crash the rank on exactly the faults
        # the blocking path absorbs (it parks in _join_pending_round
        # instead); the scheduler's own rec.run waits the round out
        if s._recovery.fatal is not None:
            raise s._recovery.fatal_exc()
        fut = CollectiveFuture(name, epoch=s._recovery.epoch)
        item = _Item(fut, name, args, kwargs, kind)
        with self._cv:
            self._raise_terminal()
            if self._stop:
                raise Mp4jError("slave is closed")
            # backpressure: MP4J_MAX_OUTSTANDING bounds queued + active
            while self._outstanding >= self._max_out:
                self._cv.wait(0.2)
                self._raise_terminal()
                if s._recovery.fatal is not None:
                    raise s._recovery.fatal_exc()
            self._pending.append(item)
            self._outstanding += 1
            self._account_locked(+1)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"mp4j-prog-r{s._rank}")
                self._thread.start()
            self._cv.notify_all()
        try:
            import os
            os.write(self._wake_w, b"x")   # nudge the batch driver
        except OSError:
            pass   # pipe full: a wake is already pending
        return fut

    def _raise_terminal(self) -> None:
        """Re-raise the scheduler's terminal error with its ORIGINAL
        type (an injected FaultKill must surface as FaultKill on the
        dying rank's own submissions, not re-wrapped)."""
        exc = self._fatal
        if exc is None:
            return
        if isinstance(exc, Mp4jError):
            raise exc
        raise Mp4jFatalError(str(exc))

    def active(self) -> bool:
        with self._cv:
            return self._outstanding > 0

    def outstanding(self) -> int:
        """Queued-or-in-flight count, read under the scheduler's
        condition (the progression thread decrements it there)."""
        with self._cv:
            return self._outstanding

    def wait_all(self, timeout: float | None = None) -> None:
        """The collective-boundary drain: block until every outstanding
        future resolved; re-raise the FIRST failure among futures that
        were never awaited (an awaited future's error was already
        delivered to its waiter)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cv:
            while self._outstanding > 0 or self._busy:
                remaining = (0.2 if deadline is None
                             else min(0.2, deadline - time.monotonic()))
                if remaining <= 0:
                    raise Mp4jError(
                        f"wait_all: {self._outstanding} collective(s) "
                        f"still outstanding after {timeout}s")
                self._cv.wait(max(remaining, 0.001))
            failed, self._failed = self._failed, []
        for f in failed:
            if not f._observed:
                f._observed = True
                raise f._exc

    def drain_for_blocking(self) -> None:
        """Called by blocking collectives / ``barrier()`` / ``close()``
        before they touch the data plane: outstanding futures complete
        first so the job-wide collective order stays the submit order.
        No-op on the progression thread itself (inline execution calls
        the blocking methods from there)."""
        if threading.current_thread() is self._thread:
            return
        if self.active():
            self.wait_all()

    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop accepting submissions and wait out the outstanding
        work (bounded) — the close() path. Releases the wake pipe
        once the progression thread exited (a long-lived process
        cycling slaves must not leak two fds per scheduler)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            deadline = time.monotonic() + timeout
            while self._outstanding > 0 and self._fatal is None \
                    and time.monotonic() < deadline:
                self._cv.wait(0.2)
        t = self._thread
        if t is not None:
            t.join(max(0.1, deadline - time.monotonic()))
        if t is None or not t.is_alive():
            import os
            for fd in (self._wake_r, self._wake_w):
                try:
                    os.close(fd)
                except OSError:
                    pass

    # ------------------------------------------------------------------
    # accounting (cv held)
    # ------------------------------------------------------------------
    def _account_locked(self, delta: int) -> None:
        now = time.perf_counter()
        if self._last_t is not None and self._n > 0:
            dt = now - self._last_t
            self._inflight_s += dt
            if self._n > 1:
                self._overlap_s += dt
        self._last_t = now
        self._n += delta
        stats = self._s._comm_stats
        stats.metrics.set_gauge("async/outstanding", float(self._n))
        if self._n > self._peak_booked:
            # outstanding_peak stays monotone by booking INCREASES
            # only, so the heartbeat's additive delta algebra carries
            # it: the per-rank value is the true peak; cluster folds
            # sum peaks across ranks (documented in README)
            stats.add("outstanding_peak", self._n - self._peak_booked,
                      bucket="<async>")
            # mp4j-lint: disable=R15 (_n is the outstanding-collective count, not roster state)
            self._peak_booked = self._n
        if self._n == 0 and self._inflight_s > 0.0:
            stats.add("async_inflight", self._inflight_s,
                      bucket="<async>")
            if self._overlap_s > 0.0:
                stats.add("async_overlap", self._overlap_s,
                          bucket="<async>")
            self._inflight_s = 0.0
            self._overlap_s = 0.0

    def _finish(self, item: _Item, value=None,
                exc: BaseException | None = None) -> None:
        # resolve BEFORE the outstanding count drops: a wait_all()
        # waiter wakes on the count and may immediately re-raise an
        # unobserved failure — the future must already carry it
        if exc is not None:
            item.future._fail(exc)
        else:
            item.future._resolve(value)
        with self._cv:
            self._account_locked(-1)
            self._outstanding -= 1
            if exc is not None:
                self._failed.append(item.future)
            self._cv.notify_all()

    # ------------------------------------------------------------------
    # progression thread
    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending:
                    if self._stop or self._fatal is not None:
                        return
                    self._cv.wait(0.2)
                head = self._pending[0]
                self._busy = True
            try:
                if head.kind == "engine":
                    self._run_engine_batch()
                elif head.kind == "map":
                    self._run_map_batch()
                elif head.kind == "array":
                    self._run_array_batch()
                else:
                    self._run_inline()
            except BaseException as e:
                # terminal (Mp4jFatalError, an injected kill, an engine
                # defect): fail every queued future with the same error
                # so no waiter ever hangs, then stop the scheduler
                self._go_fatal(e)
                return
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _go_fatal(self, exc: BaseException) -> None:
        with self._cv:
            self._fatal = exc
            self._busy = False
            items = list(self._pending)
            self._pending.clear()
            self._cv.notify_all()
        for it in items:
            self._finish(it, exc=exc)

    def _pop_head(self) -> _Item:
        with self._cv:
            return self._pending.popleft()

    # -- inline ---------------------------------------------------------
    def _run_inline(self) -> None:
        item = self._pop_head()
        try:
            out = getattr(self._s, item.name)(*item.args,
                                              **item.kwargs)
        except Mp4jFatalError:
            self._finish(item, exc=self._s._recovery.fatal_exc(
                str(self._s._recovery.fatal or "fatal abort")))
            raise
        except Exception as e:
            if _is_kill(e):
                self._finish(item, exc=e)
                raise
            self._finish(item, exc=e)
            return
        self._finish(item, value=out)

    # -- fused maps (small-message coalescing) --------------------------
    def _run_map_batch(self) -> None:
        s = self._s
        batch = [self._pop_head()]
        operand = batch[0].args[1]
        operator = batch[0].args[2]
        deadline = time.monotonic() + self._coalesce_s
        while len(batch) < self._max_out:
            with self._cv:
                if not self._pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(min(remaining, 0.002))
                nxt = self._pending[0] if self._pending else None
                # only CONSECUTIVE same-signature maps fuse: batching
                # by content keeps the multi-call sequence identical on
                # every rank whatever the local timing (the negotiated
                # batch size absorbs ragged coalescing depth)
                if (nxt is not None and nxt.kind == "map"
                        and nxt.args[1] is operand
                        and nxt.args[2] is operator):
                    batch.append(self._pending.popleft())
                    continue
                if nxt is not None:
                    break
        dicts = [it.args[0] for it in batch]
        try:
            m = s.allreduce_map_multi(dicts, operand, operator)
        except Mp4jFatalError:
            for it in batch:
                self._finish(it, exc=s._recovery.fatal_exc(
                    str(s._recovery.fatal or "fatal abort")))
            raise
        except Exception as e:
            for it in batch:
                self._finish(it, exc=e)
            if _is_kill(e):
                raise
            return
        # de-fuse: the negotiated first m maps completed; leftovers
        # (this rank coalesced deeper than the slowest rank) re-queue
        # at the FRONT so submit order is preserved
        leftovers = batch[m:]
        if leftovers:
            with self._cv:
                self._pending.extendleft(reversed(leftovers))
        for it in batch[:m]:
            self._finish(it, value=it.args[0])

    # -- fused dense small arrays (ISSUE 17) ----------------------------
    def _run_array_batch(self) -> None:
        """The array-plane twin of :meth:`_run_map_batch`: consecutive
        same-signature small ``iallreduce`` submissions arriving within
        the coalescing window fuse into ONE count-negotiated
        ``allreduce_array_multi`` exchange; the negotiated first ``m``
        resolve, leftovers re-queue at the front (submit order
        preserved — the job-wide collective order)."""
        s = self._s
        batch = [self._pop_head()]
        operand = batch[0].args[1]
        operator = batch[0].args[2]
        deadline = time.monotonic() + self._coalesce_s
        while len(batch) < self._max_out:
            with self._cv:
                if not self._pending:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(min(remaining, 0.002))
                nxt = self._pending[0] if self._pending else None
                # only CONSECUTIVE same-signature arrays fuse — the
                # map batch's rule, for the same job-wide reason
                if (nxt is not None and nxt.kind == "array"
                        and nxt.args[1] is operand
                        and nxt.args[2] is operator):
                    batch.append(self._pending.popleft())
                    continue
                if nxt is not None:
                    break
        arrs = [it.args[0] for it in batch]
        try:
            m = s.allreduce_array_multi(arrs, operand, operator)
        except Mp4jFatalError:
            for it in batch:
                self._finish(it, exc=s._recovery.fatal_exc(
                    str(s._recovery.fatal or "fatal abort")))
            raise
        except Exception as e:
            for it in batch:
                self._finish(it, exc=e)
            if _is_kill(e):
                raise
            return
        leftovers = batch[m:]
        if leftovers:
            with self._cv:
                self._pending.extendleft(reversed(leftovers))
        for it in batch[:m]:
            self._finish(it, value=it.args[0])

    # ==================================================================
    # the interleaved raw-plane engine
    # ==================================================================
    def _run_engine_batch(self) -> None:
        s = self._s
        rec = s._recovery
        tun = s._tuner
        if tun is not None and tun.dirty:
            # the batch is ONE collective boundary: pending tuner
            # decisions (chunk granularity, socket buffers) land
            # before any member's wire byte moves, exactly where the
            # blocking wrapper applies them — engine legs then read
            # the adapted per-link chunk schedule via _chunk_for
            s._tuner_apply(tun)
        batch: list[_Item] = []
        queues: dict[tuple[int, str], collections.deque] = {}
        touched: dict = {}       # channels switched to nonblocking
        first = [True]
        base = s._progress_state[0] + 1
        s._progress_state = (base, True)

        def preserve():
            return None          # per-item snapshots live at admission

        def restore(_):
            self._restore_batch(batch)

        def attempt():
            admit = first[0]
            first[0] = False
            if not admit:
                # retry: rebuild every member's state machine from its
                # restored payload; the ticket queues are re-derived so
                # the fresh epoch's channels replay the same sequence
                queues.clear()
                for it in batch:
                    self._build_ops(it)
                    self._enqueue(it, queues)
            try:
                self._drive(batch, queues, touched, admit=admit,
                            base=base)
            finally:
                self._restore_channels(touched)

        outermost = rec.enter()
        try:
            assert outermost, "engine batch nested inside a collective"
            try:
                if s._faults is not None:
                    # batch boundary: earlier ordinals' unfired
                    # one-shot directives disarm here (the sequential
                    # path disarms at each next collective instead)
                    s._faults.prune_below(base)
                # admit the head before rec.run so the batch is never
                # empty (further admissions happen inside the drive)
                self._admit(self._pop_head(), batch, queues, base)
                rec.run(batch[0].name, attempt, preserve, restore)
            except BaseException as e:
                if s._audit is not None:
                    for it in batch:
                        if it.arec is not None:
                            s._audit.abandon(it.arec, e)
                for it in batch:
                    if not it.resolved:
                        self._finish(it, exc=e)
                if isinstance(e, Mp4jFatalError) or _is_kill(e):
                    raise
                if not isinstance(e, (Mp4jError, OSError, EOFError)):
                    raise          # engine defect: surface loudly
                return
            finally:
                s._progress_state = (
                    batch[-1].ordinal if batch else base, False)
            # audit records commit once, at batch end: a retry would
            # re-run even already-resolved members, and a committed
            # record must carry the FINAL attempt's wire folds
            audit = s._audit
            now = time.perf_counter()
            for it in batch:
                if audit is not None and it.arec is not None:
                    if audit.wire_on and it.wire:
                        audit.put_wire(it.wire)
                    audit.commit(it.arec, it.payload)
                if not it.resolved:   # pragma: no cover - safety net
                    it.resolved = True
                    s._comm_stats.async_end(it.name, now - it.t0)
                    self._finish(it, value=it.payload)
        finally:
            rec.exit()

    # -- admission ------------------------------------------------------
    def _admit(self, item: _Item, batch, queues, base: int) -> None:
        try:
            self._admit_inner(item, batch, queues, base)
        except BaseException as e:
            # an admission that dies BEFORE the item joins the batch
            # (an injected kill firing at on_collective, a schedule-
            # build defect) must still fail the item's future — the
            # batch error path only covers members, and a popped-but-
            # lost item would strand its waiter forever
            if item not in batch:
                self._finish(item, exc=e)
            raise

    def _admit_inner(self, item: _Item, batch, queues,
                     base: int) -> None:
        s = self._s
        item.ordinal = base + len(batch)
        item.payload = item.args[0]
        if s._faults is not None:
            # kill plans fire here, exactly as at the blocking
            # wrapper's entry (retried attempts keep the first
            # ordinal: _admit runs once per submission, never on a
            # retry rebuild, so a one-shot fault cannot re-fire into
            # its own recovery). The WINDOW variant arms without
            # disarming earlier batch members' directives — batch
            # ordinals are concurrent, not sequential
            s._faults.on_collective_window(item.ordinal, s._fault_kill)
        if s._max_retries > 0 and item.name not in (
                "allgather_array", "gather_array"):
            # the same tight snapshot rule as _SNAPSHOT_FREE: pure
            # overwrite collectives retry from the caller's intact data
            from ytk_mp4j_tpu.comm import process_comm as pc
            item.snapshot = pc._preserve_payload(s, item.payload)
        if s._audit is not None:
            item.arec = s._audit.begin(
                item.ordinal, item.name, item.payload,
                self._audit_meta(item))
        item.seq = s._comm_stats.async_begin(item.name)
        item.t0 = time.perf_counter()
        self._build_ops(item)
        batch.append(item)
        self._enqueue(item, queues)

    @staticmethod
    def _audit_meta(item: _Item) -> dict:
        kw = item.kwargs
        meta_: dict = {}
        if len(item.args) > 1:
            meta_["operand"] = item.args[1].name
        if len(item.args) > 2:
            meta_["operator"] = item.args[2].name
        if "root" in kw:
            meta_["root"] = int(kw.get("root", 0))
        # records replayable as the blocking twin carry only the
        # standard leading run; ranges / nonzero root / sub-ranges mark
        # the record non-replayable instead of replaying another call
        if (kw.get("from_", 0) != 0 or kw.get("to") is not None
                or kw.get("ranges") is not None
                or kw.get("root", 0) != 0):
            meta_["nonstd"] = True
        return meta_

    def _restore_batch(self, batch: list[_Item]) -> None:
        s = self._s
        audit = s._audit
        if audit is not None:
            # the failed attempt's wire folds died in the epoch drain
            # on the peer side too (see the blocking wrapper)
            audit.reset_wire()
        from ytk_mp4j_tpu.comm import process_comm as pc
        from ytk_mp4j_tpu.obs import audit as audit_mod
        for it in batch:
            if it.snapshot is None:
                continue
            pc._restore_payload(it.payload, it.snapshot)
            if audit is not None and it.arec is not None:
                h, _sig = audit_mod.digest_payload(it.payload)
                if h != it.arec["in"]:
                    raise Mp4jError(
                        f"audit: restored retry snapshot of "
                        f"'{it.name}' (collective #{it.ordinal}) "
                        f"digests {h:#018x}, original input was "
                        f"{it.arec['in']:#018x} — the snapshot was "
                        "corrupted; refusing to retry from tainted "
                        "input")

    # -- schedule builders ---------------------------------------------
    def _build_ops(self, item: _Item) -> None:
        s = self._s
        name = item.name
        item.cursor = 0
        item.ops = []
        item.wire = {}
        if name == "allreduce_array":
            arr, operand, operator = item.args[0:3]
            arr, lo, hi = s._norm_range(arr, operand,
                                        item.kwargs.get("from_", 0),
                                        item.kwargs.get("to"))
            algo = _resolved_allreduce_algo(
                s, arr, lo, hi, operand, item.kwargs.get("algo", "auto"))
            if algo == "rhd":
                item.ops = _rhd_ops(s, item, arr, lo, hi, operator)
            else:
                segs = meta.partition_range(lo, hi, s._n)
                item.ops = _ring_rs_ops(s, item, arr, segs, operator)
                item.ops += _ring_ag_ops(s, item, arr, segs,
                                         base_idx=len(item.ops))
        elif name == "reduce_scatter_array":
            arr, operand, operator = item.args[0:3]
            arr, _, _ = s._norm_range(arr, operand, 0, None)
            ranges = (item.kwargs.get("ranges")
                      or meta.partition_range(0, len(arr), s._n))
            item.ops = _ring_rs_ops(s, item, arr, ranges, operator)
        elif name == "allgather_array":
            arr, operand = item.args[0:2]
            arr, _, _ = s._norm_range(arr, operand, 0, None)
            ranges = (item.kwargs.get("ranges")
                      or meta.partition_range(0, len(arr), s._n))
            item.ops = _ring_ag_ops(s, item, arr, ranges)
        elif name == "gather_array":
            arr, operand = item.args[0:2]
            arr, _, _ = s._norm_range(arr, operand, 0, None)
            root = item.kwargs.get("root", 0)
            s._check_root(root)
            ranges = (item.kwargs.get("ranges")
                      or meta.partition_range(0, len(arr), s._n))
            item.ops = _gather_ops(s, item, arr, ranges, root)
        else:                    # pragma: no cover - classifier bug
            raise Mp4jError(f"engine cannot schedule '{name}'")

    def _enqueue(self, item: _Item,
                 queues: dict[tuple[int, str], collections.deque]
                 ) -> None:
        """Enqueue every leg ticket of every op UP FRONT: the complete
        per-(peer, direction) sequence is what makes interleaving safe
        — both endpoints derive the identical order from the pure
        schedules and the shared submit order, so a later collective's
        leg can never overtake an earlier one on the same wire."""
        for op in item.ops:
            for leg in op.legs:
                queues.setdefault((leg.peer, leg.dir),
                                  collections.deque()).append(leg)

    # -- the poll loop --------------------------------------------------
    def _drive(self, batch, queues, touched, admit: bool,
               base: int) -> None:
        if native.have_progress_multi():
            s = self._s
            # the batch leg-graph driver books its wire records POST
            # HOC, which is only truthful for receive buffers (merges
            # never touch them); a SEND view's bytes are overwritten
            # by later rounds of its own schedule, so verify-mode wire
            # folds must ride the per-leg loop, which folds each leg
            # at its true wire time. Fault hooks likewise fire per leg.
            wire_on = s._audit is not None and s._audit.wire_on
            if s._faults is None and not wire_on and \
                    all(self._full_ok(it) for it in batch) and \
                    self._drive_full(batch, queues, touched, admit,
                                     base):
                return
            return self._drive_native(batch, queues, touched, admit,
                                      base)
        return self._drive_py(batch, queues, touched, admit, base)

    # -- the batch leg-graph driver (one native call per batch) ---------
    def _full_ok(self, it: _Item) -> bool:
        """Whether a collective's whole op list can run inside the
        native leg-graph driver: no carry chains or completion hooks
        (ring reduce-scatter rotates pooled buffers in Python), and
        every merge must have a native kernel. A pure function of the
        call parameters — but only an EXECUTION-strategy choice (the
        wire bytes and their per-channel order are identical on every
        path), so no cross-rank agreement is needed."""
        if it.kind != "engine":
            return False
        for op in it.ops:
            if op.ring or op.on_done is not None:
                return False
            if op.acc is not None and native.reduce_opcode(
                    op.operator, op.acc.dtype) is None:
                return False
        return True

    def _drive_full(self, batch, queues, touched, admit: bool,
                    base: int) -> bool:
        """Run the WHOLE batch's leg graph in the native driver
        (``mp4j_run_legs``): every leg of every outstanding collective,
        its FIFO and op-order dependencies encoded as gates, and its
        reduce-merge run natively at leg completion — one Python-to-C
        round trip per batch instead of one per leg, which is what
        lets k outstanding collectives amortize the per-exchange
        scheduling costs k-fold. Falls back (returns False, nothing
        moved) when any channel rides shm — the rings are not fds; the
        hybrid loop owns them."""
        import ctypes

        s = self._s
        rec = s._recovery
        for it in batch:
            for op in it.ops:
                if not op.armed and not self._arm(op, touched):
                    return False     # peer not dialed in yet
                for leg in op.legs:
                    if isinstance(leg.ch, shm_mod.ShmChannel):
                        return False     # hybrid loop owns the rings
        timeout = s._peer_timeout

        def build(gates):
            legs: list[_Leg] = []
            last_q: dict[tuple[int, str], int] = {}
            for it in batch:
                prev_op: list[int] = []
                for op in it.ops:
                    cur: list[int] = []
                    for leg in op.legs:
                        cur.append(len(legs))
                        legs.append(leg)
                    for i in cur:
                        leg = legs[i]
                        # gate 0: the per-(peer, direction) FIFO
                        # predecessor; gates 1-2: the previous op's
                        # legs (the collective's own sequencing).
                        # Only wire-touching legs may anchor the FIFO
                        # chain: a zero-length leg (an empty rhd
                        # segment) is "complete" at birth, so a
                        # successor gated on it would unblock before
                        # the chain BEHIND it finished — two same-
                        # (peer, dir) legs ungated at once, and the
                        # fd slot scan would pair the stream's bytes
                        # with the wrong collective
                        g = ([last_q.get((leg.peer, leg.dir), -1)]
                             + prev_op[:2])
                        while len(g) < 3:
                            g.append(-1)
                        gates[i * 3:i * 3 + 3] = g
                        if leg.n > 0:
                            last_q[(leg.peer, leg.dir)] = i
                    if cur:
                        prev_op = cur
            return legs

        while True:
            cap = sum(len(op.legs) for it in batch for op in it.ops)
            if cap > 256:
                return False     # far beyond MP4J_MAX_OUTSTANDING use
            gates = np.full(3 * cap, -1, np.int32)
            legs = build(gates)
            n = len(legs)
            fds = np.fromiter((lg.ch.sock.fileno() for lg in legs),
                              np.int32, n)
            dirs = np.fromiter(
                (0 if lg.dir == "send" else 1 for lg in legs),
                np.int32, n)
            bufs = (ctypes.c_void_p * n)(
                *[lg.src.ctypes.data for lg in legs])
            lens = np.fromiter((lg.n for lg in legs), np.int64, n)
            dones = np.fromiter((lg.off for lg in legs), np.int64, n)
            mdst = (ctypes.c_void_p * n)()
            msrc = (ctypes.c_void_p * n)()
            mdtype = np.zeros(n, np.int32)
            mopcode = np.zeros(n, np.int32)
            mcount = np.zeros(n, np.int64)
            # chunk-granular native merges (ISSUE 17): the merge step
            # is the leg's tuner-adapted chunk schedule, the cursor
            # resumes mid-buffer across rebuilds/handovers
            mchunk = np.zeros(n, np.int64)
            melems = np.zeros(n, np.int64)
            for i, lg in enumerate(legs):
                op = lg.op
                if lg.dir == "recv" and op.acc is not None:
                    dt, oc = native.reduce_opcode(op.operator,
                                                  op.acc.dtype)
                    mdst[i] = op.acc.ctypes.data
                    msrc[i] = op.rbuf.ctypes.data
                    mdtype[i] = dt
                    mopcode[i] = oc
                    mcount[i] = op.acc.size
                    if lg.chunks:
                        mchunk[i] = lg.chunks[0][1] - lg.chunks[0][0]
                        melems[i] = (lg.chunks[lg.merged - 1][1]
                                     if lg.merged else 0)
            status = np.zeros(n, np.int8)
            stall_since = time.monotonic()
            last_total = int(dones.sum())
            grew = False
            t0 = time.perf_counter()
            while True:
                try:
                    rc = native.run_legs(
                        fds, dirs, bufs, lens, dones, gates,
                        mdst, msrc, mdtype, mopcode, mcount,
                        mchunk, melems, status, self._wake_r, 0.05)
                except Mp4jError as e:
                    self._sync_full(legs, dones, melems)
                    bad = np.flatnonzero(status != 0)
                    peer = (legs[int(bad[0])].peer if bad.size
                            else "?")
                    raise Mp4jTransportError(
                        f"async exchange with peer {peer} failed: "
                        f"{e}") from None
                rec.poll()
                if rc == 1:
                    break
                total = int(dones.sum())
                if total != last_total:
                    last_total = total
                    stall_since = time.monotonic()
                elif timeout is not None and \
                        time.monotonic() - stall_since > timeout:
                    self._sync_full(legs, dones, melems)
                    raise Mp4jTransportError(
                        f"async batch stalled for {timeout}s "
                        f"({int((lens - dones).sum())} bytes pending)")
                if rc == 2 and admit:
                    self._sync_full(legs, dones, melems)
                    added = False
                    with self._cv:
                        while (self._pending
                               and self._pending[0].kind == "engine"
                               and len(batch) < self._max_out):
                            self._admit(self._pending.popleft(),
                                        batch, queues, base)
                            added = True
                            if not self._full_ok(batch[-1]):
                                break
                    if added:
                        if not all(self._full_ok(it)
                                   for it in batch):
                            # a newcomer the leg-graph driver cannot
                            # express: finish the batch on the hybrid
                            # loop (wire-identical)
                            self._handover_folds(legs)
                            self._drive_native(batch, queues,
                                               touched, False, base)
                            return True
                        for it in batch:
                            for op in it.ops:
                                if not op.armed and \
                                        not self._arm(op, touched):
                                    # a newcomer whose peer has not
                                    # dialed in yet: the hybrid loop
                                    # retries arming each pass
                                    self._handover_folds(legs)
                                    self._drive_native(
                                        batch, queues, touched,
                                        False, base)
                                    return True
                                for leg in op.legs:
                                    if isinstance(
                                            leg.ch,
                                            shm_mod.ShmChannel):
                                        self._handover_folds(legs)
                                        self._drive_native(
                                            batch, queues, touched,
                                            False, base)
                                        return True
                        grew = True
                        break     # rebuild arrays with the newcomers
            if grew:
                continue
            dt_total = time.perf_counter() - t0
            self._sync_full(legs, dones, melems)
            # post-hoc stats bookkeeping (the driver ran the bytes;
            # records follow). Wire AUDIT folds never ride this path:
            # verify mode routes to the per-leg loop (see _drive) —
            # a send view's bytes are overwritten by its schedule's
            # later rounds, so only at-wire-time folds are truthful.
            nbytes_total = max(1, int(lens.sum()))
            for lg in legs:
                lg.busy = dt_total * lg.n / nbytes_total
            for it in batch:
                for op in it.ops:
                    for lg in op.legs:
                        q = queues.get((lg.peer, lg.dir))
                        if q and q[0] is lg:
                            q.popleft()
                        elif q and lg in q:
                            q.remove(lg)
                        self._leg_done(lg)
            return True

    @staticmethod
    def _sync_full(legs, dones, melems) -> None:
        """Mirror the native driver's in-out progress back onto the
        leg objects (rebuilds and error paths read them). ``melems``
        always lands on a chunk boundary — the native merge step IS
        the leg's chunk schedule — so the chunk cursor is exact."""
        for i, lg in enumerate(legs):
            lg.off = int(dones[i])
            done = int(melems[i])
            if done:
                lg.merged = (sum(1 for _, hi in lg.chunks
                                 if hi <= done)
                             if lg.chunks else 1)

    def _handover_folds(self, legs) -> None:
        """Catch the wire folds up before handing a part-run batch to
        the hybrid loop: bytes the native driver already received must
        fold now (the hybrid loop folds incrementally from the current
        offset); send legs keep their not-started state — the hybrid
        leg-start folds the whole intended view once, as always."""
        if self._s._audit is None or not self._s._audit.wire_on:
            for lg in legs:
                if lg.dir == "recv" and lg.off > 0:
                    lg.started = True
            return
        for lg in legs:
            if lg.dir == "recv" and lg.off > 0 and not lg.started:
                lg.op.item.fold(lg.peer, "recv", lg.view[:lg.off],
                                lg.ch.transport)
                lg.started = True

    def _drive_native(self, batch, queues, touched, admit: bool,
                      base: int) -> None:
        """The per-leg native byte mover: every runnable tcp leg (each
        per-channel queue's head whose op's turn has come) goes down
        to ONE C++ poll loop per pass (``mp4j_progress_multi``), which
        moves bytes on whichever fd is ready and returns on leg
        completions (or a fence-poll tick); shm legs pump the ring
        piece/sync-byte schedule chunk-granularly in Python each pass
        (wire-identical to the blocking path at every size — see
        :meth:`_pump_shm`). This is the engine's fallback when the
        whole-batch leg-graph driver (:meth:`_drive_full`) cannot
        express a member; correctness equal, more Python per leg."""
        import ctypes

        s = self._s
        rec = s._recovery
        timeout = s._peer_timeout
        while True:
            rec.poll()
            if admit and len(batch) < self._max_out:
                with self._cv:
                    while (self._pending
                           and self._pending[0].kind == "engine"
                           and len(batch) < self._max_out):
                        self._admit(self._pending.popleft(), batch,
                                    queues, base)
            progressed = False
            legs: list[_Leg] = []
            for q in queues.values():
                if not q:
                    continue
                leg = q[0]
                op = leg.op
                if op.item.cursor != op.idx:
                    continue      # not this collective's turn yet
                if not op.armed:
                    if not self._arm(op, touched):
                        continue  # peer not dialed in yet: next pass
                    progressed = True
                if isinstance(leg.ch, shm_mod.ShmChannel):
                    # the rings are not fds: pump in Python each pass
                    if self._pump_shm(leg):
                        progressed = True
                    if self._leg_settled(leg):
                        q.popleft()
                        self._leg_done(leg)
                        progressed = True
                    elif timeout is not None and \
                            time.monotonic() - leg.last_progress \
                            > timeout:
                        to = "to" if leg.dir == "send" else "from"
                        raise Mp4jTransportError(
                            f"async {leg.dir} {to} peer {leg.peer} "
                            f"stalled for {timeout}s (collective "
                            f"#{leg.op.item.ordinal})")
                    continue
                if not leg.started:
                    self._leg_start(leg)
                if leg.off >= leg.n:
                    # already complete (a leg-graph handover, or a
                    # zero-length leg): retire it here — the native
                    # pass below only processes legs that moved
                    q.popleft()
                    self._leg_done(leg)
                    progressed = True
                    continue
                legs.append(leg)
            if all(it.cursor >= len(it.ops) for it in batch):
                with self._cv:
                    more = (admit and self._pending
                            and self._pending[0].kind == "engine"
                            and len(batch) < self._max_out)
                if not more:
                    return
                continue
            if not legs:
                if not progressed:
                    time.sleep(0.0005)
                continue
            # the native driver's poll set is capped at 256 fds; the
            # scan order is queue order, so slicing stays FIFO-fair
            # (the tail runs on later passes)
            legs = legs[:256]
            n = len(legs)
            fds = np.fromiter((leg.ch.sock.fileno() for leg in legs),
                              np.int32, n)
            dirs = np.fromiter(
                (0 if leg.dir == "send" else 1 for leg in legs),
                np.int32, n)
            bufs = (ctypes.c_void_p * n)(
                *[leg.src.ctypes.data for leg in legs])
            lens = np.fromiter((leg.n for leg in legs), np.int64, n)
            dones = np.fromiter((leg.off for leg in legs), np.int64, n)
            status = np.zeros(n, np.int8)
            tick = 0.001 if progressed else 0.05
            t0 = time.perf_counter()
            try:
                native.progress_multi(fds, dirs, bufs, lens, dones,
                                      status, tick)
            except Mp4jError as e:
                bad = np.flatnonzero(status != 0)
                peer = (legs[int(bad[0])].peer if bad.size
                        else "?")
                raise Mp4jTransportError(
                    f"async exchange with peer {peer} failed: {e}"
                ) from None
            dt = time.perf_counter() - t0
            now = time.monotonic()
            moved_total = int(dones.sum()) - sum(
                leg.off for leg in legs)
            for i, leg in enumerate(legs):
                delta = int(dones[i]) - leg.off
                if delta <= 0:
                    if timeout is not None and \
                            now - leg.last_progress > timeout:
                        to = "to" if leg.dir == "send" else "from"
                        raise Mp4jTransportError(
                            f"async {leg.dir} {to} peer {leg.peer} "
                            f"stalled for {timeout}s (collective "
                            f"#{leg.op.item.ordinal})")
                    continue
                prev = leg.off
                leg.off = int(dones[i])
                leg.last_progress = now
                if moved_total > 0:
                    leg.busy += dt * delta / moved_total
                if leg.dir == "recv":
                    if s._audit is not None and s._audit.wire_on:
                        # fold arrivals BEFORE any merge mutates the
                        # scratch (the ring shape merges in place)
                        leg.op.item.fold(leg.peer, "recv",
                                         leg.view[prev:leg.off],
                                         leg.ch.transport)
                    self._merge_ready(leg)
                if leg.off >= leg.n:
                    queues[(leg.peer, leg.dir)].popleft()
                    self._leg_done(leg)

    def _drive_py(self, batch, queues, touched, admit: bool,
                  base: int) -> None:
        s = self._s
        rec = s._recovery
        while True:
            rec.poll()
            # dynamic admission (first attempt only): consecutive
            # engine-eligible submissions join the running batch so a
            # stream of iallreduces overlaps end to end
            if admit and len(batch) < self._max_out:
                with self._cv:
                    while (self._pending
                           and self._pending[0].kind == "engine"
                           and len(batch) < self._max_out):
                        self._admit(self._pending.popleft(), batch,
                                    queues, base)
            progressed = False
            rsel: dict[int, _Leg] = {}
            wsel: dict[int, _Leg] = {}
            rwait: list[_Leg] = []
            for q in queues.values():
                if not q:
                    continue
                leg = q[0]
                op = leg.op
                if op.item.cursor != op.idx:
                    continue      # not this collective's turn yet
                if not op.armed:
                    if not self._arm(op, touched):
                        continue  # peer not dialed in yet: next pass
                    progressed = True
                if isinstance(leg.ch, shm_mod.ShmChannel):
                    moved = self._pump_shm(leg)
                else:
                    moved = (self._pump_send(leg) if leg.dir == "send"
                             else self._pump_recv(leg))
                if moved:
                    progressed = True
                    leg.last_progress = time.monotonic()
                if self._leg_settled(leg):
                    q.popleft()
                    self._leg_done(leg)
                    progressed = True
                elif leg.pump is not None and leg.dir == "send" \
                        and not leg.pump.want_carrier:
                    # blocked on ring SPACE (peer reader behind):
                    # nothing selectable — park on a short tick
                    rwait.append(leg)
                else:
                    fd = leg.ch.sock.fileno()
                    (wsel if leg.dir == "send" else rsel)[fd] = leg
            if all(it.cursor >= len(it.ops) for it in batch):
                with self._cv:
                    more = (admit and self._pending
                            and self._pending[0].kind == "engine"
                            and len(batch) < self._max_out)
                if not more:
                    return
                continue          # admit the newcomers first
            if not progressed:
                self._park(rsel, wsel, rwait)

    def _park(self, rsel, wsel, rwait=()) -> None:
        if rsel or wsel:
            try:
                select.select(list(rsel), list(wsel), [],
                              0.002 if rwait else 0.02)
            except (OSError, ValueError):
                # a torn-down fd (abort teardown raced the select):
                # the next pump raises a clean transport error
                time.sleep(0.001)
        else:
            time.sleep(0.0005 if rwait else 0.001)
        timeout = self._s._peer_timeout
        if timeout is not None:
            now = time.monotonic()
            for leg in [*rsel.values(), *wsel.values(), *rwait]:
                if now - leg.last_progress > timeout:
                    to = "to" if leg.dir == "send" else "from"
                    raise Mp4jTransportError(
                        f"async {leg.dir} {to} peer {leg.peer} "
                        f"stalled for {timeout}s (collective "
                        f"#{leg.op.item.ordinal})")

    # -- arming ---------------------------------------------------------
    def _arm(self, op: _Op, touched: dict) -> bool:
        """Bind the op's channels NOW, under the epoch fence (the PR 5
        submit-time-binding discipline: an op from an aborted attempt
        must die with its own epoch's channel, never late-resolve a
        fresh one), resolve buffers, and flip TCP sockets nonblocking
        for the poll loop.

        Channel binding is NON-blocking: when an accept-side channel
        has not been dialed yet this returns False and the op stays
        queued for a later pass — parking the progression thread here
        would stop every other leg it owns, and the missing dial can
        be cursor-gated behind exactly those legs' bytes on the peer
        (a cross-rank establishment/byte deadlock). A dead peer still
        surfaces: the deferral clock raises after the job timeout."""
        s = self._s
        chans = []
        for leg in op.legs:
            ch = s._fenced_try(leg.peer)
            if ch is None:
                now = time.monotonic()
                if op.wait_since is None:
                    op.wait_since = now
                elif s._timeout is not None and \
                        now - op.wait_since > s._timeout:
                    raise Mp4jTransportError(
                        f"timeout waiting for peer {leg.peer} to "
                        f"connect (collective #{op.item.ordinal})")
                return False
            chans.append(ch)
        op.wait_since = None
        sarr = op.sarr() if callable(op.sarr) else op.sarr
        for leg, ch in zip(op.legs, chans):
            leg.ch = ch
            if leg.dir == "send":
                leg.src = (np.ascontiguousarray(sarr)
                           if sarr is not None else None)
        if op.acc is not None and op.rbuf is None:
            op.rbuf = s._scratch.take(op.acc.dtype, op.acc.size)
        for leg in op.legs:
            if leg.dir == "send":
                leg.view = memoryview(_raw_view(leg.src)).cast("B")
            else:
                dst = op.rbuf if op.acc is not None else op.rdst
                leg.src = dst
                leg.view = memoryview(_raw_view(dst)).cast("B")
                # per-LINK chunk granularity (ISSUE 17): merge (and,
                # on shm, wire) boundaries follow the tuner's adapted
                # decision exactly like the blocking _chunked_exchange
                # — merges are element-wise, so any partition is
                # bit-exact; shm links pin the job default (_chunk_for)
                leg.chunks = tuning.chunk_ranges(
                    dst.size, dst.dtype.itemsize,
                    s._chunk_for(leg.peer))
            leg.n = len(leg.view)
            leg.last_progress = time.monotonic()
            if leg.ch not in touched:
                touched[leg.ch] = True
                leg.ch.sock.setblocking(False)
        op.pending_legs = len(op.legs)
        op.armed = True
        if not op.legs:           # pragma: no cover - degenerate op
            self._op_done(op)
        return True

    def _leg_start(self, leg: _Leg) -> None:
        """First-byte hooks: the send-side audit fold (BEFORE any
        injected corruption — the record describes what this rank
        MEANT to send) into the collective's OWN fold accumulator, and
        the fault-injection I/O hook."""
        s = self._s
        leg.started = True
        wire_on = s._audit is not None and s._audit.wire_on
        if leg.dir == "send":
            if wire_on:
                leg.op.item.fold(leg.peer, "send", leg.view,
                                 leg.ch.transport)
            if s._faults is not None:
                s._faults.on_io(leg.ch, "send")
                f = s._faults.take_corrupt(leg.ch, leg.n)
                if f is not None:
                    from ytk_mp4j_tpu.resilience import faults as fm
                    corrupted = fm.corrupt_copy(leg.src)
                    leg.src = corrupted
                    leg.view = memoryview(
                        _raw_view(corrupted)).cast("B")
        else:
            if s._faults is not None:
                s._faults.on_io(leg.ch, "recv")

    def _restore_channels(self, touched: dict) -> None:
        for ch in list(touched):
            try:
                ch.set_timeout(self._s._peer_timeout)
            except OSError:
                pass   # torn down since; the drain owns the close
        touched.clear()

    # -- byte movement --------------------------------------------------
    def _pump_recv(self, leg: _Leg) -> int:
        if not leg.started:
            self._leg_start(leg)
        sock = leg.ch.sock
        moved = 0
        while leg.off < leg.n:
            want = min(leg.n - leg.off, _IO_SLICE)
            t0 = time.perf_counter()
            try:
                r = sock.recv_into(leg.view[leg.off:], want)
            except (BlockingIOError, InterruptedError):
                break
            except OSError as e:
                raise Mp4jTransportError(
                    f"async recv from peer {leg.peer} failed: {e}"
                ) from None
            finally:
                leg.busy += time.perf_counter() - t0
            if r == 0:
                raise Mp4jTransportError(
                    f"peer {leg.peer} closed the connection mid-"
                    f"collective ({leg.n - leg.off}/{leg.n} bytes "
                    "short)")
            prev = leg.off
            leg.off += r
            moved += r
            if self._s._audit is not None and self._s._audit.wire_on:
                # fold arrivals BEFORE any merge mutates the scratch
                # (the ring shape merges in place); crc folds are
                # chunking-invariant, so arbitrary recv spans compose
                leg.op.item.fold(leg.peer, "recv",
                                 leg.view[prev:leg.off],
                                 leg.ch.transport)
            self._merge_ready(leg)
        return moved

    def _pump_send(self, leg: _Leg) -> int:
        if not leg.started:
            self._leg_start(leg)
        sock = leg.ch.sock
        moved = 0
        while leg.off < leg.n:
            t0 = time.perf_counter()
            try:
                r = sock.send(leg.view[leg.off:leg.off + _IO_SLICE])
            except (BlockingIOError, InterruptedError):
                break
            except OSError as e:
                raise Mp4jTransportError(
                    f"async send to peer {leg.peer} failed: {e}"
                ) from None
            finally:
                leg.busy += time.perf_counter() - t0
            leg.off += r
            moved += r
        return moved

    def _merge_ready(self, leg: _Leg) -> None:
        """Run the op's per-chunk merge for every fully-received chunk
        — ascending offsets over the same ``tuning.chunk_ranges``
        boundaries as the blocking engine, so the merge order (and
        therefore the result) is bit-identical."""
        op = leg.op
        if op.acc is None:
            return
        itemsize = op.rbuf.dtype.itemsize
        while leg.merged < len(leg.chunks):
            clo, chi = leg.chunks[leg.merged]
            if leg.off < chi * itemsize:
                break
            op.merge_chunk(self._s._comm_stats, op.item.name, clo, chi)
            leg.merged += 1

    # -- completion -----------------------------------------------------
    def _leg_done(self, leg: _Leg) -> None:
        s = self._s
        op = leg.op
        s._comm_stats.add_wire(
            leg.n if leg.dir == "send" else 0,
            leg.n if leg.dir == "recv" else 0,
            leg.busy, chunks=max(1, len(leg.chunks)),
            bucket=op.item.name, peer=leg.peer,
            transport=leg.ch.transport)
        op.pending_legs -= 1
        if op.pending_legs <= 0:
            self._op_done(op)

    def _op_done(self, op: _Op) -> None:
        if op.on_done is not None:
            op.on_done(op)        # may claim op.rbuf (ring carry)
        if op.rbuf is not None:
            self._s._give_buf(op.rbuf)
            op.rbuf = None
        it = op.item
        it.cursor = op.idx + 1
        if it.cursor >= len(it.ops) and not it.resolved:
            # resolve AT COMPLETION (not batch end) so a rolling
            # submit window pipelines: the waiter wakes while the rest
            # of the batch is still on the wire. A later abort round
            # re-runs this collective from its snapshot bit-exactly,
            # so the resolved value stays truthful; only a concurrent
            # read DURING an active recovery can observe the transient
            # restore (documented on CollectiveFuture).
            it.resolved = True
            self._s._comm_stats.async_end(
                it.name, time.perf_counter() - it.t0)
            self._finish(it, value=it.payload)

    # -- shm chunk pumps (ISSUE 17) -------------------------------------
    @staticmethod
    def _leg_settled(leg: _Leg) -> bool:
        """Retirable: every wire byte moved — for a shm pump leg that
        includes owed carrier sync bytes, which must flush before a
        later leg on the same (peer, dir) queue may touch the carrier
        stream (the per-direction protocol order)."""
        return leg.off >= leg.n and (leg.pump is None
                                     or leg.pump.done)

    def _pump_shm(self, leg: _Leg) -> int:
        """Drive one shm engine leg through its nonblocking chunk pump
        (:class:`transport.shm.SendPump`/``RecvPump``). The chunk
        bounds are the SAME per-link schedule the blocking
        ``_chunked_exchange`` derives (``_chunk_for``; shm links pin
        the job default), and each chunk routes ring-vs-carrier by the
        same size rule — so the per-direction wire streams are
        bit-identical to the blocking twin's and a mixed
        engine/blocking pair cannot desync."""
        s = self._s
        if not leg.started:
            self._leg_start(leg)
        pump = leg.pump
        if pump is None:
            # built AFTER _leg_start: an injected send corruption
            # swaps leg.view, and the pump must ship what the fault
            # actually put on the wire
            isz = leg.src.dtype.itemsize
            chunks = leg.chunks or tuning.chunk_ranges(
                leg.src.size, isz, s._chunk_for(leg.peer))
            bounds = [(lo * isz, hi * isz) for lo, hi in chunks]
            cls = (shm_mod.SendPump if leg.dir == "send"
                   else shm_mod.RecvPump)
            pump = leg.pump = cls(leg.ch, leg.view, bounds)
        prev = leg.off
        t0 = time.perf_counter()
        try:
            moved = pump.pump()
        finally:
            leg.busy += time.perf_counter() - t0
        leg.off = pump.off
        if moved:
            leg.last_progress = time.monotonic()
        if leg.dir == "recv" and leg.off > prev:
            if s._audit is not None and s._audit.wire_on:
                # fold arrivals BEFORE any merge mutates the scratch
                leg.op.item.fold(leg.peer, "recv",
                                 leg.view[prev:leg.off],
                                 leg.ch.transport)
            self._merge_ready(leg)
        return moved


def _is_kill(e: BaseException) -> bool:
    from ytk_mp4j_tpu.resilience import faults as fm
    return isinstance(e, fm.FaultKill)


# ----------------------------------------------------------------------
# pure schedule builders — these mirror the blocking engine EXACTLY
# (same partners, same segment windows, same merge boundaries and
# operand order; mp4j-lint R1/R8 discipline: pure functions of the
# job-wide call parameters), which is what makes i*().wait() and the
# blocking twin bit-identical (tests/test_async.py conformance grid).
# ----------------------------------------------------------------------
def _resolved_allreduce_algo(s, arr, lo, hi, operand,
                             algo: str) -> str:
    if algo == "auto":
        return tuning.select_allreduce_algo(
            (hi - lo) * operand.dtype.itemsize, s._n,
            s._algo_small, s._algo_large)
    return algo


def engine_eligible(s, name: str, args: tuple, kwargs: dict) -> bool:
    """Whether a submission may run on the interleaved raw engine.
    This is a LOCAL execution-strategy choice — the wire bytes and
    their per-channel order are identical on the engine and the
    blocking path — so it may consult local facts (contiguity, the
    native-transport build) without any cross-rank agreement."""
    if s._n <= 1 or s._use_twolevel():
        return False
    # shm-paired jobs ride the engine too (ISSUE 17): a leg on a
    # ShmChannel pumps the ring piece/sync-byte schedule chunk-
    # granularly (transport.shm.SendPump/RecvPump) instead of
    # executing the exchange as one blocking step, so the scheduler
    # keeps serving collective k's legs while k+1's ring pieces
    # stream — the interleave-induced cycle that once forced shm
    # submissions inline cannot form against nonblocking pumps.
    if name not in ("allreduce_array", "reduce_scatter_array",
                    "allgather_array", "gather_array"):
        return False
    arr = args[0] if args else None
    operand = args[1] if len(args) > 1 else None
    if operand is None or not getattr(operand, "is_numeric", False) \
            or operand.compress or not s._raw_ok(operand):
        return False
    if not isinstance(arr, np.ndarray) or arr.ndim != 1 \
            or arr.dtype != operand.dtype \
            or not arr.flags.c_contiguous or not arr.flags.writeable:
        return False
    algo = kwargs.get("algo", "auto")
    if name == "allreduce_array":
        if kwargs.get("from_", 0) != 0 or kwargs.get("to") is not None:
            return False
        return _resolved_allreduce_algo(
            s, arr, 0, arr.size, operand, algo) in ("rhd", "ring")
    if name == "reduce_scatter_array":
        resolved = (tuning.select_partitioned_algo(
            arr.nbytes, s._n, s._algo_small, s._algo_large)
            if algo == "auto" else algo)
        return resolved == "ring"
    if name == "allgather_array":
        ranges = kwargs.get("ranges")
        if algo == "ring":
            return True
        if algo != "auto":
            return False
        if ranges is not None:
            contiguous = all(ranges[i][1] == ranges[i + 1][0]
                             for i in range(len(ranges) - 1))
            if not contiguous:
                return True       # auto picks ring for these
            size = (ranges[-1][1] - ranges[0][0]) \
                * operand.dtype.itemsize
        else:
            size = arr.nbytes
        return tuning.select_partitioned_algo(
            size, s._n, s._algo_small, s._algo_large) == "ring"
    return True                   # gather_array: always direct sends


def _rhd_ops(s, item, arr, lo, hi, operator) -> list[_Op]:
    """Recursive halving/doubling, mirroring ``_rhd_allreduce``."""
    n, r = s._n, s._rank
    ops: list[_Op] = []
    p = 1
    while p * 2 <= n:
        p *= 2
    extra = n - p
    if r >= p:                    # folded rank
        fold = r - p
        ops.append(_Op(item, 0, sp=fold, sarr=arr[lo:hi]))
        ops.append(_Op(item, 1, rp=fold, rdst=arr[lo:hi]))
        return ops
    i = 0
    if r < extra:                 # fold partner: merge the extra rank
        ops.append(_Op(item, i, rp=r + p, acc=arr[lo:hi],
                       operator=operator))
        i += 1
    segs = meta.partition_range(lo, hi, p)

    def span(a, b):
        return segs[a][0], segs[b - 1][1]

    vr = r
    dist = p >> 1
    while dist >= 1:              # reduce-scatter by halving
        partner = vr ^ dist
        block0 = (vr // (2 * dist)) * (2 * dist)
        if vr & dist:
            keep = (block0 + dist, block0 + 2 * dist)
            give = (block0, block0 + dist)
        else:
            keep = (block0, block0 + dist)
            give = (block0 + dist, block0 + 2 * dist)
        gs, ge = span(*give)
        ks, ke = span(*keep)
        ops.append(_Op(item, i, sp=partner, sarr=arr[gs:ge],
                       rp=partner, acc=arr[ks:ke], operator=operator))
        i += 1
        dist >>= 1
    dist = 1
    while dist < p:               # allgather by doubling (in place)
        pv = vr ^ dist
        mb0 = (vr // dist) * dist
        tb0 = (pv // dist) * dist
        ms, me = span(mb0, mb0 + dist)
        ts, te = span(tb0, tb0 + dist)
        ops.append(_Op(item, i, sp=pv, sarr=arr[ms:me], rp=pv,
                       rdst=arr[ts:te]))
        i += 1
        dist *= 2
    if r < extra:                 # unfold
        ops.append(_Op(item, i, sp=r + p, sarr=arr[lo:hi]))
    return ops


def _ring_rs_ops(s, item, arr, segs, operator) -> list[_Op]:
    """Pipelined ring reduce-scatter, mirroring
    ``_ring_reduce_scatter``: the received scratch merges the LOCAL
    segment in (``rbuf = op(rbuf, local)``) and becomes the next
    step's carry; the final carry deposits into this rank's segment."""
    n, r = s._n, s._rank
    right, left = (r + 1) % n, (r - 1) % n
    ops: list[_Op] = []
    state: dict = {"carry": None, "carry_buf": None}

    def make_done(last: bool):
        def done(op: _Op):
            rbuf = op.rbuf
            op.rbuf = None        # claimed as the carry, not pooled
            if state["carry_buf"] is not None:
                s._give_buf(state["carry_buf"])
            state["carry"] = rbuf
            state["carry_buf"] = rbuf
            if last:
                ms, me = segs[r]
                arr[ms:me] = state["carry"]
                s._give_buf(state["carry_buf"])
                state["carry"] = None
                state["carry_buf"] = None
        return done

    for step in range(n - 1):
        ss, se = segs[(r - 1 - step) % n]
        ri_s, ri_e = segs[(r - 2 - step) % n]
        local = arr[ri_s:ri_e]

        def sarr(st=state, ss=ss, se=se):
            return st["carry"] if st["carry"] is not None \
                else arr[ss:se]

        ops.append(_Op(item, step, sp=right, sarr=sarr, rp=left,
                       acc=local, operator=operator, ring=True,
                       on_done=make_done(step == n - 2)))
    return ops


def _ring_ag_ops(s, item, arr, segs, base_idx: int = 0) -> list[_Op]:
    """Pipelined ring allgather, mirroring ``_ring_allgather``:
    segments land in place, no merge."""
    n, r = s._n, s._rank
    right, left = (r + 1) % n, (r - 1) % n
    ops: list[_Op] = []
    for step in range(n - 1):
        ss, se = segs[(r - step) % n]
        rs, re = segs[(r - 1 - step) % n]
        ops.append(_Op(item, base_idx + step, sp=right,
                       sarr=arr[ss:se], rp=left, rdst=arr[rs:re]))
    return ops


def _gather_ops(s, item, arr, ranges, root) -> list[_Op]:
    """Rooted gather, mirroring ``gather_array``'s direct sends."""
    n, r = s._n, s._rank
    ops: list[_Op] = []
    if r == root:
        i = 0
        for peer in range(n):
            if peer == root:
                continue
            ps, pe = ranges[peer]
            ops.append(_Op(item, i, rp=peer, rdst=arr[ps:pe]))
            i += 1
    else:
        ps, pe = ranges[r]
        ops.append(_Op(item, 0, sp=root, sarr=arr[ps:pe]))
    return ops
