from ytk_mp4j_tpu.comm.tpu_comm import TpuCommCluster

__all__ = ["TpuCommCluster"]
