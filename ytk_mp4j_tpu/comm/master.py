"""Rendezvous master — the control plane.

The reference runs a master process that slaves connect to: it assigns
ranks, distributes the slave roster (rank -> host:port), serves as the
centralized log sink for ``info()/error()``, coordinates barriers, and
aggregates exit codes at ``close(code)`` (SURVEY.md sections 2, 3a, 3e).

This is that master, rebuilt in Python over the framed-socket transport.
It can run embedded (a thread, for tests and single-host jobs) or as a
CLI: ``python -m ytk_mp4j_tpu.comm.master --port P --slaves N``.

Failure model (ISSUE 5, a deliberate departure from the reference's
fail-stop scope, SURVEY.md section 5): the slave count is still fixed —
no elastic membership — but transient transport faults are recoverable.
The master drives the epoch-fenced abort protocol (resilience.recovery):
an ABORT_REQ from any rank fans out an abort round, all-rank acks gate
the ``abort_go`` release, and unrecoverable states (dead control
connection, stalled round, exhausted retry budget, watchdog-escalated
barrier stall) fan out ONE terminal abort so every surviving rank
raises the same ``Mp4jFatalError`` within its bounded wait.
``MP4J_MAX_RETRIES=0`` restores the reference's exact fail-stop
contract. Rendezvous keeps its optional timeout.

Observability (ISSUE 3): slaves piggyback periodic TELEMETRY heartbeats
(``{progress, stats}``, schema in obs.telemetry) on the control
channel; the master keeps a per-rank table, serves cross-rank skew via
:meth:`Master.cluster_stats`, and turns the paper's worst failure mode
— a silent mismatched-schedule deadlock — into a runtime report: a
slave whose bounded collective wait expires ships a DIAGNOSE, and a
barrier generation stalled past ``stall_timeout`` trips the watchdog;
either way the master logs which ranks trail the cluster's max
collective sequence number, where each laggard last was, and how stale
its heartbeat is. Heartbeats ride the control plane only — they can
never block a data-plane exchange.
"""

from __future__ import annotations

import argparse
import http.server
import json
import secrets
import socket
import sys
import threading
import time

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.obs import audit as audit_mod
from ytk_mp4j_tpu.obs import health as health_mod
from ytk_mp4j_tpu.obs import metrics as metrics_mod
from ytk_mp4j_tpu.obs import postmortem as postmortem_mod
from ytk_mp4j_tpu.obs import telemetry as telemetry_mod
from ytk_mp4j_tpu.resilience import membership as membership_mod
from ytk_mp4j_tpu.transport.channel import Channel
from ytk_mp4j_tpu.transport.tcp import TcpChannel
from ytk_mp4j_tpu.utils import stats as stats_mod
from ytk_mp4j_tpu.utils import tuning

# control-plane message kinds (slave -> master)
REGISTER = "register"
LOG = "log"
BARRIER = "barrier"
CLOSE = "close"
TELEMETRY = "telemetry"   # periodic heartbeat: {progress, stats}
DIAGNOSE = "diagnose"     # a slave's bounded wait expired; report it
ABORT_REQ = "abort_req"   # a collective failed; start an abort round
ABORT_ACK = "abort_ack"   # slave finished tearing down the old epoch
SPARE_PING = "spare_ping"  # an idle warm spare proving liveness
ADOPT_ACK = "adopt_ack"   # a spare finished seeding its adopted rank
MANIFEST = "manifest"     # a survivor's adoption manifest contribution


class _Slot:
    """One connected slave: its channel, a per-channel send lock
    (master->slave pushes may originate on any serve thread), and a
    MUTABLE rank — a shrink round renumbers survivors, and the serve
    thread must attribute every later message to the rank the slave
    currently holds, not the one it registered with (ISSUE 10)."""

    __slots__ = ("rank", "ch", "lock", "dead")

    def __init__(self, rank: int, ch: Channel):
        self.rank = rank
        self.ch = ch
        self.lock = threading.Lock()
        # set when the rank is DECLARED dead while its channel still
        # answers (watchdog escalation): the serve thread must stop
        # attributing this zombie's messages to a rank id that a
        # replacement spare may now legitimately hold
        self.dead = False


class Master:
    """Rank assignment, roster exchange, log sink, barrier, exit codes,
    plus the cluster telemetry table (heartbeats, skew, hang diagnosis)."""

    def __init__(self, slave_num: int, port: int = 0, host: str = "",
                 log_stream=None, timeout: float | None = 120.0,
                 handshake_timeout: float | None = 5.0,
                 stall_timeout: float | None = 60.0,
                 dead_rank_secs: float | None = None,
                 metrics_port: int | None = None,
                 postmortem_dir: str | None = None,
                 sink_dir: str | None = None,
                 elastic: str | None = None,
                 spares: int | None = None,
                 adopt_secs: float | None = None,
                 health: bool | None = None):
        """``timeout`` bounds the whole rendezvous; ``handshake_timeout``
        bounds each accepted connection's registration message, so one
        stray dial-in stalls rendezvous briefly instead of consuming the
        entire budget while real slaves queue behind it.
        ``stall_timeout`` arms the barrier watchdog: a barrier
        generation with some ranks still missing after this many
        seconds gets a hang diagnosis logged (once per generation);
        ``None`` disables the watchdog.

        ``dead_rank_secs`` (None reads ``MP4J_DEAD_RANK_SECS``;
        ``float("inf")`` disables escalation, restoring the PR-3
        log-only watchdog) is the ESCALATION threshold (ISSUE 5): a barrier generation or an
        abort round still incomplete after this many seconds means a
        rank is permanently gone or permanently diverged, and the
        watchdog escalates from the PR-3 log-only diagnosis to a
        terminal abort fan-out — every surviving rank raises the same
        clean error instead of relying on its local timeout. It is
        deliberately much larger than ``stall_timeout``: the diagnosis
        is cheap and reversible, declaring a rank dead is neither.

        ``metrics_port`` (ISSUE 6; None reads ``MP4J_METRICS_PORT``,
        which unset keeps the endpoint off) serves the live metrics
        plane over plain HTTP on the CONTROL plane only: ``/metrics``
        is Prometheus text format, ``/metrics.json`` the same document
        as JSON. ``0`` binds an ephemeral port; the bound port is
        ``self.metrics_port``. ``postmortem_dir`` (None reads
        ``MP4J_POSTMORTEM_DIR``; empty disables) makes a terminal
        abort also write the flight recorder's cluster manifest.
        ``sink_dir`` (ISSUE 9; None reads ``MP4J_SINK_DIR`` gated by
        ``MP4J_SINK``; empty disables) names the job's durable-sink
        root in that manifest so ``mp4j-scope postmortem`` joins the
        full-job segment history — the same constructor seam as
        ``postmortem_dir``.

        ``elastic`` (ISSUE 10; None reads ``MP4J_ELASTIC``, default
        ``off``) selects the elastic-membership mode: ``off`` keeps
        the pre-elastic contract (a dead rank is a job-wide
        ``Mp4jFatalError``), ``replace`` adopts a warm spare into the
        dead rank's id at the next epoch (bit-exact continuation),
        ``shrink`` renumbers the survivors and continues at n-1.
        ``spares`` (None reads ``MP4J_SPARES``) is how many warm-spare
        registrations rendezvous waits for before the job starts;
        spares may also register later, mid-job. ``adopt_secs`` (None
        reads ``MP4J_ADOPT_SECS``) bounds each adoption handshake
        before the next spare is tried.

        ``health`` (ISSUE 12; None reads ``MP4J_HEALTH``, default on)
        arms the streaming health engine (:mod:`ytk_mp4j_tpu.obs.
        health`): every heartbeat fold also feeds per-rank baselines
        and the detector set, verdict transitions are pushed to the
        subject rank's recovery log + durable sink and exported on
        ``/metrics``, and :meth:`health_status` is the operator hook a
        future autoscaler calls — this plane recommends, it never
        acts."""
        self.slave_num = slave_num
        self.timeout = timeout
        self.handshake_timeout = handshake_timeout
        self.stall_timeout = stall_timeout
        self.dead_rank_secs = tuning.dead_rank_secs(dead_rank_secs)
        # elastic knobs validated BEFORE any socket binds (a knob
        # conflict must not leak a bound listener out of a failed
        # constructor — the metrics-server precedent)
        self.elastic = tuning.elastic_mode(elastic)
        self._spares_expected = tuning.spares(spares)
        self._adopt_secs = tuning.adopt_secs(adopt_secs)
        self.log_stream = log_stream if log_stream is not None else sys.stderr
        # log sink config: validated once at construction (a typo'd
        # MP4J_LOG_LEVEL fails the job here, not silently mid-run)
        self._min_level = tuning.LOG_LEVELS[tuning.log_level()]
        self._rank_width = max(1, len(str(max(slave_num - 1, 0))))
        # job id (ISSUE 7): rides the rendezvous reply and namespaces
        # every shm segment this job's peer pairs create, so two jobs
        # on one host can never collide on a segment name
        self.job_id = secrets.token_hex(4)
        # rendezvous listen socket — sanctioned raw-socket site: the
        # master IS the control plane the transport SPI is negotiated
        # over (mp4j-lint R12 baseline)
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host or "0.0.0.0", port))
        self._server.listen(slave_num * 2)
        self.port = self._server.getsockname()[1]
        self._slots: list[_Slot] = []           # by CURRENT rank
        self._exit_codes: dict[int, int] = {}
        self._barrier_waiting: dict[int, list[int]] = {}  # gen -> ranks
        self._barrier_since: dict[int, float] = {}        # gen -> mono ts
        # highest generation ever released: an adopted joiner seeded
        # from a manifest sampled a beat early may re-send an already-
        # released generation — release it back to that rank alone
        # instead of opening a ghost generation nobody else will join
        # (ISSUE 10)
        self._barrier_max_released = -1
        self._diagnosed_gens: set[int] = set()
        self._diag_incident_seq: int | None = None  # debounce key
        # recovery protocol state (ISSUE 5)
        self._abort_epoch = 0                   # highest epoch fanned out
        self._abort_acks: set[int] = set()      # ranks acked current round
        self._abort_progress: dict[int, tuple[int, bool]] = {}
        self._abort_since: float | None = None  # mono ts of open round
        self._departed: dict[int, str] = {}     # rank -> why it left
        self._fatal_msg: str | None = None      # terminal abort, once
        # elastic membership (ISSUE 10): warm-spare pool + the open
        # round's membership extension (kind/dead/manifest/adoptions).
        # All guarded by self._lock like the abort state.
        self._membership = membership_mod.MembershipLog(self.elastic)
        self._spare_pool: list[membership_mod.SpareRecord] = []
        self._spare_seq = 0                     # spares ever registered
        self._spare_threads: list[threading.Thread] = []
        self._serve_threads: list[threading.Thread] = []
        self._roster: list[tuple] = []          # current (host, port, fp)
        self._round_kind: str | None = None     # None/'abort'/mode
        self._round_dead: dict[int, str] = {}   # this round's casualties
        self._round_why = ""                    # first casualty's message
        self._round_manifest: dict | None = None
        self._round_manifest_from: int | None = None
        self._round_seq: int | None = None      # joiner resume ordinal
        self._round_adoptions: dict[int, membership_mod.SpareRecord] = {}
        self._round_adopted: dict[int, membership_mod.SpareRecord] = {}
        # rank -> last heartbeat: progress fields + stats + arrival time
        self._telemetry: dict[int, dict] = {}
        # audit plane (ISSUE 8): folds heartbeat digest-record deltas
        # and flags cross-rank divergences (obs.audit.ClusterAuditor);
        # passive — it only ever sees records when slaves run
        # MP4J_AUDIT=verify|capture
        self._auditor = audit_mod.ClusterAuditor(slave_num)
        # health plane (ISSUE 12): the streaming verdict engine,
        # folded right next to the auditor in _record_telemetry; None
        # when disabled so every fold site pays one attribute check
        self._hb_secs = tuning.heartbeat_secs()
        self._health: health_mod.HealthEngine | None = (
            health_mod.HealthEngine(
                slave_num,
                window=tuning.health_window(),
                dominator_ordinals=tuning.health_dominator_ordinals(),
                drift_pct=tuning.health_drift_pct(),
                hb_secs=self._hb_secs)
            if tuning.health_enabled(health) else None)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.final_code: int | None = None
        # -- live metrics plane (ISSUE 6) -------------------------------
        self._postmortem_dir = (tuning.postmortem_dir()
                                if postmortem_dir is None
                                else str(postmortem_dir))
        # durable-sink root (ISSUE 9): the master never writes
        # segments itself, but the manifest records where the ranks'
        # sinks are so `mp4j-scope postmortem` can join full-job
        # history into the report
        if sink_dir is None:
            self._sink_dir = (tuning.sink_dir()
                              if tuning.sink_enabled() else "")
        else:
            self._sink_dir = str(sink_dir)
        self._metrics_window = tuning.metrics_window_secs()
        # per-rank + cluster rate rings, fed on every heartbeat fold;
        # cluster totals are maintained incrementally (O(1 rank) per
        # beat), not re-summed across the fleet under the lock
        self._rank_windows: dict[int, metrics_mod.RateWindow] = {}
        self._rank_totals: dict[int, dict[str, float]] = {}
        self._cluster_totals: dict[str, float] = {}
        # cluster histogram/counter aggregate, folded incrementally
        # from each heartbeat's metrics_delta (never re-summed across
        # the fleet at scrape time)
        self._cluster_metrics: dict = {"counters": {}, "gauges": {},
                                       "histograms": {}}
        self._cluster_window = metrics_mod.RateWindow(
            self._metrics_window)
        self._metrics_server: http.server.ThreadingHTTPServer | None = None
        self.metrics_port: int | None = None
        want_port = tuning.metrics_port(override=metrics_port)
        if want_port is not None:
            try:
                self._start_metrics_server(host, want_port)
            except BaseException:
                # don't leak the already-bound listeners (data plane,
                # and the metrics socket if it bound before the fail)
                # out of a failed constructor — a retry Master on the
                # same explicit port would hit EADDRINUSE until GC
                self._stop_metrics_server()
                self._server.close()
                raise

    # ------------------------------------------------------------------
    def serve(self) -> int:
        """Run rendezvous then the control loop; returns aggregate exit
        code (0 iff every slave closed with 0)."""
        try:
            return self._serve()
        finally:
            # every listener must die with serve() on EVERY path — a
            # rendezvous timeout raising past a leaked HTTP server or
            # a still-bound data-plane socket would hold the port
            # against the retry Master
            self._server.close()
            self._write_postmortem_manifest()
            self._stop_metrics_server()

    def _serve(self) -> int:
        self._rendezvous()
        with self._lock:
            for slot in self._slots:
                t = threading.Thread(target=self._serve_slave,
                                     args=(slot,), daemon=True,
                                     name=f"master-slave{slot.rank}")
                t.start()
                self._serve_threads.append(t)
        # late spare registrations (ISSUE 10): a replacement spare may
        # dial in any time after the job started; the rendezvous
        # listener stays open for exactly that
        spare_accept = threading.Thread(target=self._spare_accept_loop,
                                        daemon=True,
                                        name="mp4j-spare-accept")
        spare_accept.start()
        # the watchdog now also drives the dead-rank ESCALATION
        # (ISSUE 5): it must run even with stall_timeout=None —
        # disabling the diagnosis must not silently disable the
        # terminal abort that bounds every recovery wait. Only when
        # BOTH functions are off (dead_rank_secs=inf too) is there
        # nothing it could ever do — skip the thread instead of
        # waking at 1 Hz for the job's lifetime
        watchdog = None
        if (self.stall_timeout is not None
                or self.dead_rank_secs != float("inf")):
            watchdog = threading.Thread(target=self._watchdog_loop,
                                        daemon=True,
                                        name="mp4j-watchdog")
            watchdog.start()
        try:
            # the list GROWS when a spare is adopted (its serve thread
            # becomes the rank's), so re-read it until drained
            i = 0
            while True:
                with self._lock:
                    if i >= len(self._serve_threads):
                        break
                    t = self._serve_threads[i]
                i += 1
                t.join()
        finally:
            self._stop.set()
            # unadopted spares idle in a blocking recv: release them
            # so their constructors raise Mp4jSpareReleased instead of
            # waiting out a timeout against a finished job
            self._release_spares(
                self._fatal_msg or "job completed without adopting "
                "this spare")
        if watchdog is not None:
            watchdog.join(2.0)
        # serve()'s finally closes the listener, refreshes the
        # flight-recorder manifest with the FINAL table (the slaves'
        # fatal-path telemetry flushes landed after the fan-out-time
        # write) and stops the endpoint
        codes = [self._exit_codes.get(r, 1) for r in range(self.slave_num)]
        self.final_code = max(codes) if codes else 0
        return self.final_code

    def serve_in_thread(self) -> "Master":
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name="mp4j-master")
        self._thread.start()
        return self

    def join(self, timeout: float | None = None):
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------------
    def _rendezvous(self):
        """Accept slave registrations; assign ranks in registration order
        (pinned free choice — the reference's exact rule is unverified);
        broadcast the roster to all. Warm spares (``spare: True`` in the
        REGISTER payload, ISSUE 10) are parked in the spare pool instead
        of claiming a rank; rendezvous additionally waits for
        ``spares`` of them so a job configured with spares starts with
        its pool warm."""
        deadline = (None if self.timeout is None
                    else time.monotonic() + self.timeout)
        pending = []  # (channel, (host, listen_port, fp))
        self._server.settimeout(1.0)
        while (len(pending) < self.slave_num
               or len(self._spare_pool) < self._spares_expected):
            if deadline is not None and time.monotonic() > deadline:
                got = [hp for _, hp in pending]
                raise Mp4jError(
                    f"rendezvous timeout: {len(pending)}/{self.slave_num} "
                    f"slaves and {len(self._spare_pool)}/"
                    f"{self._spares_expected} spares registered (heard "
                    f"from: {got or 'none'} — the missing slaves never "
                    "dialed in)")
            try:
                sock, addr = self._server.accept()
            except socket.timeout:
                continue
            # sanctioned channel-construction site: rendezvous wraps
            # the just-accepted control connection (R12 baseline)
            ch = TcpChannel(sock)
            # bound the registration handshake: a stray connection that
            # never sends must neither hang rendezvous (no timeout) nor
            # consume the whole budget while real slaves queue behind it
            remaining = (None if deadline is None
                         else max(0.1, deadline - time.monotonic()))
            bounds = [t for t in (remaining, self.handshake_timeout)
                      if t is not None]
            ch.set_timeout(min(bounds) if bounds else None)
            try:
                # anything a hostile/broken dial-in can do — reset,
                # garbage frame, non-tuple payload, malformed REGISTER
                # body, timeout — must not kill rendezvous for the
                # real slaves, so the whole decode stays in this try
                kind, payload = ch.recv()
                ok = kind == REGISTER and isinstance(payload, dict)
                listen_port = int(payload["listen_port"]) if ok else 0
                host = str(payload.get("host") or addr[0]) if ok else ""
                # host fingerprint (ISSUE 7): opaque token two slaves
                # share iff they can attach each other's shm segments;
                # "" means the slave opted out (MP4J_SHM=0)
                fp = str(payload.get("fp") or "") if ok else ""
                is_spare = bool(payload.get("spare")) if ok else False
            except Exception:
                ok = False
            if not ok:
                ch.close()
                continue
            ch.set_timeout(None)  # control plane is fail-stop from here
            if is_spare:
                self._register_spare(ch, (host, listen_port, fp))
                continue
            if len(pending) >= self.slave_num:
                # every rank is claimed; rendezvous only stays open
                # for the spares it is still waiting on — a surplus
                # non-spare dial-in must not mint an out-of-range rank
                ch.close()
                continue
            pending.append((ch, (host, listen_port, fp)))
        roster = [hp for _, hp in pending]
        self._roster = roster
        for rank, (ch, _) in enumerate(pending):
            ch.send_obj({"rank": rank, "roster": roster,
                         "job": self.job_id})
            self._slots.append(_Slot(rank, ch))

    def _serve_slave(self, slot: _Slot):
        ch = slot.ch
        try:
            while True:
                kind, payload = ch.recv()
                if slot.dead:
                    # a zombie: this rank was declared dead and its id
                    # may already belong to a replacement — drop the
                    # connection instead of laundering its messages
                    ch.close()
                    return
                # the CURRENT rank, re-read per message: a shrink round
                # renumbers survivors mid-job (ISSUE 10)
                rank = slot.rank
                if kind == LOG:
                    self._log(rank, payload["level"], payload["msg"])
                elif kind == BARRIER:
                    self._barrier(slot, payload["gen"])
                elif kind == TELEMETRY:
                    self._record_telemetry(rank, payload)
                elif kind == DIAGNOSE:
                    self._handle_diagnose(rank, payload)
                elif kind == ABORT_REQ:
                    self._handle_abort_req(rank, payload)
                elif kind == ABORT_ACK:
                    self._handle_abort_ack(rank, payload)
                elif kind == MANIFEST:
                    self._handle_manifest(rank, payload)
                elif kind == CLOSE:
                    code = payload["code"]
                    with self._lock:
                        already_dead = rank in self._departed
                        if not already_dead:
                            self._exit_codes[rank] = code
                        live_left = (set(range(self.slave_num))
                                     - set(self._departed)
                                     - set(self._exit_codes))
                    with slot.lock:
                        ch.send_obj("closed")
                    ch.close()
                    if already_dead:
                        # this rank's death is already being handled
                        # (declared dead, possibly replaced): its late
                        # close must not re-kill the job
                        return
                    self._mark_departed(
                        rank, f"closed with code {code}")
                    if code != 0 and live_left:
                        # a nonzero close is a defect report; peers
                        # blocked on this rank's data would otherwise
                        # only find out at their own (long) timeouts.
                        # Deliberately NOT an elastic trigger: the
                        # process defected with its own error — its
                        # state is suspect, replacement would launder a
                        # defect into "recovery"
                        self._fatal_abort(
                            f"rank {rank} exited with code {code} "
                            "before the job completed; aborting the "
                            "job")
                    return
                else:
                    self._log(rank, "ERROR", f"unknown message {kind!r}")
        except Exception as e:
            # a dead slave (reset, EOF, corrupt frame) marks a nonzero
            # exit code and the master keeps serving the others — but
            # no longer silently (ISSUE 5): a lost connection means the
            # process died without closing, so the job cannot complete
            # under MP4J_ELASTIC=off. The elastic modes (ISSUE 10)
            # dispatch through _on_rank_dead instead: replacement from
            # a warm spare, or a contiguous shrink of the survivors.
            if slot.dead:
                # this rank was ALREADY declared dead (its channel
                # erroring now is the expected aftermath) — a shrink
                # may meanwhile have renumbered a healthy survivor
                # into slot.rank, and a fresh declaration here would
                # kill THAT rank (found by the ISSUE 12 chaos loop:
                # the health-alert dispatch shifted this race's
                # timing, but the hole predates it)
                self._log(slot.rank, "INFO",
                          f"declared-dead rank's channel closed: {e!r}")
                return
            rank = slot.rank
            self._log(rank, "ERROR", f"slave connection lost: {e!r}")
            with self._lock:
                self._exit_codes.setdefault(rank, 1)
            self._on_rank_dead(
                rank, f"connection lost ({e!r})",
                f"rank {rank} is dead (connection lost: {e!r}); "
                "aborting the job")

    # -- recovery protocol (ISSUE 5) ------------------------------------
    def _send_to(self, rank: int, obj) -> None:
        """Push one control message to a slave; a rank that dies while
        we push is marked departed, never crashes a serve thread."""
        try:
            with self._slots[rank].lock:
                self._slots[rank].ch.send_obj(obj)
        except (Mp4jError, OSError):
            self._mark_departed(rank, "unreachable on push")

    def _live_ranks(self) -> set[int]:
        with self._lock:
            return set(range(self.slave_num)) - set(self._departed)

    def _mark_departed(self, rank: int, why: str) -> None:
        with self._lock:
            self._departed.setdefault(rank, why)
            pending = self._abort_since is not None
        if pending:
            # an open abort round can never complete without this rank
            # — terminal under MP4J_ELASTIC=off; the elastic modes
            # extend the round into a membership round instead
            self._on_rank_dead(
                rank, why,
                f"rank {rank} left during recovery ({why}); "
                "aborting the job")

    def _handle_abort_req(self, rank: int, payload: dict) -> None:
        if payload.get("fatal"):
            self._fatal_abort(
                f"terminal abort requested by rank {rank}: "
                f"{payload.get('error')}")
            return
        target = int(payload.get("epoch", 0)) + 1
        with self._lock:
            if target <= self._abort_epoch:
                dup = True      # round already fanned out; debounce
            else:
                dup = False
                self._open_round_locked(target)
                dead = dict(self._departed)
        self._log(rank, "ERROR",
                  f"collective '{payload.get('collective')}' failed "
                  f"(epoch {payload.get('epoch')}): "
                  f"{payload.get('error')}")
        if dup:
            return
        if dead:
            msg = (f"cannot recover: rank(s) {sorted(dead)} already gone "
                   f"({'; '.join(f'{r}: {w}' for r, w in sorted(dead.items()))})")
            if self.elastic == "off":
                self._fatal_abort(msg)
                return
            # elastic (ISSUE 10): the departed ranks become this
            # round's casualties — the round just opened fans out
            # below, then the membership machinery takes over
            self._log("M", "WARN",
                      f"abort round -> epoch {target}: tearing down "
                      f"the data plane on all surviving ranks")
            for r in sorted(self._live_ranks()):
                self._send_to(r, ("abort", target))
            self._begin_membership(dead, msg)
            return
        self._log("M", "WARN",
                  f"abort round -> epoch {target}: tearing down the "
                  f"data plane on all {self.slave_num} ranks")
        for r in sorted(self._live_ranks()):
            self._send_to(r, ("abort", target))

    def _open_round_locked(self, target: int) -> None:
        """Reset the round state for a new abort round (caller holds
        the lock and has verified ``target`` advances the epoch)."""
        self._abort_epoch = target
        self._abort_acks = set()
        self._abort_progress = {}
        self._abort_since = time.monotonic()
        self._round_kind = "abort"
        self._round_dead = {}
        self._round_why = ""
        self._round_manifest = None
        self._round_manifest_from = None
        self._round_seq = None
        self._round_adoptions = {}
        self._round_adopted = {}

    def _handle_abort_ack(self, rank: int, payload: dict) -> None:
        with self._lock:
            if int(payload.get("epoch", 0)) != self._abort_epoch:
                return          # ack for a stale round
            self._abort_acks.add(rank)
            self._abort_progress[rank] = (int(payload.get("seq", 0)),
                                          bool(payload.get("inflight")))
        self._try_advance_round()

    def _handle_manifest(self, rank: int, payload: dict) -> None:
        """A survivor's adoption-manifest contribution (ISSUE 10):
        pinned keycodec vocabularies + its progress/barrier position."""
        with self._lock:
            if (int(payload.get("epoch", 0)) != self._abort_epoch
                    or self._round_kind != "replace"):
                return          # stale round, or mode changed
            self._round_manifest = payload
            self._round_manifest_from = rank
        self._try_advance_round()

    # -- elastic membership (ISSUE 10) ----------------------------------
    def _on_rank_dead(self, rank: int, why: str, fatal_msg: str) -> None:
        """Central dead-rank dispatch. ``fatal_msg`` is EXACTLY the
        message the pre-elastic master fanned out — used verbatim when
        elastic membership is off (the MP4J_ELASTIC=off contract is
        bit-for-bit the old behavior) or cannot help."""
        with self._lock:
            already = self._fatal_msg is not None
            pending = self._abort_since is not None
            # the health plane's DEAD verdict rides the SAME liveness
            # decision, never a second opinion (ISSUE 12)
            dead_alerts = (self._health.note_dead(rank, why)
                           if self._health is not None else [])
        self._dispatch_health_alerts(dead_alerts)
        if self.elastic == "off" or already:
            with self._lock:
                self._departed.setdefault(rank, why)
            if pending:
                # pre-elastic precedence: an open abort round can
                # never complete without this rank, and THAT message
                # is the one the old _mark_departed fanned out first
                self._fatal_abort(
                    f"rank {rank} left during recovery ({why}); "
                    "aborting the job")
            self._fatal_abort(fatal_msg)   # debounced if above fired
            return
        self._begin_membership({rank: why}, fatal_msg)

    def _begin_membership(self, dead: dict[int, str],
                          fatal_msg: str) -> None:
        """Open (or extend) a membership round for the newly dead
        ranks: fan out the abort if no round is open, upgrade the
        round's kind to the elastic mode, request the adoption
        manifest (replace), and push a terminal notice to any declared-
        dead rank whose control channel still answers (a watchdog-
        declared straggler must learn it was replaced, not hang)."""
        notify: list[tuple[_Slot, Channel]] = []
        fan_abort = False
        manifest_req: int | None = None
        fatal: str | None = None
        with self._lock:
            if self._fatal_msg is not None:
                return
            mode = self.elastic
            fresh = {r: w for r, w in dead.items()
                     if r not in self._round_dead}
            for r, w in dead.items():
                self._departed.setdefault(r, w)
            if self._abort_since is None:
                self._open_round_locked(self._abort_epoch + 1)
                fan_abort = True
            self._round_kind = mode
            for r, w in fresh.items():
                self._round_dead[r] = w
                if not self._round_why:
                    self._round_why = fatal_msg
                slot = (self._slots[r]
                        if 0 <= r < len(self._slots) else None)
                if slot is not None:
                    slot.dead = True
                    notify.append((slot, slot.ch))
            live = set(range(self.slave_num)) - set(self._departed)
            if not live:
                fatal = fatal_msg + "; no surviving rank left"
            elif mode == "replace":
                avail = sum(1 for s in self._spare_pool
                            if s.alive and s.adopting_rank is None)
                if avail < (len(self._round_dead)
                            - len(self._round_adopted)
                            - len(self._round_adoptions)):
                    # today's clean Mp4jFatalError: elasticity was
                    # requested but the pool cannot cover the loss
                    fatal = (fatal_msg
                             + "; no warm spare available to replace "
                             f"rank(s) {sorted(self._round_dead)}")
                elif (self._round_manifest is None
                        and (self._round_manifest_from is None
                             or self._round_manifest_from not in live)):
                    manifest_req = min(live)
                    self._round_manifest_from = manifest_req
            target = self._abort_epoch
        if fatal is not None:
            self._fatal_abort(fatal)
            return
        for slot, ch in notify:
            # best-effort: the rank was DECLARED dead, but a merely
            # wedged process should still raise the same clean error
            try:
                with slot.lock:
                    ch.send_obj(("abort_fatal", fatal_msg))
            except (Mp4jError, OSError):
                pass
        if dead:
            self._log(
                "M", "WARN",
                f"membership round ({mode}) -> epoch {target}: "
                f"rank(s) {sorted(dead)} declared dead "
                f"({'; '.join(f'{r}: {w}' for r, w in sorted(dead.items()))})")
        if fan_abort:
            for r in sorted(self._live_ranks()):
                self._send_to(r, ("abort", target))
        if manifest_req is not None:
            self._send_to(manifest_req, ("manifest_req", target))
        self._try_advance_round()

    def _next_spare_locked(self):
        for rec in self._spare_pool:
            if rec.alive and rec.adopting_rank is None:
                return rec
        return None

    def _try_advance_round(self) -> None:
        """Evaluate the open round against its completion condition and
        take the next step: release a plain abort round, start spare
        adoptions, or finalize a membership round. Re-entered whenever
        an input lands — an ack, a departure, the manifest, an adopt
        ack, a spare death."""
        adopts: list[tuple[int, object, dict]] = []
        fatal: str | None = None
        release = None
        with self._lock:
            if self._abort_since is None or self._fatal_msg is not None:
                return
            live = set(range(self.slave_num)) - set(self._departed)
            if not live or not live <= self._abort_acks:
                return
            kind = self._round_kind or "abort"
            epoch = self._abort_epoch
            progress = {r: self._abort_progress.get(r, (0, False))
                        for r in sorted(live)}
            mixed = self._mixed_progress(progress)
            if mixed is not None:
                fatal = mixed
            elif kind == "abort":
                self._abort_since = None
                self._round_kind = None
                release = ("abort", epoch, None, sorted(live), [], ())
            elif kind == "replace":
                if self._round_manifest is not None:
                    if self._round_seq is None:
                        self._round_seq = membership_mod.joiner_seq(
                            progress)
                    need = [r for r in sorted(self._round_dead)
                            if r not in self._round_adoptions]
                    for r in need:
                        rec = self._next_spare_locked()
                        if rec is None:
                            fatal = (self._round_why
                                     + "; no warm spare available to "
                                     f"replace rank {r}")
                            break
                        rec.adopting_rank = r
                        rec.adopt_since = time.monotonic()
                        self._round_adoptions[r] = rec
                    if fatal is None:
                        man = self._round_manifest
                        repl = {r2: rec2.entry for r2, rec2
                                in self._round_adoptions.items()}
                        roster = membership_mod.swap_roster(
                            self._roster, repl)
                        for r in need:
                            rec = self._round_adoptions[r]
                            adopts.append((r, rec, {
                                "rank": r, "epoch": epoch,
                                "roster": roster, "job": self.job_id,
                                "seq": self._round_seq,
                                # the donor's CommStats position (it
                                # counts nested collectives the
                                # recovery ordinal does not) keeps the
                                # joiner's heartbeat seq out of the
                                # skew table's laggard column
                                "stats_seq": int(man.get(
                                    "stats_seq", self._round_seq)),
                                "barrier_gen": int(
                                    man.get("barrier_gen", 0)),
                                "vocab": man.get("vocab") or {},
                                "watermark":
                                    self._auditor.verified_seq,
                                "why": self._round_dead.get(r, ""),
                            }))
                        if (not adopts and set(self._round_dead)
                                <= set(self._round_adopted)):
                            release = self._finalize_replace_locked(
                                epoch, live)
            elif kind == "shrink":
                release = self._finalize_shrink_locked(epoch)
        if fatal is not None:
            self._fatal_abort(fatal)
            return
        for r, rec, info in adopts:
            self._log("M", "WARN",
                      f"adopting spare #{rec.idx} into rank {r} "
                      f"(epoch {epoch}, resume seq {info['seq']})")
            self._send_spare(rec, ("adopt", info))
        if release is None:
            return
        kind, epoch, info, targets, extra_lines, release_gens = release
        for line in extra_lines:
            self._log("M", "ERROR", line)
        if kind == "abort":
            self._log("M", "WARN",
                      f"abort round complete: releasing epoch {epoch} "
                      f"to all ranks")
            for r in targets:
                self._send_to(r, ("abort_go", epoch))
        elif kind == "replace":
            self._log("M", "WARN",
                      f"membership round complete: rank(s) "
                      f"{sorted(info['replaced'])} replaced from warm "
                      f"spares; releasing epoch {epoch}")
            for r in targets:
                self._send_to(r, ("abort_go", epoch, info))
        elif kind == "shrink":
            self._log("M", "WARN",
                      f"membership round complete: shrunk to "
                      f"{self.slave_num} rank(s) "
                      f"(dropped {info['shrink']['departed']}); "
                      f"releasing epoch {epoch}")
            for r in targets:
                self._send_to(r, ("abort_go", epoch, info))
            for gen in release_gens:
                for r in range(self.slave_num):
                    self._send_to(r, ("barrier_release", gen))

    def _finalize_replace_locked(self, epoch: int, live: set[int]):
        """All survivors acked, every casualty's spare acked its
        adoption: swap the roster, resurrect the replaced ranks and
        compose the go message (caller holds the lock and fans out)."""
        repl = {r: rec.entry for r, rec in self._round_adopted.items()}
        self._roster = membership_mod.swap_roster(self._roster, repl)
        joiners = sorted(self._round_adopted)
        extra_lines: list[str] = []
        for r in joiners:
            rec = self._round_adopted[r]
            self._departed.pop(r, None)
            self._exit_codes.pop(r, None)
            self._membership.note_replace(
                r, epoch, rec.idx, self._round_dead.get(r, ""))
            extra_lines.extend(
                self._auditor.note_replacement(
                    r, self._round_seq or 0))
            if self._health is not None:
                # the joiner starts HEALTHY with fresh baselines; the
                # reset alert is informational (the DEAD alert already
                # reached the durable sinks)
                extra_lines.extend(
                    "health: " + health_mod.format_alert(ev)
                    for ev in self._health.note_replacement(r))
        info = {"replaced": joiners, "roster": self._roster,
                "epoch": epoch}
        targets = sorted(live)
        self._abort_since = None
        self._round_kind = None
        self._round_dead = {}
        self._round_adoptions = {}
        self._round_adopted = {}
        self._round_manifest = None
        self._round_manifest_from = None
        self._round_seq = None
        return ("replace", epoch, info, targets, extra_lines, ())

    def _finalize_shrink_locked(self, epoch: int):
        """All survivors acked a shrink round: renumber them
        contiguously, rebuild every rank-keyed table under the new
        numbering, and compose the go message (caller holds the lock
        and fans out)."""
        dead = set(self._departed)
        mapping = membership_mod.shrink_mapping(self.slave_num, dead)
        new_roster = membership_mod.shrink_roster(self._roster, mapping)
        dead_list = sorted(dead)
        new_slots: list = [None] * len(mapping)
        for old, new in mapping.items():
            slot = self._slots[old]
            slot.rank = new
            new_slots[new] = slot
        self._slots = new_slots
        self._roster = new_roster
        self.slave_num = len(mapping)
        self._rank_width = max(1, len(str(max(self.slave_num - 1, 0))))
        self._exit_codes = {mapping[r]: c for r, c
                            in self._exit_codes.items() if r in mapping}
        self._telemetry = {mapping[r]: t for r, t
                           in self._telemetry.items() if r in mapping}
        self._rank_windows = {mapping[r]: w for r, w
                              in self._rank_windows.items()
                              if r in mapping}
        self._rank_totals = {mapping[r]: t for r, t
                             in self._rank_totals.items() if r in mapping}
        self._departed = {}
        self._abort_progress = {}
        self._auditor.note_shrink(self.slave_num, mapping)
        if self._health is not None:
            self._health.note_shrink(self.slave_num, mapping)
        self._membership.note_shrink(dead_list, mapping, epoch,
                                     self._round_why)
        # pending barriers renumber too; one now-complete generation
        # (every survivor already arrived, only the dead were missing)
        # releases on the way out
        release_gens = []
        for gen, ranks in list(self._barrier_waiting.items()):
            self._barrier_waiting[gen] = [
                mapping[r] for r in ranks if r in mapping]
            if len(self._barrier_waiting[gen]) == self.slave_num:
                release_gens.append(gen)
                self._barrier_max_released = max(
                    self._barrier_max_released, gen)
                del self._barrier_waiting[gen]
                self._barrier_since.pop(gen, None)
        info = {"shrink": {"roster": new_roster, "ranks": mapping,
                           "departed": dead_list, "epoch": epoch}}
        targets = sorted(mapping.values())
        self._abort_since = None
        self._round_kind = None
        self._round_dead = {}
        self._round_manifest = None
        self._round_manifest_from = None
        self._round_seq = None
        return ("shrink", epoch, info, targets, [], release_gens)

    # -- warm spares (ISSUE 10) -----------------------------------------
    def _register_spare(self, ch: Channel, entry: tuple) -> None:
        """Park a warm-spare registration: ack it, pool it, and start
        its serve thread (pings until adopted)."""
        with self._lock:
            idx = self._spare_seq
            self._spare_seq += 1
            rec = membership_mod.SpareRecord(idx, ch, entry)
            self._spare_pool.append(rec)
        try:
            ch.send_obj({"spare": idx, "job": self.job_id})
        except (Mp4jError, OSError):
            self._spare_gone(rec, "died during registration")
            return
        t = threading.Thread(target=self._serve_spare, args=(rec,),
                             daemon=True, name=f"master-spare{idx}")
        with self._lock:
            self._spare_threads.append(t)
        t.start()
        self._log("M", "INFO",
                  f"warm spare #{idx} registered "
                  f"({entry[0]}:{entry[1]})")

    def _spare_accept_loop(self) -> None:
        """Post-rendezvous listener: only spare registrations are
        accepted mid-job (a late non-spare dial-in has no rank to
        claim)."""
        while not self._stop.is_set():
            try:
                sock, addr = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return          # listener closed with serve()
            ch = TcpChannel(sock)
            ch.set_timeout(self.handshake_timeout)
            try:
                kind, payload = ch.recv()
                ok = (kind == REGISTER and isinstance(payload, dict)
                      and bool(payload.get("spare")))
                entry = ((str(payload.get("host") or addr[0]),
                          int(payload["listen_port"]),
                          str(payload.get("fp") or ""))
                         if ok else None)
            except Exception:
                ok = False
            if not ok:
                ch.close()
                continue
            ch.set_timeout(None)
            self._register_spare(ch, entry)

    def _serve_spare(self, rec) -> None:
        """Read one spare's control channel: liveness pings until an
        adoption completes — then this THREAD becomes the adopted
        rank's serve thread (the channel is the same object; only its
        role changes)."""
        slot = None
        try:
            while True:
                kind, payload = rec.ch.recv()
                if kind == SPARE_PING:
                    rec.last_ping = time.monotonic()
                elif kind == ADOPT_ACK:
                    slot = self._finish_adoption(rec)
                    if slot is not None:
                        break
                elif kind == LOG:
                    self._log(f"s{rec.idx}", payload["level"],
                              payload["msg"])
                elif kind == CLOSE:
                    # a spare shutting down cleanly before adoption
                    try:
                        rec.ch.send_obj("closed")
                    except (Mp4jError, OSError):
                        pass
                    rec.ch.close()
                    self._spare_gone(rec, "closed")
                    return
                # anything else from an unadopted spare is noise
        except Exception as e:
            self._spare_gone(rec, f"connection lost ({e!r})")
            return
        self._serve_slave(slot)

    def _finish_adoption(self, rec):
        """An adopted spare acked: install its channel as the rank's
        slot and hand the round machinery the news. Returns the slot
        (the caller's thread continues as the rank's serve thread), or
        None when the ack is stale."""
        with self._lock:
            r = rec.adopting_rank
            if r is None or self._fatal_msg is not None:
                return None
            rec.adopt_since = None
            slot = _Slot(r, rec.ch)
            self._slots[r] = slot
            self._round_adopted[r] = rec
            if rec in self._spare_pool:
                self._spare_pool.remove(rec)
            # the dead occupant's telemetry must not pollute the
            # joiner's: fresh windows, fresh deltas (cluster TOTALS
            # keep the dead rank's history — it really happened)
            self._telemetry.pop(r, None)
            self._rank_windows.pop(r, None)
            self._rank_totals.pop(r, None)
            self._serve_threads.append(threading.current_thread())
        self._log("M", "WARN",
                  f"spare #{rec.idx} adopted as rank {r}")
        self._try_advance_round()
        return slot

    def _send_spare(self, rec, obj) -> None:
        try:
            rec.ch.send_obj(obj)
        except (Mp4jError, OSError):
            self._spare_gone(rec, "unreachable on adopt push")

    def _spare_gone(self, rec, why: str) -> None:
        """A spare died (pre- or mid-adoption): drop it from the pool,
        un-assign any in-flight adoption and re-drive the round — the
        next spare is tried, or the round goes terminal through the
        no-spare path."""
        retry = False
        with self._lock:
            rec.alive = False
            if rec in self._spare_pool:
                self._spare_pool.remove(rec)
            r = rec.adopting_rank
            rec.adopting_rank = None
            rec.adopt_since = None
            if r is not None and self._round_adoptions.get(r) is rec:
                del self._round_adoptions[r]
                retry = True
        self._log("M", "WARN", f"warm spare #{rec.idx} lost: {why}")
        try:
            rec.ch.close()
        except OSError:
            pass
        if retry:
            # re-enter through _begin_membership so the no-spare path
            # produces the same clean fatal as never having had one
            self._begin_membership({}, self._round_why or
                                   f"spare #{rec.idx} died mid-adoption")
            self._try_advance_round()

    def _release_spares(self, reason: str) -> None:
        with self._lock:
            pool = list(self._spare_pool)
            self._spare_pool = []
            threads = list(self._spare_threads)
        for rec in pool:
            try:
                rec.ch.send_obj(("release", reason))
            except (Mp4jError, OSError):
                pass
            try:
                rec.ch.close()
            except OSError:
                pass
        me = threading.current_thread()
        for t in threads:
            # the fatal path can be DRIVEN from a spare's own serve
            # thread (last spare dies mid-adoption -> no-spare fatal);
            # joining it would raise "cannot join current thread"
            if t is not me:
                t.join(2.0)

    @staticmethod
    def _mixed_progress(progress: dict) -> str | None:
        """Recovery is PER-COLLECTIVE: a round may only be released
        when every in-flight rank is retrying the SAME collective
        ordinal m, and every idle rank sits exactly one behind (it
        will enter m fresh). Any other shape means the fault spans a
        collective boundary — a rank that already completed m cannot
        re-serve its contribution (its input snapshot is gone), so
        retrying would deadlock or, worse, pair mismatched exchanges
        into silently wrong results. Returns the terminal message, or
        None when consistent."""
        inflight = {r: s for r, (s, f) in progress.items() if f}
        if not inflight:
            return None
        m = max(inflight.values())
        bad = {r: s for r, (s, f) in progress.items()
               if (f and s != m) or (not f and s != m - 1)}
        if not bad:
            return None
        detail = ", ".join(
            f"rank {r} at collective #{s}"
            f"{' (in flight)' if progress[r][1] else ' (completed)'}"
            for r, s in sorted(bad.items()))
        return (f"cannot recover: the fault spans a collective "
                f"boundary — ranks retrying collective #{m} but "
                f"{detail}; recovery is per-collective (align the "
                "schedule, e.g. with a barrier, to make this fault "
                "window recoverable)")

    def _fatal_abort(self, msg: str) -> None:
        """Fan the terminal abort out to every live rank, once. The
        message is composed HERE so all ranks raise identically."""
        with self._lock:
            if self._fatal_msg is not None:
                return
            self._fatal_msg = msg
            self._abort_since = None
        self._log("M", "ERROR", f"terminal abort: {msg}")
        for line in self.diagnose():
            self._log("M", "WARN", line)
        # flight recorder: write the manifest NOW (survivors may be
        # about to exit); serve() refreshes it once the slaves' final
        # fatal-path telemetry flushes have landed
        self._write_postmortem_manifest()
        for r in sorted(self._live_ranks()):
            self._send_to(r, ("abort_fatal", msg))
        # idle spares raise Mp4jSpareReleased instead of outliving
        # the job they were provisioned for (ISSUE 10)
        self._release_spares(msg)

    def _log(self, rank, level: str, msg: str):
        """Centralized log sink: ISO-8601 timestamps and a fixed-width
        ``[rank/size LEVEL]`` prefix so interleaved multi-rank logs are
        sortable and greppable; lines below ``MP4J_LOG_LEVEL`` are
        dropped. ``rank`` may be the string ``"M"`` for master-origin
        lines (watchdog, rendezvous)."""
        if tuning.LOG_LEVELS.get(level, tuning.LOG_LEVELS["INFO"]) \
                < self._min_level:
            return
        now = time.time()
        ts = (time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(now))
              + f".{int(now % 1 * 1000):03d}")
        who = f"{rank!s:>{self._rank_width}}"
        print(f"{ts} [{who}/{self.slave_num} {level:<5}] {msg}",
              file=self.log_stream, flush=True)

    # -- telemetry ------------------------------------------------------
    def _record_telemetry(self, rank: int, payload: dict) -> None:
        """Fold one heartbeat into the rolling cluster time-series.

        Since ISSUE 6 the beat carries DELTAS (``stats_delta`` /
        ``metrics_delta``) folded onto the rank's cumulative view;
        a full ``stats`` snapshot (older senders, external tools)
        replaces it instead. Each fold also advances the rank's and
        the cluster's rate rings, so windowed GB/s / collectives/s /
        keys/s stay derivable without a second pass."""
        progress = payload.get("progress") or {}
        now = time.monotonic()
        audit_lines: list[str] = []
        health_alerts: list[dict] = []
        with self._lock:
            live = set(range(self.slave_num)) - set(self._departed)
            new_divergences: list[dict] = []
            if "audit_delta" in payload:
                # verification happens as records complete — a flagged
                # divergence is logged within one heartbeat of the last
                # rank's record arriving; log lines emitted OUTSIDE the
                # lock below
                before_div = self._auditor.divergence_total
                audit_lines = self._auditor.fold(
                    rank, payload.get("audit_delta"), live)
                grew = self._auditor.divergence_total - before_div
                if grew:
                    new_divergences = list(
                        self._auditor.divergences)[-grew:]
            if self._health is not None:
                # the health plane folds the SAME beat: baselines,
                # detectors, the online dominator over the shipped
                # cells, and audit-divergence escalation — alert
                # dispatch (log + push to the subject rank) happens
                # outside the lock below
                health_alerts = self._health.fold(
                    rank, payload, now, live)
                if new_divergences:
                    health_alerts.extend(self._health.note_audit(
                        new_divergences, live))
            prev = self._telemetry.get(rank)
            if "stats_delta" in payload:
                stats = stats_mod.merge_snapshots(
                    prev["stats"] if prev else {},
                    payload.get("stats_delta") or {})
            else:
                stats = (payload.get("stats")
                         or (prev["stats"] if prev else {}))
            delta = payload.get("metrics_delta") or {}
            metrics = metrics_mod.fold_snapshot(
                (prev or {}).get("metrics") or {}, delta)
            self._cluster_metrics = metrics_mod.fold_snapshot(
                self._cluster_metrics, delta)
            self._telemetry[rank] = {
                "seq": int(progress.get("seq", 0)),
                "current": progress.get("current"),
                "last": progress.get("last"),
                "phase": progress.get("phase"),
                "current_secs": float(progress.get("current_secs", 0.0)),
                # per-rank recovery epoch (ISSUE 10): `mp4j-scope
                # live` renders it next to the roster badges
                "epoch": int(progress.get("epoch", 0)),
                "stats": stats,
                "metrics": metrics,
                "mono": now,
            }
            win = self._rank_windows.get(rank)
            if win is None:
                win = self._rank_windows[rank] = metrics_mod.RateWindow(
                    self._metrics_window)
            totals = self._stats_totals(stats)
            win.note(now, totals)
            # running cluster totals: add this rank's movement since
            # its last fold — O(1 rank) per beat, not a re-sum of every
            # rank's whole stats table under the master lock
            before = self._rank_totals.get(rank, {})
            for k, v in totals.items():
                self._cluster_totals[k] = (self._cluster_totals.get(k, 0)
                                           + v - before.get(k, 0))
            self._rank_totals[rank] = totals
            self._cluster_window.note(now, self._cluster_totals)
        for line in audit_lines:
            self._log("M", "ERROR", line)
        self._dispatch_health_alerts(health_alerts)

    def _dispatch_health_alerts(self, alerts: list[dict]) -> None:
        """Emit freshly minted health alerts: one master log line
        each, plus a control-plane push to the SUBJECT rank (its
        recovery log and durable sink make the verdict durable). A
        dead/missing subject's alert lands on the lowest live rank
        instead — the evidence must outlive the patient."""
        if not alerts:
            return
        live = self._live_ranks()
        for ev in alerts:
            level = ("ERROR" if ev.get("to") in (
                "SUSPECT", "EVICT_RECOMMENDED", "DEAD") else "WARN")
            self._log("M", level,
                      "health: " + health_mod.format_alert(ev))
            target = ev.get("rank")
            if ev.get("to") == "DEAD" or target not in live:
                # never push a DEAD verdict at its own subject — the
                # channel is the thing that just died, and the failed
                # push would re-enter the death path as "unreachable
                # on push"; the evidence lands on the lowest OTHER
                # live rank instead
                target = next((r for r in sorted(live)
                               if r != ev.get("rank")), None)
            if target is not None and 0 <= target < len(self._slots):
                self._send_to(target, ("health_alert", ev))

    def _handle_diagnose(self, rank: int, payload: dict) -> None:
        """A slave's bounded collective wait expired: refresh its table
        entry from the report itself (fresher than its last heartbeat),
        then log the cluster-wide diagnosis — ONCE per incident. When
        one rank stalls, every other rank's bounded wait expires in the
        same window; without the debounce (keyed on the cluster's max
        sequence number) a 256-rank job would bury the one useful
        report under ~N full per-rank dumps."""
        self._record_telemetry(rank, payload)
        self._log(rank, "ERROR",
                  f"collective '{payload.get('collective')}' failed: "
                  f"{payload.get('error')}")
        with self._lock:
            incident = max((t["seq"] for t in self._telemetry.values()),
                           default=0)
            repeat = incident == self._diag_incident_seq
            self._diag_incident_seq = incident
        if repeat:
            self._log("M", "WARN",
                      f"rank {rank} reports the same incident (max seq "
                      f"{incident}) — full diagnosis already logged above")
            return
        for line in self.diagnose():
            self._log("M", "WARN", line)

    def _snapshot_table(self) -> dict[int, dict]:
        """One heartbeat-table snapshot (progress fields + age) —
        the shared shape behind the diagnosis, the metrics document
        and the postmortem manifest. Caller must NOT hold the lock."""
        now = time.monotonic()
        with self._lock:
            return {r: {**{k: t.get(k) for k in
                           ("seq", "current", "last", "phase",
                            "current_secs", "epoch")},
                        "age": now - t["mono"]}
                    for r, t in self._telemetry.items()}

    def diagnose(self) -> list[str]:
        """Render the hang/straggler diagnosis from the heartbeat
        table (obs.telemetry.render_diagnosis)."""
        return telemetry_mod.render_diagnosis(self._snapshot_table(),
                                              self.slave_num)

    def cluster_stats(self) -> dict[str, dict]:
        """Cross-rank skew per collective family from the latest
        heartbeat stats snapshots (schema:
        obs.telemetry.cluster_skew)."""
        with self._lock:
            per_rank = {r: t["stats"] for r, t in self._telemetry.items()
                        if t.get("stats")}
        return telemetry_mod.cluster_skew(per_rank)

    def format_cluster_stats(self) -> str:
        """The ``mp4j-scope report`` table, live from the master."""
        return telemetry_mod.format_skew(self.cluster_stats())

    # -- live metrics plane (ISSUE 6) -----------------------------------
    def _start_metrics_server(self, host: str, port: int) -> None:
        """Bind the control-plane HTTP metrics endpoint. Loopback by
        default (host "" would mean every interface for the DATA
        master socket too, but metrics add nothing a peer needs — an
        operator scrapes where the master runs, or passes an explicit
        host)."""
        master = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):         # noqa: N802
                if self.path in ("/metrics", "/metrics/"):
                    body = metrics_mod.to_prometheus(
                        master.metrics_doc()).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path in ("/metrics.json", "/json"):
                    body = json.dumps(master.metrics_doc()).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # scrapes are not log lines
                pass

        srv = http.server.ThreadingHTTPServer(
            (host or "127.0.0.1", port), Handler)
        srv.daemon_threads = True
        self._metrics_server = srv
        self.metrics_port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mp4j-metrics-http").start()

    def _stop_metrics_server(self) -> None:
        srv, self._metrics_server = self._metrics_server, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()

    @staticmethod
    def _stats_totals(stats: dict) -> dict[str, float]:
        """Cumulative totals the rate windows differentiate."""
        return {
            "bytes": sum(e.get("bytes_sent", 0) + e.get("bytes_recv", 0)
                         for e in stats.values()),
            "collectives": sum(e.get("calls", 0)
                               for e in stats.values()),
            "keys": sum(e.get("keys", 0) for e in stats.values()),
        }

    def metrics_doc(self) -> dict:
        """The metrics document both endpoint formats serve: per-rank
        progress/stats/rates plus the cluster aggregate (summed stats,
        folded histograms, windowed rates). Plain JSON-ready dicts —
        ``obs.metrics.to_prometheus`` renders the text form."""
        now = time.monotonic()
        with self._lock:
            ranks: dict[str, dict] = {}
            for r in sorted(self._telemetry):
                t = self._telemetry[r]
                win = self._rank_windows.get(r)
                # snapshots/aggregates are handed out by REFERENCE:
                # every fold/merge builds a NEW object (the previous
                # one is never mutated), so readers outside the lock
                # see a consistent frozen view — no per-scrape deep
                # copy of the whole fleet's stats under the lock
                ranks[str(r)] = {
                    "progress": {k: t.get(k) for k in
                                 ("seq", "current", "last", "phase",
                                  "current_secs", "epoch")},
                    "age": now - t["mono"],
                    "stats": t["stats"],
                    "rates": win.rates() if win is not None else {},
                    "histograms": (t.get("metrics") or {}).get(
                        "histograms", {}),
                    # registry counters/gauges ride the doc since
                    # ISSUE 9 — the sink series (sink/bytes,
                    # sink/dropped_records, sink/lag_secs) render per
                    # rank in Prometheus and in `mp4j-scope live`
                    "counters": (t.get("metrics") or {}).get(
                        "counters", {}),
                    "gauges": (t.get("metrics") or {}).get(
                        "gauges", {}),
                }
            cluster_rates = self._cluster_window.rates()
            cluster_metrics = self._cluster_metrics
            audit_status = self._auditor.status()
            membership_status = self._membership_status_locked()
            health_status = (self._health.status()
                             if self._health is not None else None)
        cluster_stats = stats_mod.merge_snapshots(
            *(info["stats"] for info in ranks.values()))
        for r, info in ranks.items():
            info["audit_seq"] = int(
                audit_status["rank_seq"].get(r, 0))
        return {
            "slave_num": self.slave_num,
            "window_secs": self._metrics_window,
            # heartbeat period (ISSUE 12 satellite): the live view
            # needs it to annotate a stale rank's derived rate columns
            "hb_secs": self._hb_secs,
            "ranks": ranks,
            "cluster": {
                "stats": cluster_stats,
                "rates": cluster_rates,
                "histograms": cluster_metrics["histograms"],
                "audit": audit_status,
                "membership": membership_status,
                "health": health_status,
            },
        }

    def _membership_status_locked(self) -> dict:
        """ONE definition of the membership snapshot (availability
        predicate included) for every surface that renders it — the
        metrics doc, :meth:`membership_status` and the postmortem
        manifest must never disagree. Caller holds the lock."""
        return self._membership.status(
            spares_available=sum(
                1 for s in self._spare_pool
                if s.alive and s.adopting_rank is None),
            spares_total=self._spare_seq)

    def membership_status(self) -> dict:
        """The elastic-membership document (ISSUE 10): mode, counters,
        spare availability, per-rank badges and the bounded event
        history (schema: resilience.membership.MembershipLog.status)."""
        with self._lock:
            return self._membership_status_locked()

    def audit_status(self) -> dict:
        """The cluster audit document (ISSUE 8): last cross-rank-
        verified collective ordinal, divergence count, recent
        divergence details (schema: obs.audit.ClusterAuditor.status).
        All zeros unless slaves run ``MP4J_AUDIT=verify|capture``."""
        with self._lock:
            return self._auditor.status()

    def health_status(self) -> dict | None:
        """The health plane's verdict document (ISSUE 12) — THE
        operator hook the future elastic autoscaler calls: per-rank
        state (``HEALTHY``/``DEGRADED``/``SUSPECT``/
        ``EVICT_RECOMMENDED``/``DEAD``) with detector-pressure
        evidence, the ``evict_recommended`` list, dominator window
        shares/streak, onset count and the recent alert tail (schema:
        obs.health.HealthEngine.status). This plane only ever
        RECOMMENDS — acting on a verdict (replacing a SUSPECT rank
        from a spare, shrinking around an EVICT_RECOMMENDED one) is
        the caller's decision. None when ``MP4J_HEALTH=0``."""
        with self._lock:
            return (self._health.status()
                    if self._health is not None else None)

    def _write_postmortem_manifest(self) -> None:
        """Flight-recorder manifest (once per write site, idempotent
        overwrite): only on a terminal abort — a clean job leaves no
        postmortem."""
        with self._lock:
            reason = self._fatal_msg
            departed = dict(self._departed)
            audit_status = self._auditor.status()
            membership_status = self._membership_status_locked()
            health_status = (self._health.status()
                             if self._health is not None else None)
        if not self._postmortem_dir or reason is None:
            return
        # ONE table snapshot feeds both fields, so the manifest's
        # diagnosis and table describe the same instant
        table = self._snapshot_table()
        try:
            postmortem_mod.write_master_manifest(
                self._postmortem_dir, slave_num=self.slave_num,
                reason=reason, table=table, departed=departed,
                diagnosis=telemetry_mod.render_diagnosis(
                    table, self.slave_num),
                audit=audit_status,
                sink_dir=self._sink_dir or None,
                membership=membership_status,
                health=health_status)
        except OSError:
            pass  # best-effort: the job is already terminal

    def _watchdog_loop(self):
        """Diagnose stalled barriers, then ACT on them (ISSUE 5).

        A generation some ranks reached ``stall_timeout`` seconds ago
        while others never arrived is the mismatched-schedule deadlock
        signature — log the diagnosis once per generation (the PR-3
        behavior). A generation (or an open abort round) still
        incomplete after ``dead_rank_secs`` escalates to the terminal
        abort fan-out: the whole cluster raises one clean error instead
        of each rank relying on its local timeout — the watchdog is no
        longer log-only. ``stall_timeout=None`` disables the diagnosis
        only; ``dead_rank_secs=inf`` disables the escalation only."""
        bounds = [t for t in (self.stall_timeout, self.dead_rank_secs)
                  if t is not None and t != float("inf")]
        tick = min(1.0, max(0.05, min(bounds) / 4)) if bounds else 1.0
        while not self._stop.wait(tick):
            now = time.monotonic()
            stalled, fatal = [], None
            escalate: dict[int, str] = {}   # rank -> why (elastic)
            lost_spares = []
            with self._lock:
                round_open = self._abort_since is not None
                for gen, since in self._barrier_since.items():
                    if gen not in self._barrier_waiting:
                        continue
                    age = now - since
                    if (age > self.dead_rank_secs
                            and self._fatal_msg is None
                            # a barrier waiting out a membership round
                            # (the joiner has not re-arrived yet) is
                            # the round's business, not a new death
                            and not (self.elastic != "off"
                                     and round_open)):
                        missing = sorted(
                            set(range(self.slave_num))
                            - set(self._barrier_waiting[gen]))
                        fatal = (f"barrier gen {gen} stalled for "
                                 f"{age:.1f}s waiting on ranks "
                                 f"{missing}; aborting the job")
                        if self.elastic != "off":
                            for r in missing:
                                escalate.setdefault(
                                    r, f"barrier gen {gen} stalled "
                                    f"{age:.1f}s without it")
                    elif (self.stall_timeout is not None
                            and age > self.stall_timeout
                            and gen not in self._diagnosed_gens):
                        self._diagnosed_gens.add(gen)
                        stalled.append(
                            (gen, list(self._barrier_waiting[gen]), age))
                if (fatal is None and round_open
                        and now - self._abort_since > self.dead_rank_secs):
                    missing = sorted(set(range(self.slave_num))
                                     - set(self._departed)
                                     - self._abort_acks)
                    if missing:
                        fatal = (f"abort round -> epoch "
                                 f"{self._abort_epoch} stalled: no "
                                 f"teardown ack from ranks "
                                 f"{missing}; aborting the job")
                        if self.elastic != "off":
                            for r in missing:
                                escalate.setdefault(
                                    r, "no teardown ack within "
                                    f"{self.dead_rank_secs:.1f}s")
                    elif self._round_kind in ("replace", "shrink"):
                        # acks complete but the membership half never
                        # finished (manifest or adoption wedged past
                        # every narrower deadline): terminal
                        fatal = (f"membership round -> epoch "
                                 f"{self._abort_epoch} stalled for "
                                 f"{now - self._abort_since:.1f}s; "
                                 "aborting the job")
                # spare-adoption deadline (ISSUE 10): a spare that
                # never acks its adoption burns one deadline, not the
                # whole recovery budget — the next spare is tried
                for r, rec in list(self._round_adoptions.items()):
                    if (rec.adopt_since is not None
                            and now - rec.adopt_since > self._adopt_secs):
                        lost_spares.append(rec)
            for gen, ranks, age in stalled:
                missing = sorted(set(range(self.slave_num)) - set(ranks))
                self._log("M", "WARN",
                          f"barrier gen {gen} stalled for {age:.1f}s: "
                          f"ranks {sorted(ranks)} waiting on ranks "
                          f"{missing}")
                for line in self.diagnose():
                    self._log("M", "WARN", line)
            for rec in lost_spares:
                self._spare_gone(
                    rec, f"adoption not acked within "
                    f"{self._adopt_secs:.1f}s")
            if fatal is not None:
                if self.elastic != "off" and escalate:
                    for r, why in escalate.items():
                        self._on_rank_dead(r, why, fatal)
                else:
                    self._fatal_abort(fatal)

    def _barrier(self, slot: _Slot, gen: int):
        release = False
        stale = False
        with self._lock:
            rank = slot.rank
            fatal = self._fatal_msg
            if fatal is None:
                if gen <= self._barrier_max_released:
                    stale = True    # see _barrier_max_released
                else:
                    waiting = self._barrier_waiting.setdefault(gen, [])
                    self._barrier_since.setdefault(gen,
                                                   time.monotonic())
                    waiting.append(rank)
                    if len(waiting) == self.slave_num:
                        release = True
                        self._barrier_max_released = max(
                            self._barrier_max_released, gen)
        if stale:
            self._send_to(rank, ("barrier_release", gen))
            return
        if fatal is not None:
            # the job is terminally aborted: never release a barrier
            # into it — a straggler arriving after the fan-out must
            # raise the fatal, not "complete" a dead job (re-push the
            # message in case the original fan-out raced its dial-in)
            self._send_to(rank, ("abort_fatal", fatal))
            return
        if release:
            # release everyone waiting on this generation
            for r in range(self.slave_num):
                self._send_to(r, ("barrier_release", gen))
            with self._lock:
                del self._barrier_waiting[gen]
                self._barrier_since.pop(gen, None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="ytk-mp4j-tpu rendezvous master")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--slaves", type=int, required=True)
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args(argv)
    m = Master(args.slaves, port=args.port, timeout=args.timeout)
    print(f"mp4j master listening on port {m.port} for {args.slaves} slaves",
          file=sys.stderr, flush=True)
    return m.serve()


if __name__ == "__main__":
    sys.exit(main())
