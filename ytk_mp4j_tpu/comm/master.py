"""Rendezvous master — the control plane.

The reference runs a master process that slaves connect to: it assigns
ranks, distributes the slave roster (rank -> host:port), serves as the
centralized log sink for ``info()/error()``, coordinates barriers, and
aggregates exit codes at ``close(code)`` (SURVEY.md sections 2, 3a, 3e).

This is that master, rebuilt in Python over the framed-socket transport.
It can run embedded (a thread, for tests and single-host jobs) or as a
CLI: ``python -m ytk_mp4j_tpu.comm.master --port P --slaves N``.

Failure model matches the reference: fail-stop, fixed slave count, no
elastic recovery (SURVEY.md section 5) — but rendezvous has an optional
timeout as a cheap diagnosability win over indefinite hangs.
"""

from __future__ import annotations

import argparse
import socket
import sys
import threading
import time

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.transport.channel import Channel

# control-plane message kinds (slave -> master)
REGISTER = "register"
LOG = "log"
BARRIER = "barrier"
CLOSE = "close"


class Master:
    """Rank assignment, roster exchange, log sink, barrier, exit codes."""

    def __init__(self, slave_num: int, port: int = 0, host: str = "",
                 log_stream=None, timeout: float | None = 120.0,
                 handshake_timeout: float | None = 5.0):
        """``timeout`` bounds the whole rendezvous; ``handshake_timeout``
        bounds each accepted connection's registration message, so one
        stray dial-in stalls rendezvous briefly instead of consuming the
        entire budget while real slaves queue behind it."""
        self.slave_num = slave_num
        self.timeout = timeout
        self.handshake_timeout = handshake_timeout
        self.log_stream = log_stream if log_stream is not None else sys.stderr
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host or "0.0.0.0", port))
        self._server.listen(slave_num * 2)
        self.port = self._server.getsockname()[1]
        self._channels: list[Channel] = []      # by rank after rendezvous
        self._exit_codes: dict[int, int] = {}
        self._barrier_waiting: dict[int, list[int]] = {}  # gen -> ranks
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.final_code: int | None = None

    # ------------------------------------------------------------------
    def serve(self) -> int:
        """Run rendezvous then the control loop; returns aggregate exit
        code (0 iff every slave closed with 0)."""
        self._rendezvous()
        threads = []
        for rank, ch in enumerate(self._channels):
            t = threading.Thread(target=self._serve_slave, args=(rank, ch),
                                 daemon=True, name=f"master-slave{rank}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        self._server.close()
        codes = [self._exit_codes.get(r, 1) for r in range(self.slave_num)]
        self.final_code = max(codes) if codes else 0
        return self.final_code

    def serve_in_thread(self) -> "Master":
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name="mp4j-master")
        self._thread.start()
        return self

    def join(self, timeout: float | None = None):
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------------
    def _rendezvous(self):
        """Accept slave registrations; assign ranks in registration order
        (pinned free choice — the reference's exact rule is unverified);
        broadcast the roster to all."""
        deadline = None if self.timeout is None else time.time() + self.timeout
        pending = []  # (channel, (host, listen_port))
        self._server.settimeout(1.0)
        while len(pending) < self.slave_num:
            if deadline is not None and time.time() > deadline:
                raise Mp4jError(
                    f"rendezvous timeout: {len(pending)}/{self.slave_num} "
                    "slaves registered")
            try:
                sock, addr = self._server.accept()
            except socket.timeout:
                continue
            ch = Channel(sock)
            # bound the registration handshake: a stray connection that
            # never sends must neither hang rendezvous (no timeout) nor
            # consume the whole budget while real slaves queue behind it
            remaining = (None if deadline is None
                         else max(0.1, deadline - time.time()))
            bounds = [t for t in (remaining, self.handshake_timeout)
                      if t is not None]
            ch.set_timeout(min(bounds) if bounds else None)
            try:
                # anything a hostile/broken dial-in can do — reset,
                # garbage frame, non-tuple payload, malformed REGISTER
                # body, timeout — must not kill rendezvous for the
                # real slaves, so the whole decode stays in this try
                kind, payload = ch.recv()
                ok = kind == REGISTER and isinstance(payload, dict)
                listen_port = int(payload["listen_port"]) if ok else 0
                host = str(payload.get("host") or addr[0]) if ok else ""
            except Exception:
                ok = False
            if not ok:
                ch.close()
                continue
            ch.set_timeout(None)  # control plane is fail-stop from here
            pending.append((ch, (host, listen_port)))
        roster = [hp for _, hp in pending]
        for rank, (ch, _) in enumerate(pending):
            ch.send_obj({"rank": rank, "roster": roster})
            self._channels.append(ch)

    def _serve_slave(self, rank: int, ch: Channel):
        try:
            while True:
                kind, payload = ch.recv()
                if kind == LOG:
                    self._log(rank, payload["level"], payload["msg"])
                elif kind == BARRIER:
                    self._barrier(rank, payload["gen"], ch)
                elif kind == CLOSE:
                    with self._lock:
                        self._exit_codes[rank] = payload["code"]
                    ch.send_obj("closed")
                    ch.close()
                    return
                else:
                    self._log(rank, "ERROR", f"unknown message {kind!r}")
        except Exception as e:
            # fail-stop: a dead slave (reset, EOF, corrupt frame) marks a
            # nonzero exit code; the master keeps serving the others
            self._log(rank, "ERROR", f"slave connection lost: {e!r}")
            with self._lock:
                self._exit_codes.setdefault(rank, 1)

    def _log(self, rank: int, level: str, msg: str):
        ts = time.strftime("%H:%M:%S")
        print(f"[{ts}][rank {rank}/{self.slave_num}][{level}] {msg}",
              file=self.log_stream, flush=True)

    def _barrier(self, rank: int, gen: int, ch: Channel):
        release = False
        with self._lock:
            waiting = self._barrier_waiting.setdefault(gen, [])
            waiting.append(rank)
            if len(waiting) == self.slave_num:
                release = True
        if release:
            # release everyone waiting on this generation
            for r, c in enumerate(self._channels):
                c.send_obj(("barrier_release", gen))
            with self._lock:
                del self._barrier_waiting[gen]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="ytk-mp4j-tpu rendezvous master")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--slaves", type=int, required=True)
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args(argv)
    m = Master(args.slaves, port=args.port, timeout=args.timeout)
    print(f"mp4j master listening on port {m.port} for {args.slaves} slaves",
          file=sys.stderr, flush=True)
    return m.serve()


if __name__ == "__main__":
    sys.exit(main())
