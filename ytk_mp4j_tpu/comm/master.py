"""Rendezvous master — the control plane.

The reference runs a master process that slaves connect to: it assigns
ranks, distributes the slave roster (rank -> host:port), serves as the
centralized log sink for ``info()/error()``, coordinates barriers, and
aggregates exit codes at ``close(code)`` (SURVEY.md sections 2, 3a, 3e).

This is that master, rebuilt in Python over the framed-socket transport.
It can run embedded (a thread, for tests and single-host jobs) or as a
CLI: ``python -m ytk_mp4j_tpu.comm.master --port P --slaves N``.

Failure model matches the reference: fail-stop, fixed slave count, no
elastic recovery (SURVEY.md section 5) — but rendezvous has an optional
timeout as a cheap diagnosability win over indefinite hangs.

Observability (ISSUE 3): slaves piggyback periodic TELEMETRY heartbeats
(``{progress, stats}``, schema in obs.telemetry) on the control
channel; the master keeps a per-rank table, serves cross-rank skew via
:meth:`Master.cluster_stats`, and turns the paper's worst failure mode
— a silent mismatched-schedule deadlock — into a runtime report: a
slave whose bounded collective wait expires ships a DIAGNOSE, and a
barrier generation stalled past ``stall_timeout`` trips the watchdog;
either way the master logs which ranks trail the cluster's max
collective sequence number, where each laggard last was, and how stale
its heartbeat is. Heartbeats ride the control plane only — they can
never block a data-plane exchange.
"""

from __future__ import annotations

import argparse
import socket
import sys
import threading
import time

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.obs import telemetry as telemetry_mod
from ytk_mp4j_tpu.transport.channel import Channel
from ytk_mp4j_tpu.utils import tuning

# control-plane message kinds (slave -> master)
REGISTER = "register"
LOG = "log"
BARRIER = "barrier"
CLOSE = "close"
TELEMETRY = "telemetry"   # periodic heartbeat: {progress, stats}
DIAGNOSE = "diagnose"     # a slave's bounded wait expired; report it


class Master:
    """Rank assignment, roster exchange, log sink, barrier, exit codes,
    plus the cluster telemetry table (heartbeats, skew, hang diagnosis)."""

    def __init__(self, slave_num: int, port: int = 0, host: str = "",
                 log_stream=None, timeout: float | None = 120.0,
                 handshake_timeout: float | None = 5.0,
                 stall_timeout: float | None = 60.0):
        """``timeout`` bounds the whole rendezvous; ``handshake_timeout``
        bounds each accepted connection's registration message, so one
        stray dial-in stalls rendezvous briefly instead of consuming the
        entire budget while real slaves queue behind it.
        ``stall_timeout`` arms the barrier watchdog: a barrier
        generation with some ranks still missing after this many
        seconds gets a hang diagnosis logged (once per generation);
        ``None`` disables the watchdog. The watchdog only LOGS — the
        barrier itself stays fail-stop, per the reference contract."""
        self.slave_num = slave_num
        self.timeout = timeout
        self.handshake_timeout = handshake_timeout
        self.stall_timeout = stall_timeout
        self.log_stream = log_stream if log_stream is not None else sys.stderr
        # log sink config: validated once at construction (a typo'd
        # MP4J_LOG_LEVEL fails the job here, not silently mid-run)
        self._min_level = tuning.LOG_LEVELS[tuning.log_level()]
        self._rank_width = max(1, len(str(max(slave_num - 1, 0))))
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host or "0.0.0.0", port))
        self._server.listen(slave_num * 2)
        self.port = self._server.getsockname()[1]
        self._channels: list[Channel] = []      # by rank after rendezvous
        self._exit_codes: dict[int, int] = {}
        self._barrier_waiting: dict[int, list[int]] = {}  # gen -> ranks
        self._barrier_since: dict[int, float] = {}        # gen -> mono ts
        self._diagnosed_gens: set[int] = set()
        self._diag_incident_seq: int | None = None  # debounce key
        # rank -> last heartbeat: progress fields + stats + arrival time
        self._telemetry: dict[int, dict] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.final_code: int | None = None

    # ------------------------------------------------------------------
    def serve(self) -> int:
        """Run rendezvous then the control loop; returns aggregate exit
        code (0 iff every slave closed with 0)."""
        self._rendezvous()
        threads = []
        for rank, ch in enumerate(self._channels):
            t = threading.Thread(target=self._serve_slave, args=(rank, ch),
                                 daemon=True, name=f"master-slave{rank}")
            t.start()
            threads.append(t)
        watchdog = None
        if self.stall_timeout is not None:
            watchdog = threading.Thread(target=self._watchdog_loop,
                                        daemon=True, name="mp4j-watchdog")
            watchdog.start()
        try:
            for t in threads:
                t.join()
        finally:
            self._stop.set()
        if watchdog is not None:
            watchdog.join(2.0)
        self._server.close()
        codes = [self._exit_codes.get(r, 1) for r in range(self.slave_num)]
        self.final_code = max(codes) if codes else 0
        return self.final_code

    def serve_in_thread(self) -> "Master":
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name="mp4j-master")
        self._thread.start()
        return self

    def join(self, timeout: float | None = None):
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------------
    def _rendezvous(self):
        """Accept slave registrations; assign ranks in registration order
        (pinned free choice — the reference's exact rule is unverified);
        broadcast the roster to all."""
        deadline = None if self.timeout is None else time.time() + self.timeout
        pending = []  # (channel, (host, listen_port))
        self._server.settimeout(1.0)
        while len(pending) < self.slave_num:
            if deadline is not None and time.time() > deadline:
                got = [hp for _, hp in pending]
                raise Mp4jError(
                    f"rendezvous timeout: {len(pending)}/{self.slave_num} "
                    f"slaves registered (heard from: {got or 'none'} — "
                    "the missing slaves never dialed in)")
            try:
                sock, addr = self._server.accept()
            except socket.timeout:
                continue
            ch = Channel(sock)
            # bound the registration handshake: a stray connection that
            # never sends must neither hang rendezvous (no timeout) nor
            # consume the whole budget while real slaves queue behind it
            remaining = (None if deadline is None
                         else max(0.1, deadline - time.time()))
            bounds = [t for t in (remaining, self.handshake_timeout)
                      if t is not None]
            ch.set_timeout(min(bounds) if bounds else None)
            try:
                # anything a hostile/broken dial-in can do — reset,
                # garbage frame, non-tuple payload, malformed REGISTER
                # body, timeout — must not kill rendezvous for the
                # real slaves, so the whole decode stays in this try
                kind, payload = ch.recv()
                ok = kind == REGISTER and isinstance(payload, dict)
                listen_port = int(payload["listen_port"]) if ok else 0
                host = str(payload.get("host") or addr[0]) if ok else ""
            except Exception:
                ok = False
            if not ok:
                ch.close()
                continue
            ch.set_timeout(None)  # control plane is fail-stop from here
            pending.append((ch, (host, listen_port)))
        roster = [hp for _, hp in pending]
        for rank, (ch, _) in enumerate(pending):
            ch.send_obj({"rank": rank, "roster": roster})
            self._channels.append(ch)

    def _serve_slave(self, rank: int, ch: Channel):
        try:
            while True:
                kind, payload = ch.recv()
                if kind == LOG:
                    self._log(rank, payload["level"], payload["msg"])
                elif kind == BARRIER:
                    self._barrier(rank, payload["gen"], ch)
                elif kind == TELEMETRY:
                    self._record_telemetry(rank, payload)
                elif kind == DIAGNOSE:
                    self._handle_diagnose(rank, payload)
                elif kind == CLOSE:
                    with self._lock:
                        self._exit_codes[rank] = payload["code"]
                    ch.send_obj("closed")
                    ch.close()
                    return
                else:
                    self._log(rank, "ERROR", f"unknown message {kind!r}")
        except Exception as e:
            # fail-stop: a dead slave (reset, EOF, corrupt frame) marks a
            # nonzero exit code; the master keeps serving the others
            self._log(rank, "ERROR", f"slave connection lost: {e!r}")
            with self._lock:
                self._exit_codes.setdefault(rank, 1)

    def _log(self, rank, level: str, msg: str):
        """Centralized log sink: ISO-8601 timestamps and a fixed-width
        ``[rank/size LEVEL]`` prefix so interleaved multi-rank logs are
        sortable and greppable; lines below ``MP4J_LOG_LEVEL`` are
        dropped. ``rank`` may be the string ``"M"`` for master-origin
        lines (watchdog, rendezvous)."""
        if tuning.LOG_LEVELS.get(level, tuning.LOG_LEVELS["INFO"]) \
                < self._min_level:
            return
        now = time.time()
        ts = (time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(now))
              + f".{int(now % 1 * 1000):03d}")
        who = f"{rank!s:>{self._rank_width}}"
        print(f"{ts} [{who}/{self.slave_num} {level:<5}] {msg}",
              file=self.log_stream, flush=True)

    # -- telemetry ------------------------------------------------------
    def _record_telemetry(self, rank: int, payload: dict) -> None:
        progress = payload.get("progress") or {}
        with self._lock:
            self._telemetry[rank] = {
                "seq": int(progress.get("seq", 0)),
                "current": progress.get("current"),
                "last": progress.get("last"),
                "phase": progress.get("phase"),
                "current_secs": float(progress.get("current_secs", 0.0)),
                "stats": payload.get("stats") or {},
                "mono": time.monotonic(),
            }

    def _handle_diagnose(self, rank: int, payload: dict) -> None:
        """A slave's bounded collective wait expired: refresh its table
        entry from the report itself (fresher than its last heartbeat),
        then log the cluster-wide diagnosis — ONCE per incident. When
        one rank stalls, every other rank's bounded wait expires in the
        same window; without the debounce (keyed on the cluster's max
        sequence number) a 256-rank job would bury the one useful
        report under ~N full per-rank dumps."""
        self._record_telemetry(rank, payload)
        self._log(rank, "ERROR",
                  f"collective '{payload.get('collective')}' failed: "
                  f"{payload.get('error')}")
        with self._lock:
            incident = max((t["seq"] for t in self._telemetry.values()),
                           default=0)
            repeat = incident == self._diag_incident_seq
            self._diag_incident_seq = incident
        if repeat:
            self._log("M", "WARN",
                      f"rank {rank} reports the same incident (max seq "
                      f"{incident}) — full diagnosis already logged above")
            return
        for line in self.diagnose():
            self._log("M", "WARN", line)

    def diagnose(self) -> list[str]:
        """Render the hang/straggler diagnosis from the heartbeat
        table (obs.telemetry.render_diagnosis)."""
        now = time.monotonic()
        with self._lock:
            table = {r: {**{k: t[k] for k in
                            ("seq", "current", "last", "phase",
                             "current_secs")},
                         "age": now - t["mono"]}
                     for r, t in self._telemetry.items()}
        return telemetry_mod.render_diagnosis(table, self.slave_num)

    def cluster_stats(self) -> dict[str, dict]:
        """Cross-rank skew per collective family from the latest
        heartbeat stats snapshots (schema:
        obs.telemetry.cluster_skew)."""
        with self._lock:
            per_rank = {r: t["stats"] for r, t in self._telemetry.items()
                        if t.get("stats")}
        return telemetry_mod.cluster_skew(per_rank)

    def format_cluster_stats(self) -> str:
        """The ``mp4j-scope report`` table, live from the master."""
        return telemetry_mod.format_skew(self.cluster_stats())

    def _watchdog_loop(self):
        """Diagnose stalled barriers: a generation some ranks reached
        ``stall_timeout`` seconds ago while others never arrived is the
        mismatched-schedule deadlock signature — log the diagnosis once
        per generation. Logging only; the barrier stays fail-stop."""
        tick = min(1.0, max(0.05, self.stall_timeout / 4))
        while not self._stop.wait(tick):
            now = time.monotonic()
            stalled = []
            with self._lock:
                for gen, since in self._barrier_since.items():
                    if (gen in self._barrier_waiting
                            and gen not in self._diagnosed_gens
                            and now - since > self.stall_timeout):
                        self._diagnosed_gens.add(gen)
                        stalled.append(
                            (gen, list(self._barrier_waiting[gen]),
                             now - since))
            for gen, ranks, age in stalled:
                missing = sorted(set(range(self.slave_num)) - set(ranks))
                self._log("M", "WARN",
                          f"barrier gen {gen} stalled for {age:.1f}s: "
                          f"ranks {sorted(ranks)} waiting on ranks "
                          f"{missing}")
                for line in self.diagnose():
                    self._log("M", "WARN", line)

    def _barrier(self, rank: int, gen: int, ch: Channel):
        release = False
        with self._lock:
            waiting = self._barrier_waiting.setdefault(gen, [])
            self._barrier_since.setdefault(gen, time.monotonic())
            waiting.append(rank)
            if len(waiting) == self.slave_num:
                release = True
        if release:
            # release everyone waiting on this generation
            for r, c in enumerate(self._channels):
                c.send_obj(("barrier_release", gen))
            with self._lock:
                del self._barrier_waiting[gen]
                self._barrier_since.pop(gen, None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="ytk-mp4j-tpu rendezvous master")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--slaves", type=int, required=True)
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args(argv)
    m = Master(args.slaves, port=args.port, timeout=args.timeout)
    print(f"mp4j master listening on port {m.port} for {args.slaves} slaves",
          file=sys.stderr, flush=True)
    return m.serve()


if __name__ == "__main__":
    sys.exit(main())
