"""Rendezvous master — the control plane.

The reference runs a master process that slaves connect to: it assigns
ranks, distributes the slave roster (rank -> host:port), serves as the
centralized log sink for ``info()/error()``, coordinates barriers, and
aggregates exit codes at ``close(code)`` (SURVEY.md sections 2, 3a, 3e).

This is that master, rebuilt in Python over the framed-socket transport.
It can run embedded (a thread, for tests and single-host jobs) or as a
CLI: ``python -m ytk_mp4j_tpu.comm.master --port P --slaves N``.

Failure model (ISSUE 5, a deliberate departure from the reference's
fail-stop scope, SURVEY.md section 5): the slave count is still fixed —
no elastic membership — but transient transport faults are recoverable.
The master drives the epoch-fenced abort protocol (resilience.recovery):
an ABORT_REQ from any rank fans out an abort round, all-rank acks gate
the ``abort_go`` release, and unrecoverable states (dead control
connection, stalled round, exhausted retry budget, watchdog-escalated
barrier stall) fan out ONE terminal abort so every surviving rank
raises the same ``Mp4jFatalError`` within its bounded wait.
``MP4J_MAX_RETRIES=0`` restores the reference's exact fail-stop
contract. Rendezvous keeps its optional timeout.

Observability (ISSUE 3): slaves piggyback periodic TELEMETRY heartbeats
(``{progress, stats}``, schema in obs.telemetry) on the control
channel; the master keeps a per-rank table, serves cross-rank skew via
:meth:`Master.cluster_stats`, and turns the paper's worst failure mode
— a silent mismatched-schedule deadlock — into a runtime report: a
slave whose bounded collective wait expires ships a DIAGNOSE, and a
barrier generation stalled past ``stall_timeout`` trips the watchdog;
either way the master logs which ranks trail the cluster's max
collective sequence number, where each laggard last was, and how stale
its heartbeat is. Heartbeats ride the control plane only — they can
never block a data-plane exchange.
"""

from __future__ import annotations

import argparse
import http.server
import json
import secrets
import socket
import sys
import threading
import time

from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.obs import audit as audit_mod
from ytk_mp4j_tpu.obs import health as health_mod
from ytk_mp4j_tpu.obs import metrics as metrics_mod
from ytk_mp4j_tpu.obs import postmortem as postmortem_mod
from ytk_mp4j_tpu.obs import telemetry as telemetry_mod
from ytk_mp4j_tpu.resilience import autoscaler as autoscaler_mod
from ytk_mp4j_tpu.resilience import membership as membership_mod
from ytk_mp4j_tpu.transport.channel import Channel
from ytk_mp4j_tpu.transport.tcp import TcpChannel
from ytk_mp4j_tpu.utils import stats as stats_mod
from ytk_mp4j_tpu.utils import tuner as tuner_mod
from ytk_mp4j_tpu.utils import tuning

# control-plane message kinds (slave -> master)
REGISTER = "register"
LOG = "log"
BARRIER = "barrier"
CLOSE = "close"
TELEMETRY = "telemetry"   # periodic heartbeat: {progress, stats}
DIAGNOSE = "diagnose"     # a slave's bounded wait expired; report it
ABORT_REQ = "abort_req"   # a collective failed; start an abort round
ABORT_ACK = "abort_ack"   # slave finished tearing down the old epoch
SPARE_PING = "spare_ping"  # an idle warm spare proving liveness
ADOPT_ACK = "adopt_ack"   # a spare finished seeding its adopted rank
MANIFEST = "manifest"     # a survivor's adoption manifest contribution
RESIZE = "resize"         # a rank reached a resize_point() boundary
FENCE_ACK = "fence_ack"   # a rank parked at its collective boundary


class _Slot:
    """One connected slave: its channel, a per-channel send lock
    (master->slave pushes may originate on any serve thread), and a
    MUTABLE rank — a shrink round renumbers survivors, and the serve
    thread must attribute every later message to the rank the slave
    currently holds, not the one it registered with (ISSUE 10)."""

    __slots__ = ("rank", "ch", "lock", "dead", "quiet")

    def __init__(self, rank: int, ch: Channel):
        self.rank = rank
        self.ch = ch
        self.lock = threading.Lock()
        # set when the rank is DECLARED dead while its channel still
        # answers (watchdog escalation): the serve thread must stop
        # attributing this zombie's messages to a rank id that a
        # replacement spare may now legitimately hold
        self.dead = False
        # planned eviction in flight (ISSUE 13): inbound messages are
        # dropped WITHOUT closing the channel — the victim's rank id
        # already belongs to the adopted spare, but the channel must
        # stay open until the ("evicted",) release lands on it (a
        # dead-style close here would turn the clean Mp4jEvicted into
        # a "master connection lost" fatal on the victim)
        self.quiet = False


class Master:
    """Rank assignment, roster exchange, log sink, barrier, exit codes,
    plus the cluster telemetry table (heartbeats, skew, hang diagnosis)."""

    def __init__(self, slave_num: int, port: int = 0, host: str = "",
                 log_stream=None, timeout: float | None = 120.0,
                 handshake_timeout: float | None = 5.0,
                 stall_timeout: float | None = 60.0,
                 dead_rank_secs: float | None = None,
                 metrics_port: int | None = None,
                 postmortem_dir: str | None = None,
                 sink_dir: str | None = None,
                 elastic: str | None = None,
                 spares: int | None = None,
                 adopt_secs: float | None = None,
                 health: bool | None = None,
                 autoscale: str | None = None,
                 autoscale_cooldown: float | None = None,
                 autoscale_budget: int | None = None,
                 provision_hook=None,
                 provision_cmd: str | None = None,
                 autoscale_tick: float = 0.25,
                 tuner: str | None = None):
        """``timeout`` bounds the whole rendezvous; ``handshake_timeout``
        bounds each accepted connection's registration message, so one
        stray dial-in stalls rendezvous briefly instead of consuming the
        entire budget while real slaves queue behind it.
        ``stall_timeout`` arms the barrier watchdog: a barrier
        generation with some ranks still missing after this many
        seconds gets a hang diagnosis logged (once per generation);
        ``None`` disables the watchdog.

        ``dead_rank_secs`` (None reads ``MP4J_DEAD_RANK_SECS``;
        ``float("inf")`` disables escalation, restoring the PR-3
        log-only watchdog) is the ESCALATION threshold (ISSUE 5): a barrier generation or an
        abort round still incomplete after this many seconds means a
        rank is permanently gone or permanently diverged, and the
        watchdog escalates from the PR-3 log-only diagnosis to a
        terminal abort fan-out — every surviving rank raises the same
        clean error instead of relying on its local timeout. It is
        deliberately much larger than ``stall_timeout``: the diagnosis
        is cheap and reversible, declaring a rank dead is neither.

        ``metrics_port`` (ISSUE 6; None reads ``MP4J_METRICS_PORT``,
        which unset keeps the endpoint off) serves the live metrics
        plane over plain HTTP on the CONTROL plane only: ``/metrics``
        is Prometheus text format, ``/metrics.json`` the same document
        as JSON. ``0`` binds an ephemeral port; the bound port is
        ``self.metrics_port``. ``postmortem_dir`` (None reads
        ``MP4J_POSTMORTEM_DIR``; empty disables) makes a terminal
        abort also write the flight recorder's cluster manifest.
        ``sink_dir`` (ISSUE 9; None reads ``MP4J_SINK_DIR`` gated by
        ``MP4J_SINK``; empty disables) names the job's durable-sink
        root in that manifest so ``mp4j-scope postmortem`` joins the
        full-job segment history — the same constructor seam as
        ``postmortem_dir``.

        ``elastic`` (ISSUE 10; None reads ``MP4J_ELASTIC``, default
        ``off``) selects the elastic-membership mode: ``off`` keeps
        the pre-elastic contract (a dead rank is a job-wide
        ``Mp4jFatalError``), ``replace`` adopts a warm spare into the
        dead rank's id at the next epoch (bit-exact continuation),
        ``shrink`` renumbers the survivors and continues at n-1.
        ``spares`` (None reads ``MP4J_SPARES``) is how many warm-spare
        registrations rendezvous waits for before the job starts;
        spares may also register later, mid-job. ``adopt_secs`` (None
        reads ``MP4J_ADOPT_SECS``) bounds each adoption handshake
        before the next spare is tried.

        ``health`` (ISSUE 12; None reads ``MP4J_HEALTH``, default on)
        arms the streaming health engine (:mod:`ytk_mp4j_tpu.obs.
        health`): every heartbeat fold also feeds per-rank baselines
        and the detector set, verdict transitions are pushed to the
        subject rank's recovery log + durable sink and exported on
        ``/metrics``, and :meth:`health_status` is the operator hook
        the autoscaler calls — the health plane recommends, the
        AUTOSCALER acts.

        ``autoscale`` (ISSUE 13; None reads ``MP4J_AUTOSCALE``,
        default ``off``) arms mp4j-autopilot
        (:mod:`ytk_mp4j_tpu.resilience.autoscaler`): the controller
        loop that reads :meth:`health_status` and drives the
        membership machinery — planned eviction of
        ``EVICT_RECOMMENDED`` ranks, spare auto-provisioning via
        ``provision_hook`` (a callable receiving this master) or
        ``provision_cmd`` (None reads ``MP4J_PROVISION_CMD``), and
        grow approval at ``resize_point()`` boundaries — behind the
        cooldown (``autoscale_cooldown`` /
        ``MP4J_AUTOSCALE_COOLDOWN_SECS``), budget
        (``autoscale_budget`` / ``MP4J_AUTOSCALE_BUDGET``),
        audit-green and circuit-breaker safety rails. ``observe``
        runs the controller but only LOGS would-be actions;
        ``autoscale_tick`` paces the loop (tests).

        ``tuner`` (ISSUE 15; None reads ``MP4J_TUNER``, default
        ``observe``) arms the master's half of the self-tuning data
        plane: the controller watches the health engine's cause-aware
        dominator rows and — in ``act`` mode — demotes a persistently
        wire-dominated host leader through a FENCED topology update
        (every rank parked at the same collective boundary, the
        override pushed, the fence released), and trips every rank's
        tuner back to static defaults on any cross-rank audit
        divergence. ``observe`` records would-be demotions only."""
        self.slave_num = slave_num
        self.timeout = timeout
        self.handshake_timeout = handshake_timeout
        self.stall_timeout = stall_timeout
        self.dead_rank_secs = tuning.dead_rank_secs(dead_rank_secs)
        # elastic knobs validated BEFORE any socket binds (a knob
        # conflict must not leak a bound listener out of a failed
        # constructor — the metrics-server precedent)
        self.elastic = tuning.elastic_mode(elastic)
        self._spares_expected = tuning.spares(spares)
        self._adopt_secs = tuning.adopt_secs(adopt_secs)
        self.log_stream = log_stream if log_stream is not None else sys.stderr
        # log sink config: validated once at construction (a typo'd
        # MP4J_LOG_LEVEL fails the job here, not silently mid-run)
        self._min_level = tuning.LOG_LEVELS[tuning.log_level()]
        self._rank_width = max(1, len(str(max(slave_num - 1, 0))))
        # job id (ISSUE 7): rides the rendezvous reply and namespaces
        # every shm segment this job's peer pairs create, so two jobs
        # on one host can never collide on a segment name
        self.job_id = secrets.token_hex(4)
        # job identity stamps (ISSUE 18): the fleet poller correlates
        # a job's /metrics.json and /health.json documents and detects
        # a master restart (new job_id at the same URL) without
        # heuristics. Wall clock: identity for humans/scrapers across
        # hosts, never duration arithmetic
        # mp4j-lint: disable=R11 (identity timestamp, not a duration)
        self.started_wall = time.time()
        # bumped under the lock at every roster publication
        # (rendezvous, replace, shrink, grow) — scrapers distinguish
        # "same job, new roster" from "same roster, fresh numbers"
        self._roster_gen = 0
        # rendezvous listen socket — sanctioned raw-socket site: the
        # master IS the control plane the transport SPI is negotiated
        # over (mp4j-lint R12 baseline)
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host or "0.0.0.0", port))
        self._server.listen(slave_num * 2)
        self.port = self._server.getsockname()[1]
        # the address the master ADVERTISES to out-of-process tooling
        # (the autoscaler's MP4J_PROVISION_CMD env): the explicit bind
        # host when given, else this machine's hostname — a
        # wildcard-bound master must not hand a provisioner on
        # another host a loopback address (ISSUE 13)
        self.host = host or ""
        self._slots: list[_Slot] = []           # by CURRENT rank
        self._exit_codes: dict[int, int] = {}
        self._barrier_waiting: dict[int, list[int]] = {}  # gen -> ranks
        self._barrier_since: dict[int, float] = {}        # gen -> mono ts
        # highest generation ever released: an adopted joiner seeded
        # from a manifest sampled a beat early may re-send an already-
        # released generation — release it back to that rank alone
        # instead of opening a ghost generation nobody else will join
        # (ISSUE 10)
        self._barrier_max_released = -1
        self._diagnosed_gens: set[int] = set()
        self._diag_incident_seq: int | None = None  # debounce key
        # recovery protocol state (ISSUE 5)
        self._abort_epoch = 0                   # highest epoch fanned out
        self._abort_acks: set[int] = set()      # ranks acked current round
        self._abort_progress: dict[int, tuple[int, bool]] = {}
        self._abort_since: float | None = None  # mono ts of open round
        self._departed: dict[int, str] = {}     # rank -> why it left
        self._fatal_msg: str | None = None      # terminal abort, once
        # elastic membership (ISSUE 10): warm-spare pool + the open
        # round's membership extension (kind/dead/manifest/adoptions).
        # All guarded by self._lock like the abort state.
        self._membership = membership_mod.MembershipLog(self.elastic)
        self._spare_pool: list[membership_mod.SpareRecord] = []
        self._spare_seq = 0                     # spares ever registered
        self._spare_threads: list[threading.Thread] = []
        self._serve_threads: list[threading.Thread] = []
        self._roster: list[tuple] = []          # current (host, port, fp)
        self._round_kind: str | None = None     # None/'abort'/mode
        self._round_dead: dict[int, str] = {}   # this round's casualties
        self._round_why = ""                    # first casualty's message
        self._round_manifest: dict | None = None
        self._round_manifest_from: int | None = None
        self._round_seq: int | None = None      # joiner resume ordinal
        self._round_adoptions: dict[int, membership_mod.SpareRecord] = {}
        self._round_adopted: dict[int, membership_mod.SpareRecord] = {}
        # planned eviction (ISSUE 13): the LIVE ranks this round
        # replaces proactively, with the victims' pre-adoption slots
        # kept aside for the ("evicted",) release push
        self._round_evict: dict[int, str] = {}
        self._round_evict_slots: dict[int, _Slot] = {}
        # resize/grow state (ISSUE 13): per-generation arrival lists,
        # the donor payload (rank 0's vocab + positions), and the open
        # grow round's adoption bookkeeping
        self._resize_waiting: dict[int, list[int]] = {}
        self._resize_since: dict[int, float] = {}
        self._resize_donor: dict[int, dict] = {}
        # generations CLAIMED by a _complete_resize call: two slave
        # serve threads can see the same generation complete (the
        # last two arrivals race), and the grow decision consults the
        # controller OUTSIDE the lock — without the claim, the loser
        # releases the generation unchanged while the winner's grow
        # is mid-adoption, orphaning it. A generation is completed
        # exactly once; gens are monotone, so claims never recycle.
        self._resize_claimed: set[int] = set()
        # generations RELEASED so far (next expected = this value):
        # the adoption manifest's resize seed takes the max of this
        # and the donor's own count — a donor sampled in the window
        # between a generation's release fan-out and its ctl-side
        # processing reports one generation stale, and a joiner
        # seeded stale would re-send a completed generation that can
        # never fill (watchdog fatal on a healthy job)
        self._resize_released = 0
        self._grow_state: dict | None = None
        # the eviction fence (ISSUE 13): before a planned-eviction
        # round tears anything down, every live rank must be parked
        # at a collective boundary (fence ack) or idle in a barrier/
        # resize wait — quiescence BY CONSTRUCTION, so the round can
        # never manufacture the mixed-progress fatal. A fence that
        # cannot complete cancels with zero disruption (the wire was
        # never touched).
        self._evict_fence: dict | None = None
        self._fence_seq = 0
        self._fence_secs = max(1.0, min(self._adopt_secs, 5.0))
        # rank -> last heartbeat: progress fields + stats + arrival time
        self._telemetry: dict[int, dict] = {}
        # audit plane (ISSUE 8): folds heartbeat digest-record deltas
        # and flags cross-rank divergences (obs.audit.ClusterAuditor);
        # passive — it only ever sees records when slaves run
        # MP4J_AUDIT=verify|capture
        self._auditor = audit_mod.ClusterAuditor(slave_num)
        # health plane (ISSUE 12): the streaming verdict engine,
        # folded right next to the auditor in _record_telemetry; None
        # when disabled so every fold site pays one attribute check
        self._hb_secs = tuning.heartbeat_secs()
        self._health: health_mod.HealthEngine | None = (
            health_mod.HealthEngine(
                slave_num,
                window=tuning.health_window(),
                dominator_ordinals=tuning.health_dominator_ordinals(),
                drift_pct=tuning.health_drift_pct(),
                hb_secs=self._hb_secs)
            if tuning.health_enabled(health) else None)
        # autoscaler (ISSUE 13): knobs validated even when off (the
        # PR 5 discipline — a typo'd MP4J_AUTOSCALE_COOLDOWN_SECS
        # fails setup, not the first action); the controller itself
        # only exists in observe/act and starts with serve()
        autoscale_mode = tuning.autoscale_mode(autoscale)
        tuning.autoscale_cooldown_secs(autoscale_cooldown)
        tuning.autoscale_budget(autoscale_budget)
        self._autoscaler: autoscaler_mod.Autoscaler | None = None
        if autoscale_mode != "off":
            self._autoscaler = autoscaler_mod.Autoscaler(
                self, mode=autoscale_mode,
                cooldown_secs=autoscale_cooldown,
                budget=autoscale_budget,
                provision_hook=provision_hook,
                provision_cmd=provision_cmd,
                tick_secs=autoscale_tick)
        # self-tuning data plane, master half (ISSUE 15): the tuner
        # controller state — leader overrides live + proposed, the
        # audit trip latch, event history. Guarded by its own lock
        # (ticks run on per-slave serve threads); pushes happen
        # outside it (the outbox discipline).
        self._tuner_mode = tuning.tuner_mode(tuner)
        self._tuner_ctl: dict | None = None
        if self._tuner_mode != "off":
            self._tuner_ctl = {
                "mode": self._tuner_mode, "overrides": {},
                "version": 0, "demotions": 0, "tripped": None,
                "last_action": 0.0, "event_seq": 0,
                "events": [],
            }
        self._tuner_lock = threading.Lock()
        # demotion cooldown: several decision windows, so one fence
        # cancel (a rank deep in compute) retries calmly, not per beat
        self._tuner_cooldown = max(5.0, tuning.tuner_window_secs() * 4)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.final_code: int | None = None
        # -- live metrics plane (ISSUE 6) -------------------------------
        self._postmortem_dir = (tuning.postmortem_dir()
                                if postmortem_dir is None
                                else str(postmortem_dir))
        # durable-sink root (ISSUE 9): the master never writes
        # segments itself, but the manifest records where the ranks'
        # sinks are so `mp4j-scope postmortem` can join full-job
        # history into the report
        if sink_dir is None:
            self._sink_dir = (tuning.sink_dir()
                              if tuning.sink_enabled() else "")
        else:
            self._sink_dir = str(sink_dir)
        self._metrics_window = tuning.metrics_window_secs()
        # per-rank + cluster rate rings, fed on every heartbeat fold;
        # cluster totals are maintained incrementally (O(1 rank) per
        # beat), not re-summed across the fleet under the lock
        self._rank_windows: dict[int, metrics_mod.RateWindow] = {}
        self._rank_totals: dict[int, dict[str, float]] = {}
        self._cluster_totals: dict[str, float] = {}
        # cluster histogram/counter aggregate, folded incrementally
        # from each heartbeat's metrics_delta (never re-summed across
        # the fleet at scrape time)
        self._cluster_metrics: dict = {"counters": {}, "gauges": {},
                                       "histograms": {}}
        self._cluster_window = metrics_mod.RateWindow(
            self._metrics_window)
        self._metrics_server: http.server.ThreadingHTTPServer | None = None
        self.metrics_port: int | None = None
        want_port = tuning.metrics_port(override=metrics_port)
        if want_port is not None:
            try:
                self._start_metrics_server(host, want_port)
            except BaseException:
                # don't leak the already-bound listeners (data plane,
                # and the metrics socket if it bound before the fail)
                # out of a failed constructor — a retry Master on the
                # same explicit port would hit EADDRINUSE until GC
                self._stop_metrics_server()
                self._server.close()
                raise

    # ------------------------------------------------------------------
    def serve(self) -> int:
        """Run rendezvous then the control loop; returns aggregate exit
        code (0 iff every slave closed with 0)."""
        try:
            return self._serve()
        finally:
            # every listener must die with serve() on EVERY path — a
            # rendezvous timeout raising past a leaked HTTP server or
            # a still-bound data-plane socket would hold the port
            # against the retry Master
            self._server.close()
            self._write_postmortem_manifest()
            self._stop_metrics_server()

    def _serve(self) -> int:
        self._rendezvous()
        with self._lock:
            for slot in self._slots:
                t = threading.Thread(target=self._serve_slave,
                                     args=(slot,), daemon=True,
                                     name=f"master-slave{slot.rank}")
                t.start()
                self._serve_threads.append(t)
        # late spare registrations (ISSUE 10): a replacement spare may
        # dial in any time after the job started; the rendezvous
        # listener stays open for exactly that
        spare_accept = threading.Thread(target=self._spare_accept_loop,
                                        daemon=True,
                                        name="mp4j-spare-accept")
        spare_accept.start()
        # the watchdog now also drives the dead-rank ESCALATION
        # (ISSUE 5): it must run even with stall_timeout=None —
        # disabling the diagnosis must not silently disable the
        # terminal abort that bounds every recovery wait. Only when
        # BOTH functions are off (dead_rank_secs=inf too) is there
        # nothing it could ever do — skip the thread instead of
        # waking at 1 Hz for the job's lifetime
        watchdog = None
        if (self.stall_timeout is not None
                or self.dead_rank_secs != float("inf")):
            watchdog = threading.Thread(target=self._watchdog_loop,
                                        daemon=True,
                                        name="mp4j-watchdog")
            watchdog.start()
        # the autoscaler controller loop (ISSUE 13): observes/acts on
        # health verdicts for the job's lifetime; the shared stop
        # event ends it with serve()
        if self._autoscaler is not None:
            self._autoscaler.start(self._stop)
        try:
            # the list GROWS when a spare is adopted (its serve thread
            # becomes the rank's), so re-read it until drained
            i = 0
            while True:
                with self._lock:
                    if i >= len(self._serve_threads):
                        break
                    t = self._serve_threads[i]
                i += 1
                t.join()
        finally:
            self._stop.set()
            # unadopted spares idle in a blocking recv: release them
            # so their constructors raise Mp4jSpareReleased instead of
            # waiting out a timeout against a finished job
            with self._lock:
                fatal_msg = self._fatal_msg
            self._release_spares(
                fatal_msg or "job completed without adopting "
                "this spare")
        if watchdog is not None:
            watchdog.join(2.0)
        if self._autoscaler is not None:
            self._autoscaler.join(2.0)
        # serve()'s finally closes the listener, refreshes the
        # flight-recorder manifest with the FINAL table (the slaves'
        # fatal-path telemetry flushes landed after the fan-out-time
        # write) and stops the endpoint
        with self._lock:
            codes = [self._exit_codes.get(r, 1)
                     for r in range(self.slave_num)]
            final = max(codes) if codes else 0
            self.final_code = final
        return final

    def serve_in_thread(self) -> "Master":
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name="mp4j-master")
        self._thread.start()
        return self

    def join(self, timeout: float | None = None):
        if self._thread is not None:
            self._thread.join(timeout)

    # ------------------------------------------------------------------
    def _rendezvous(self):
        """Accept slave registrations; assign ranks in registration order
        (pinned free choice — the reference's exact rule is unverified);
        broadcast the roster to all. Warm spares (``spare: True`` in the
        REGISTER payload, ISSUE 10) are parked in the spare pool instead
        of claiming a rank; rendezvous additionally waits for
        ``spares`` of them so a job configured with spares starts with
        its pool warm."""
        deadline = (None if self.timeout is None
                    else time.monotonic() + self.timeout)
        pending = []  # (channel, (host, listen_port, fp))
        self._server.settimeout(1.0)
        while True:
            with self._lock:
                pooled = len(self._spare_pool)
            if (len(pending) >= self.slave_num
                    and pooled >= self._spares_expected):
                break
            if deadline is not None and time.monotonic() > deadline:
                got = [hp for _, hp in pending]
                raise Mp4jError(
                    f"rendezvous timeout: {len(pending)}/{self.slave_num} "
                    f"slaves and {pooled}/"
                    f"{self._spares_expected} spares registered (heard "
                    f"from: {got or 'none'} — the missing slaves never "
                    "dialed in)")
            # bound the registration handshake: a stray connection that
            # never sends must neither hang rendezvous (no timeout) nor
            # consume the whole budget while real slaves queue behind it
            remaining = (None if deadline is None
                         else max(0.1, deadline - time.monotonic()))
            bounds = [t for t in (remaining, self.handshake_timeout)
                      if t is not None]
            try:
                sock, addr = self._server.accept()
            except socket.timeout:
                continue
            # sanctioned channel-construction site: rendezvous wraps
            # the just-accepted control connection (R12 baseline)
            ch = TcpChannel(sock)
            try:
                ch.set_timeout(min(bounds) if bounds else None)
                # anything a hostile/broken dial-in can do — reset,
                # garbage frame, non-tuple payload, malformed REGISTER
                # body, timeout — must not kill rendezvous for the
                # real slaves, so the whole decode stays in this try
                kind, payload = ch.recv()
                ok = kind == REGISTER and isinstance(payload, dict)
                listen_port = int(payload["listen_port"]) if ok else 0
                host = str(payload.get("host") or addr[0]) if ok else ""
                # host fingerprint (ISSUE 7): opaque token two slaves
                # share iff they can attach each other's shm segments;
                # "" means the slave opted out (MP4J_SHM=0)
                fp = str(payload.get("fp") or "") if ok else ""
                is_spare = bool(payload.get("spare")) if ok else False
            except Exception:
                ok = False
            if not ok:
                ch.close()
                continue
            ch.set_timeout(None)  # control plane is fail-stop from here
            if is_spare:
                self._register_spare(ch, (host, listen_port, fp))
                continue
            if len(pending) >= self.slave_num:
                # every rank is claimed; rendezvous only stays open
                # for the spares it is still waiting on — a surplus
                # non-spare dial-in must not mint an out-of-range rank
                ch.close()
                continue
            pending.append((ch, (host, listen_port, fp)))
        roster = [hp for _, hp in pending]
        slots = [_Slot(rank, ch) for rank, (ch, _) in enumerate(pending)]
        # publish table + slots under the lock (the autoscaler and
        # spare-accept threads are already running); the handshake
        # sends stay OUTSIDE it — send_obj blocks on the peer
        with self._lock:
            self._roster = roster
            self._roster_gen += 1
            self._slots.extend(slots)
        for rank, (ch, _) in enumerate(pending):
            ch.send_obj({"rank": rank, "roster": roster,
                         "job": self.job_id})

    def _serve_slave(self, slot: _Slot):
        ch = slot.ch
        try:
            while True:
                kind, payload = ch.recv()
                if slot.dead:
                    # a zombie: this rank was declared dead and its id
                    # may already belong to a replacement — drop the
                    # connection instead of laundering its messages
                    ch.close()
                    return
                if slot.quiet:
                    # planned eviction in flight (ISSUE 13): the rank
                    # id belongs to the adopted spare, but the channel
                    # must survive until the ("evicted",) release —
                    # drop the message, keep serving
                    continue
                # the CURRENT rank, re-read per message: a shrink round
                # renumbers survivors mid-job (ISSUE 10)
                rank = slot.rank
                if kind == LOG:
                    self._log(rank, payload["level"], payload["msg"])
                elif kind == BARRIER:
                    self._barrier(slot, payload["gen"])
                elif kind == TELEMETRY:
                    self._record_telemetry(rank, payload)
                elif kind == DIAGNOSE:
                    self._handle_diagnose(rank, payload)
                elif kind == ABORT_REQ:
                    self._handle_abort_req(rank, payload)
                elif kind == ABORT_ACK:
                    self._handle_abort_ack(rank, payload)
                elif kind == MANIFEST:
                    self._handle_manifest(rank, payload)
                elif kind == RESIZE:
                    self._handle_resize(slot, payload)
                elif kind == FENCE_ACK:
                    self._handle_fence_ack(rank, payload)
                elif kind == CLOSE:
                    code = payload["code"]
                    with self._lock:
                        already_dead = rank in self._departed
                        if not already_dead:
                            self._exit_codes[rank] = code
                        live_left = (set(range(self.slave_num))
                                     - set(self._departed)
                                     - set(self._exit_codes))
                    with slot.lock:
                        ch.send_obj("closed")
                    ch.close()
                    if already_dead:
                        # this rank's death is already being handled
                        # (declared dead, possibly replaced): its late
                        # close must not re-kill the job
                        return
                    self._mark_departed(
                        rank, f"closed with code {code}")
                    if code != 0 and live_left:
                        # a nonzero close is a defect report; peers
                        # blocked on this rank's data would otherwise
                        # only find out at their own (long) timeouts.
                        # Deliberately NOT an elastic trigger: the
                        # process defected with its own error — its
                        # state is suspect, replacement would launder a
                        # defect into "recovery"
                        self._fatal_abort(
                            f"rank {rank} exited with code {code} "
                            "before the job completed; aborting the "
                            "job")
                    return
                else:
                    self._log(rank, "ERROR", f"unknown message {kind!r}")
        except Exception as e:
            # a dead slave (reset, EOF, corrupt frame) marks a nonzero
            # exit code and the master keeps serving the others — but
            # no longer silently (ISSUE 5): a lost connection means the
            # process died without closing, so the job cannot complete
            # under MP4J_ELASTIC=off. The elastic modes (ISSUE 10)
            # dispatch through _on_rank_dead instead: replacement from
            # a warm spare, or a contiguous shrink of the survivors.
            if slot.dead or slot.quiet:
                # this rank was ALREADY declared dead — or released by
                # a planned eviction (ISSUE 13), whose clean process
                # exit closes the channel — either way the error is
                # expected aftermath, and a shrink may meanwhile have
                # renumbered a healthy survivor into slot.rank, so a
                # fresh declaration here would kill THAT rank (found
                # by the ISSUE 12 chaos loop: the health-alert
                # dispatch shifted this race's timing, but the hole
                # predates it)
                self._log(slot.rank, "INFO",
                          f"evicted/declared-dead rank's channel "
                          f"closed: {e!r}")
                return
            rank = slot.rank
            self._log(rank, "ERROR", f"slave connection lost: {e!r}")
            with self._lock:
                self._exit_codes.setdefault(rank, 1)
            self._on_rank_dead(
                rank, f"connection lost ({e!r})",
                f"rank {rank} is dead (connection lost: {e!r}); "
                "aborting the job")

    # -- recovery protocol (ISSUE 5) ------------------------------------
    def _send_to(self, rank: int, obj) -> None:
        """Push one control message to a slave; a rank that dies while
        we push is marked departed, never crashes a serve thread."""
        try:
            with self._lock:
                slot = self._slots[rank]
            with slot.lock:
                slot.ch.send_obj(obj)
        except (Mp4jError, OSError):
            self._mark_departed(rank, "unreachable on push")

    def _live_ranks(self) -> set[int]:
        with self._lock:
            return set(range(self.slave_num)) - set(self._departed)

    def _mark_departed(self, rank: int, why: str) -> None:
        with self._lock:
            self._departed.setdefault(rank, why)
            pending = self._abort_since is not None
        if pending:
            # an open abort round can never complete without this rank
            # — terminal under MP4J_ELASTIC=off; the elastic modes
            # extend the round into a membership round instead
            self._on_rank_dead(
                rank, why,
                f"rank {rank} left during recovery ({why}); "
                "aborting the job")

    def _handle_abort_req(self, rank: int, payload: dict) -> None:
        if payload.get("fatal"):
            self._fatal_abort(
                f"terminal abort requested by rank {rank}: "
                f"{payload.get('error')}")
            return
        target = int(payload.get("epoch", 0)) + 1
        with self._lock:
            if target <= self._abort_epoch:
                dup = True      # round already fanned out; debounce
            else:
                dup = False
                self._open_round_locked(target)
                dead = dict(self._departed)
        self._log(rank, "ERROR",
                  f"collective '{payload.get('collective')}' failed "
                  f"(epoch {payload.get('epoch')}): "
                  f"{payload.get('error')}")
        if dup:
            return
        if dead:
            msg = (f"cannot recover: rank(s) {sorted(dead)} already gone "
                   f"({'; '.join(f'{r}: {w}' for r, w in sorted(dead.items()))})")
            if self.elastic == "off":
                self._fatal_abort(msg)
                return
            # elastic (ISSUE 10): the departed ranks become this
            # round's casualties — the round just opened fans out
            # below, then the membership machinery takes over
            self._log("M", "WARN",
                      f"abort round -> epoch {target}: tearing down "
                      f"the data plane on all surviving ranks")
            for r in sorted(self._live_ranks()):
                self._send_to(r, ("abort", target))
            self._begin_membership(dead, msg)
            return
        self._log("M", "WARN",
                  f"abort round -> epoch {target}: tearing down the "
                  f"data plane on all {self.slave_num} ranks")
        for r in sorted(self._live_ranks()):
            self._send_to(r, ("abort", target))

    def _open_round_locked(self, target: int) -> None:
        """Reset the round state for a new abort round (caller holds
        the lock and has verified ``target`` advances the epoch)."""
        self._abort_epoch = target
        self._abort_acks = set()
        self._abort_progress = {}
        self._abort_since = time.monotonic()
        self._round_kind = "abort"
        self._round_dead = {}
        self._round_why = ""
        self._round_manifest = None
        self._round_manifest_from = None
        self._round_seq = None
        self._round_adoptions = {}
        self._round_adopted = {}
        self._round_evict = {}
        self._round_evict_slots = {}

    def _handle_abort_ack(self, rank: int, payload: dict) -> None:
        with self._lock:
            if int(payload.get("epoch", 0)) != self._abort_epoch:
                return          # ack for a stale round
            self._abort_acks.add(rank)
            self._abort_progress[rank] = (int(payload.get("seq", 0)),
                                          bool(payload.get("inflight")))
        self._try_advance_round()

    def _handle_manifest(self, rank: int, payload: dict) -> None:
        """A survivor's adoption-manifest contribution (ISSUE 10):
        pinned keycodec vocabularies + its progress/barrier position."""
        with self._lock:
            if (int(payload.get("epoch", 0)) != self._abort_epoch
                    or self._round_kind not in ("replace", "evict")):
                return          # stale round, or mode changed
            self._round_manifest = payload
            self._round_manifest_from = rank
        self._try_advance_round()

    # -- resize points + grow mode (ISSUE 13) ---------------------------
    def _handle_resize(self, slot: _Slot, payload: dict) -> None:
        """A rank reached a ``resize_point()`` boundary. Arrivals
        collect per generation like barriers; rank 0's message carries
        the canonical vocabulary export (at a quiesced boundary every
        rank's codecs are identical by construction — the sync rounds
        grow them lockstep). When the last rank arrives the round
        completes: grow under ``MP4J_ELASTIC=grow`` +
        ``MP4J_AUTOSCALE=act`` (behind the autoscaler's rails), or a
        no-change release."""
        gen = int(payload.get("gen", 0))
        with self._lock:
            rank = slot.rank
            fatal = self._fatal_msg
            if fatal is None:
                waiting = self._resize_waiting.setdefault(gen, [])
                self._resize_since.setdefault(gen, time.monotonic())
                if rank not in waiting:
                    waiting.append(rank)
                if payload.get("vocab") is not None:
                    self._resize_donor[gen] = dict(payload)
        if fatal is not None:
            # like a barrier into a dead job: re-push the terminal
            self._send_to(rank, ("abort_fatal", fatal))
            return
        self._check_resize_complete()
        # a resize arrival is a boundary too (ISSUE 13)
        self._check_fence()

    def _check_resize_complete(self) -> None:
        """Complete every resize generation all CURRENT ranks have
        reached. Callers re-invoke after membership changes (a shrink
        may have removed the only missing arrival) — one pass per
        call."""
        with self._lock:
            # strictly IN ORDER, and never while a grow is in flight:
            # freshly adopted joiners resize at gen+1 against the OLD
            # slave_num (it only advances at grow finalize), so an
            # arrival-count check alone would complete gen+1 for the
            # joiners while the survivors are still inside gen's grow
            # — the release paths bump _resize_released and re-invoke
            # this scan, so held generations complete on their turn
            done = [gen for gen, ranks
                    in self._resize_waiting.items()
                    if len(ranks) >= self.slave_num
                    and gen == self._resize_released
                    and self._grow_state is None]
        for gen in sorted(done):
            self._complete_resize(gen)

    def _complete_resize(self, gen: int) -> None:
        """All ranks quiesced at resize generation ``gen``: grow when
        the mode + the autoscaler's safety rails allow, else release
        unchanged. The grow decision consults the autoscaler OUTSIDE
        the master lock (lock discipline: master -> controller only)."""
        with self._lock:
            if gen not in self._resize_waiting:
                return          # already completed (re-entry)
            if gen in self._resize_claimed:
                return          # another serve thread owns this gen
            self._resize_claimed.add(gen)
            if self._grow_state is not None \
                    and self._grow_state["gen"] == gen:
                # THIS generation's grow is mid-adoption (a joiner's
                # early next-gen resize_point can re-trigger the
                # completeness scan): releasing it unchanged here
                # would orphan the grow — survivors resume at the old
                # n while the joiners arrive at n+k. The finalize (or
                # abort) path owns this generation's release.
                return
            donor = self._resize_donor.get(gen)
            avail = [s for s in self._spare_pool
                     if s.alive and s.adopting_rank is None]
            can_grow = (self.elastic == "grow"
                        and self._fatal_msg is None
                        and self._abort_since is None
                        and self._grow_state is None
                        # an armed eviction fence owns the quiesce: a
                        # grow starting under it would race the
                        # fence's round into two concurrent
                        # membership changes over one roster
                        and self._evict_fence is None
                        and donor is not None and bool(avail))
            audit = self._auditor.status()
            ranks = list(self._resize_waiting[gen])
        k = 0
        if can_grow and self._autoscaler is not None:
            k = self._autoscaler.approve_grow(len(avail), audit)
        if k <= 0:
            with self._lock:
                self._resize_waiting.pop(gen, None)
                self._resize_since.pop(gen, None)
                self._resize_donor.pop(gen, None)
                self._resize_released = max(self._resize_released,
                                            gen + 1)
            for r in ranks:
                self._send_to(r, ("resize_go", gen, None))
            self._check_resize_complete()
            return
        adopts: list = []
        with self._lock:
            # revalidate under the lock (a spare may have died while
            # the controller deliberated)
            avail = [s for s in self._spare_pool
                     if s.alive and s.adopting_rank is None][:k]
            if not avail or self._grow_state is not None \
                    or self._abort_since is not None:
                chosen = []
            else:
                chosen = avail
            if not chosen:
                # the approved grow DROPPED at revalidation (the
                # spare died / a round opened while the controller
                # deliberated): nothing was touched, so the
                # controller's pending 'grow' must settle as a benign
                # RETRY, not bleed out at the deadline as a breaker
                # failure — record the cancel event it resolves on
                self._membership.note_grow_cancel(
                    gen, "grow dropped at revalidation: spare lost "
                    "or a round opened while the controller "
                    "deliberated")
                self._resize_waiting.pop(gen, None)
                self._resize_since.pop(gen, None)
                self._resize_donor.pop(gen, None)
                self._resize_released = max(self._resize_released,
                                            gen + 1)
            else:
                base = self.slave_num
                grown = membership_mod.grow_roster(
                    self._roster, [rec.entry for rec in chosen])
                epoch = self._abort_epoch
                now = time.monotonic()
                pending: dict[int, membership_mod.SpareRecord] = {}
                for i, rec in enumerate(chosen):
                    rec.adopting_rank = base + i
                    rec.grow = True
                    rec.adopt_since = now
                    pending[base + i] = rec
                self._grow_state = {
                    "gen": gen, "pending": pending, "adopted": {},
                    "roster": grown, "epoch": epoch,
                    "resume_seq": int(donor.get("seq", 0)),
                    # kept for mid-grow adoption retries: a
                    # replacement joiner must seed from the SAME
                    # donor payload (barrier position, vocabulary)
                    # as the spare it replaces
                    "donor": dict(donor),
                }
                for i, rec in enumerate(chosen):
                    adopts.append((base + i, rec,
                                   self._grow_adopt_info(
                                       base + i, grown, donor, gen,
                                       epoch, "grow")))
        if not adopts:
            for r in ranks:
                self._send_to(r, ("resize_go", gen, None))
            self._check_resize_complete()
            return
        for r, rec, info in adopts:
            self._log("M", "WARN",
                      f"grow: adopting spare #{rec.idx} into NEW "
                      f"rank {r} (resize {gen}, epoch "
                      f"{info['epoch']})")
            self._send_spare(rec, ("adopt", info))

    def _grow_adopt_info(self, rank: int, roster: list, donor: dict,
                         gen: int, epoch: int, why: str) -> dict:
        """ONE builder for the grow adoption message — the initial
        adoptions and the mid-grow retry must seed joiners from the
        identical donor payload shape, or a field added to one path
        silently mis-seeds joiners adopted via the other (the
        parked-barrier / divergent-codes class)."""
        seq = int(donor.get("seq", 0))
        return {
            "rank": rank, "epoch": epoch, "roster": list(roster),
            "job": self.job_id, "grow": True, "seq": seq,
            "stats_seq": int(donor.get("stats_seq", seq)),
            "barrier_gen": int(donor.get("barrier_gen", 0)),
            # the joiner's NEXT resize pairs with the survivors' next
            "resize_gen": gen + 1,
            "vocab": donor.get("vocab") or {},
            "watermark": self._auditor.verified_seq,
            "why": why,
        }

    def _try_advance_grow(self) -> None:
        """Every grow adoption acked: advance the roster/slave_num,
        record the event, and release the resize generation to the
        pre-existing ranks with the grown roster."""
        with self._lock:
            gs = self._grow_state
            if gs is None or gs["pending"] or self._fatal_msg is not None:
                return
            self._grow_state = None
            gen = gs["gen"]
            new_ranks = sorted(gs["adopted"])
            old_n = self.slave_num
            self._roster = gs["roster"]
            self._roster_gen += 1
            self.slave_num = len(self._roster)
            self._rank_width = max(
                1, len(str(max(self.slave_num - 1, 0))))
            self._membership.note_grow(new_ranks, gs["epoch"], gen)
            audit_lines = self._auditor.note_grow(
                self.slave_num, gs["resume_seq"])
            if self._health is not None:
                self._health.note_grow(self.slave_num)
            ranks = [r for r in self._resize_waiting.pop(gen, [])
                     if r not in self._departed]
            self._resize_since.pop(gen, None)
            self._resize_donor.pop(gen, None)
            self._resize_released = max(self._resize_released,
                                        gen + 1)
            info = {"roster": self._roster, "grown": new_ranks,
                    "gen": gen}
        for line in audit_lines:
            self._log("M", "ERROR", line)
        self._log("M", "WARN",
                  f"grow round complete: {old_n} -> {self.slave_num} "
                  f"rank(s) (new: {new_ranks}); releasing resize "
                  f"{gen}")
        for r in ranks:
            self._send_to(r, ("resize_go", gen, info))
        # a held NEXT generation (the joiners resize early) may be
        # complete at the grown slave_num now
        self._check_resize_complete()

    def _retry_grow_adoption(self, rank: int, why: str) -> None:
        """A grow adoption failed: when NO other joiner has been
        seeded yet (their roster copies would hold the dead spare's
        listen address for this rank), try the next available spare
        for the same NEW rank id; otherwise roll the whole grow back
        — degrading a growth to a no-op is always safe (nobody
        depends on ranks that never existed)."""
        abort = None
        adopt = None
        with self._lock:
            gs = self._grow_state
            if gs is None:
                return
            rec = next((s for s in self._spare_pool
                        if s.alive and s.adopting_rank is None), None)
            if rec is None:
                abort = why + "; warm-spare pool exhausted"
            elif gs["adopted"] or gs["pending"]:
                abort = (why + "; other joiners already hold the "
                         "promised roster — rolling the grow back")
            else:
                rec.adopting_rank = rank
                rec.grow = True
                rec.adopt_since = time.monotonic()
                gs["pending"][rank] = rec
                # the grown roster promised THIS listen address for
                # the new rank — swap the replacement's entry in
                gs["roster"][rank] = rec.entry
                # seed from the SAME donor payload as the spare this
                # one replaces (one builder: _grow_adopt_info)
                adopt = (rank, rec, self._grow_adopt_info(
                    rank, gs["roster"], gs.get("donor") or {},
                    gs["gen"], gs["epoch"], "grow (retry)"))
        if abort is not None:
            self._abort_grow(abort)
            return
        r, rec, info = adopt
        self._log("M", "WARN",
                  f"grow: retrying NEW rank {r} with spare "
                  f"#{rec.idx} ({why})")
        self._send_spare(rec, ("adopt", info))

    def _abort_grow(self, reason: str) -> None:
        """Roll a failed grow back: release every already-seeded
        joiner with a clean ``Mp4jEvicted``, release the resize
        generation UNCHANGED to the waiting ranks, and record the
        failure (the autoscaler's circuit breaker reads it)."""
        with self._lock:
            gs, self._grow_state = self._grow_state, None
            if gs is None:
                return
            gen = gs["gen"]
            victims = {**gs["pending"], **gs["adopted"]}
            for r in victims:
                if 0 <= r < len(self._slots) \
                        and self._slots[r] is not None \
                        and self._slots[r].rank == r:
                    self._slots[r].quiet = True
            ranks = [r for r in self._resize_waiting.pop(gen, [])
                     if r not in self._departed]
            self._resize_since.pop(gen, None)
            self._resize_donor.pop(gen, None)
            self._resize_released = max(self._resize_released,
                                        gen + 1)
            self._membership.note_grow_abort(
                sorted(victims), gen, reason)
        self._log("M", "ERROR",
                  f"grow round ABORTED ({reason}): releasing resize "
                  f"{gen} unchanged; {len(victims)} joiner(s) "
                  "released")
        for r, rec in sorted(victims.items()):
            try:
                rec.ch.send_obj(("evicted",
                                 f"grow round aborted: {reason}"))
            except (Mp4jError, OSError):
                pass
        with self._lock:
            for r in victims:
                if 0 <= r < len(self._slots) \
                        and self._slots[r] is not None:
                    self._slots[r].dead = True
        for r in ranks:
            self._send_to(r, ("resize_go", gen, None))
        self._check_resize_complete()

    # -- elastic membership (ISSUE 10) ----------------------------------
    def _on_rank_dead(self, rank: int, why: str, fatal_msg: str) -> None:
        """Central dead-rank dispatch. ``fatal_msg`` is EXACTLY the
        message the pre-elastic master fanned out — used verbatim when
        elastic membership is off (the MP4J_ELASTIC=off contract is
        bit-for-bit the old behavior) or cannot help."""
        with self._lock:
            already = self._fatal_msg is not None
            pending = self._abort_since is not None
            # the health plane's DEAD verdict rides the SAME liveness
            # decision, never a second opinion (ISSUE 12)
            dead_alerts = (self._health.note_dead(rank, why)
                           if self._health is not None else [])
        self._dispatch_health_alerts(dead_alerts)
        if self.elastic == "off" or already:
            with self._lock:
                self._departed.setdefault(rank, why)
            if pending:
                # pre-elastic precedence: an open abort round can
                # never complete without this rank, and THAT message
                # is the one the old _mark_departed fanned out first
                self._fatal_abort(
                    f"rank {rank} left during recovery ({why}); "
                    "aborting the job")
            self._fatal_abort(fatal_msg)   # debounced if above fired
            return
        self._begin_membership({rank: why}, fatal_msg)

    def _begin_membership(self, dead: dict[int, str],
                          fatal_msg: str) -> None:
        """Open (or extend) a membership round for the newly dead
        ranks: fan out the abort if no round is open, upgrade the
        round's kind to the elastic mode, request the adoption
        manifest (replace), and push a terminal notice to any declared-
        dead rank whose control channel still answers (a watchdog-
        declared straggler must learn it was replaced, not hang)."""
        notify: list[tuple[_Slot, Channel]] = []
        fan_abort = False
        manifest_req: int | None = None
        fatal: str | None = None
        # a death outranks an in-flight grow: its joiners were seeded
        # at an epoch this round is about to retire — roll the grow
        # back before the membership round claims the spare pool
        with self._lock:
            grow_pending = self._grow_state is not None
        if grow_pending and dead:
            self._abort_grow(
                f"membership round opened (rank(s) {sorted(dead)} "
                "dead)")
        with self._lock:
            if self._fatal_msg is not None:
                return
            # grow mode's death response IS replacement (it has a
            # spare pool by construction); shrink/replace unchanged
            mode = ("replace" if self.elastic == "grow"
                    else self.elastic)
            fresh = {r: w for r, w in dead.items()
                     if r not in self._round_dead}
            for r, w in dead.items():
                self._departed.setdefault(r, w)
            if self._abort_since is None:
                self._open_round_locked(self._abort_epoch + 1)
                fan_abort = True
            if self._round_evict and dead:
                # a REAL death arrived while a planned eviction was
                # quiescing (ISSUE 13): abandon the eviction — the
                # victim stays a live member of what is now an
                # ordinary membership round, and the autoscaler's
                # pending action resolves as failed. An adoption
                # already assigned to a still-alive victim is
                # released back to the pool; one assigned to a victim
                # that itself just died carries over (the replace
                # path below adopts into exactly that id).
                for r, rec in list(self._round_adoptions.items()):
                    if r in self._round_evict and r not in dead:
                        rec.adopting_rank = None
                        rec.adopt_since = None
                        del self._round_adoptions[r]
                for r in self._round_evict:
                    # the cancel event settles the controller's
                    # pending action as a benign retry NOW — without
                    # it the one-in-flight rail blocks every other
                    # action until the ~25 s deadline, then charges a
                    # breaker strike for an abandonment the master
                    # chose deliberately
                    self._membership.note_evict_cancel(
                        r, 0, "a real death superseded the planned "
                        "eviction")
                self._round_evict = {}
                self._round_evict_slots = {}
            self._round_kind = mode
            for r, w in fresh.items():
                self._round_dead[r] = w
                if not self._round_why:
                    self._round_why = fatal_msg
                slot = (self._slots[r]
                        if 0 <= r < len(self._slots) else None)
                if slot is not None:
                    slot.dead = True
                    notify.append((slot, slot.ch))
            live = set(range(self.slave_num)) - set(self._departed)
            if not live:
                fatal = fatal_msg + "; no surviving rank left"
            elif mode == "replace":
                avail = sum(1 for s in self._spare_pool
                            if s.alive and s.adopting_rank is None)
                if avail < (len(self._round_dead)
                            - len(self._round_adopted)
                            - len(self._round_adoptions)):
                    # today's clean Mp4jFatalError: elasticity was
                    # requested but the pool cannot cover the loss
                    fatal = (fatal_msg
                             + "; no warm spare available to replace "
                             f"rank(s) {sorted(self._round_dead)}")
                elif (self._round_manifest is None
                        and (self._round_manifest_from is None
                             or self._round_manifest_from not in live)):
                    manifest_req = min(live)
                    self._round_manifest_from = manifest_req
            target = self._abort_epoch
        if fatal is not None:
            self._fatal_abort(fatal)
            return
        for slot, ch in notify:
            # best-effort: the rank was DECLARED dead, but a merely
            # wedged process should still raise the same clean error
            try:
                with slot.lock:
                    ch.send_obj(("abort_fatal", fatal_msg))
            except (Mp4jError, OSError):
                pass
        if dead:
            self._log(
                "M", "WARN",
                f"membership round ({mode}) -> epoch {target}: "
                f"rank(s) {sorted(dead)} declared dead "
                f"({'; '.join(f'{r}: {w}' for r, w in sorted(dead.items()))})")
        if fan_abort:
            for r in sorted(self._live_ranks()):
                self._send_to(r, ("abort", target))
        if manifest_req is not None:
            self._send_to(manifest_req, ("manifest_req", target))
        # a real membership round cancels any armed eviction fence
        # (the death outranks the planned action — ISSUE 13)
        self._check_fence()
        self._try_advance_round()

    def _next_spare_locked(self):
        for rec in self._spare_pool:
            if rec.alive and rec.adopting_rank is None:
                return rec
        return None

    def _try_advance_round(self) -> None:
        """Evaluate the open round against its completion condition and
        take the next step: release a plain abort round, start spare
        adoptions, or finalize a membership round. Re-entered whenever
        an input lands — an ack, a departure, the manifest, an adopt
        ack, a spare death."""
        adopts: list[tuple[int, object, dict]] = []
        fatal: str | None = None
        release = None
        with self._lock:
            if self._abort_since is None or self._fatal_msg is not None:
                return
            live = set(range(self.slave_num)) - set(self._departed)
            if not live or not live <= self._abort_acks:
                return
            kind = self._round_kind or "abort"
            epoch = self._abort_epoch
            progress = {r: self._abort_progress.get(r, (0, False))
                        for r in sorted(live)}
            if kind == "evict":
                # the victim's progress is EXCLUDED from the
                # per-collective coherence check, exactly like a dead
                # rank's (ISSUE 13): a persistently slow victim sits
                # one collective BEHIND its peers at quiesce time —
                # the precise state eviction exists to resolve — and
                # its unfinished collective leaves with it (survivors
                # already hold its contributions to everything they
                # completed; the joiner enters the retried collective
                # fresh, the dead-replacement rule)
                progress = {r: p for r, p in progress.items()
                            if r not in self._round_evict}
            mixed = self._mixed_progress(progress)
            if mixed is not None:
                fatal = mixed
            elif kind == "abort":
                self._abort_since = None
                self._round_kind = None
                release = ("abort", epoch, None, sorted(live), [], (),
                           ())
            elif kind in ("replace", "evict"):
                # one adoption path for both variants: `replace` fills
                # DEAD ranks (empty pool is terminal — the job cannot
                # continue at n), `evict` proactively swaps LIVE ranks
                # (ISSUE 13: empty pool ABANDONS the eviction and
                # releases a plain abort — the victim is still a
                # member, so degrading to no-op is strictly safer)
                casualties = (self._round_dead if kind == "replace"
                              else self._round_evict)
                if self._round_manifest is not None:
                    if self._round_seq is None:
                        self._round_seq = membership_mod.joiner_seq(
                            progress)
                    need = [r for r in sorted(casualties)
                            if r not in self._round_adoptions
                            and r not in self._round_adopted]
                    abandon = None
                    for r in need:
                        rec = self._next_spare_locked()
                        if rec is None:
                            if kind == "replace":
                                fatal = (self._round_why
                                         + "; no warm spare available "
                                         f"to replace rank {r}")
                            else:
                                abandon = (
                                    "planned eviction of rank(s) "
                                    f"{sorted(casualties)} abandoned: "
                                    "warm-spare pool exhausted; "
                                    "releasing the round as a plain "
                                    "abort")
                            break
                        rec.adopting_rank = r
                        rec.adopt_since = time.monotonic()
                        self._round_adoptions[r] = rec
                    if abandon is not None:
                        # abandoning is only SOUND when the quiesced
                        # state is coherent INCLUDING the victim: a
                        # victim interrupted one collective behind
                        # would retry ordinal m-1 against survivors
                        # retrying m — raw exchanges carry no
                        # collective tag, so the mispairing is silent
                        # corruption, not an error. Incoherent + no
                        # spare -> hold the round open for a late
                        # spare registration (_register_spare
                        # re-drives it; the watchdog's stalled-round
                        # fatal bounds the wait).
                        full = {r2: self._abort_progress.get(
                                    r2, (0, False))
                                for r2 in sorted(live)}
                        if self._mixed_progress(full) is not None:
                            abandon = None
                    if abandon is not None:
                        self._membership.note_evict_abort(
                            sorted(casualties), epoch, abandon)
                        self._abort_since = None
                        self._round_kind = None
                        self._round_evict = {}
                        self._round_evict_slots = {}
                        self._round_manifest = None
                        self._round_manifest_from = None
                        self._round_seq = None
                        release = ("abort", epoch, None, sorted(live),
                                   [abandon], (), ())
                    elif fatal is None:
                        man = self._round_manifest
                        repl = {r2: rec2.entry for r2, rec2
                                in self._round_adoptions.items()}
                        roster = membership_mod.swap_roster(
                            self._roster, repl)
                        for r in need:
                            rec = self._round_adoptions[r]
                            adopts.append((r, rec, {
                                "rank": r, "epoch": epoch,
                                "roster": roster, "job": self.job_id,
                                "seq": self._round_seq,
                                # the donor's CommStats position (it
                                # counts nested collectives the
                                # recovery ordinal does not) keeps the
                                # joiner's heartbeat seq out of the
                                # skew table's laggard column
                                "stats_seq": int(man.get(
                                    "stats_seq", self._round_seq)),
                                "barrier_gen": int(
                                    man.get("barrier_gen", 0)),
                                # max with the master's released
                                # count: a pending generation needs
                                # the joiner's arrival (donor == the
                                # master then), a just-released one
                                # must not be replayed (see
                                # _resize_released)
                                "resize_gen": max(
                                    int(man.get("resize_gen", 0)),
                                    self._resize_released),
                                "vocab": man.get("vocab") or {},
                                "watermark":
                                    self._auditor.verified_seq,
                                "why": casualties.get(r, ""),
                            }))
                        if (not adopts and set(casualties)
                                <= set(self._round_adopted)):
                            release = self._finalize_replace_locked(
                                epoch, live)
            elif kind == "shrink":
                release = self._finalize_shrink_locked(epoch)
        if fatal is not None:
            self._fatal_abort(fatal)
            return
        for r, rec, info in adopts:
            self._log("M", "WARN",
                      f"adopting spare #{rec.idx} into rank {r} "
                      f"(epoch {epoch}, resume seq {info['seq']})")
            self._send_spare(rec, ("adopt", info))
        if release is None:
            return
        (kind, epoch, info, targets, extra_lines, release_gens,
         evict_notify) = release
        # planned-eviction release (ISSUE 13), ordered for the victim
        # race: the ("evicted",) push rides the still-open channel
        # FIRST (its slot is already quiet, so inbound noise cannot
        # close it), only then does the slot go fully dead — and the
        # epoch releases to the survivors + joiner after that
        for slot, r, msg in evict_notify:
            try:
                with slot.lock:
                    slot.ch.send_obj(("evicted", msg))
            except (Mp4jError, OSError):
                pass    # the victim died anyway; nothing to release
            slot.dead = True
        for line in extra_lines:
            self._log("M", "ERROR", line)
        if kind == "abort":
            self._log("M", "WARN",
                      f"abort round complete: releasing epoch {epoch} "
                      f"to all ranks")
            for r in targets:
                self._send_to(r, ("abort_go", epoch))
        elif kind == "replace":
            self._log("M", "WARN",
                      f"membership round complete: rank(s) "
                      f"{sorted(info['replaced'])} replaced from warm "
                      f"spares; releasing epoch {epoch}")
            for r in targets:
                self._send_to(r, ("abort_go", epoch, info))
        elif kind == "shrink":
            self._log("M", "WARN",
                      f"membership round complete: shrunk to "
                      f"{self.slave_num} rank(s) "
                      f"(dropped {info['shrink']['departed']}); "
                      f"releasing epoch {epoch}")
            for r in targets:
                self._send_to(r, ("abort_go", epoch, info))
            for gen in release_gens:
                for r in range(self.slave_num):
                    self._send_to(r, ("barrier_release", gen))
        # a membership change can complete a pending resize round
        # (shrink: the dead rank was the only missing arrival)
        self._check_resize_complete()

    # -- planned eviction (ISSUE 13) ------------------------------------
    def request_planned_evict(self, rank: int, why: str) -> bool:
        """Proactively replace a LIVE rank from a warm spare at the
        next collective boundary — the autoscaler's actuation hook
        (callable by an operator too). Opens a membership round of
        kind ``evict``: every rank (victim included) quiesces through
        the epoch-fenced abort round, the lowest live NON-victim
        survivor donates the adoption manifest, a spare is adopted
        into the victim's id, and the victim is released with a clean
        :class:`~ytk_mp4j_tpu.exceptions.Mp4jEvicted` while everyone
        else continues bit-exactly — the proactive twin of the
        death-driven replace path.

        Returns False (nothing opened) when the request cannot start:
        wrong elastic mode, a round or fence already open, the rank
        gone, no live peer to donate the manifest, or no spare
        available. Everything is validated HERE under the lock — the
        caller's snapshot may be stale, and a refusal is always safe.

        The quiesce is a two-step: first the soft FENCE parks every
        live rank at its next outermost collective entry (the wire
        untouched — a fence that cannot complete cancels for free),
        and only a fully-fenced cluster opens the abort round, so the
        round's teardown can never manufacture the per-collective
        mixed-progress fatal on a healthy job."""
        why = str(why)[:300]
        with self._lock:
            live = set(range(self.slave_num)) - set(self._departed)
            ok = (self.elastic in ("replace", "grow")
                  and self._fatal_msg is None
                  and self._abort_since is None
                  and self._grow_state is None
                  and self._evict_fence is None
                  and rank in live and len(live) >= 2
                  and self._next_spare_locked() is not None
                  # rendezvous must have seated every rank (a request
                  # this early has no slot to fence)
                  and len(self._slots) >= self.slave_num
                  and 0 <= rank < len(self._slots)
                  and not (self._slots[rank].dead
                           or self._slots[rank].quiet))
            if not ok:
                return False
            self._fence_seq += 1
            token = self._fence_seq
            self._evict_fence = {"token": token, "rank": rank,
                                 "why": why, "acks": {},
                                 "goal": 0,
                                 "since": time.monotonic()}
        self._log("M", "WARN",
                  f"planned eviction: fencing the job at the next "
                  f"collective boundary to replace LIVE rank {rank} "
                  f"({why})")
        for r in sorted(live):
            self._send_to(r, ("fence", token))
        self._check_fence()
        return True

    def _handle_fence_ack(self, rank: int, payload: dict) -> None:
        with self._lock:
            f = self._evict_fence
            if f is None or int(payload.get("token", -1)) != f["token"]:
                return          # stale fence
            f["acks"][rank] = int(payload.get("seq", 0))
        self._check_fence()

    def _check_fence(self) -> None:
        """Evaluate the armed eviction fence: complete it into an
        abort round once every live rank is provably at a boundary
        (fence ack, or idle in a barrier/resize wait — SPMD makes
        those states schedule-equivalent), or cancel it (fence
        release, zero disruption) when it can no longer succeed:
        victim gone, a real round opened, the pool drained, or the
        deadline passed (a rank deep in application compute never
        reaches a boundary — retrying later is free)."""
        start = None
        cancel = None
        advance = None
        push = None     # tuner fence completion (ISSUE 15)
        with self._lock:
            f = self._evict_fence
            if f is None:
                return
            kind = f.get("kind", "evict")
            live = set(range(self.slave_num)) - set(self._departed)
            victim = f["rank"]
            now = time.monotonic()
            if self._fatal_msg is not None:
                cancel = "job is terminally aborting"
            elif self._abort_since is not None:
                cancel = "a membership/abort round opened meanwhile"
            elif self._grow_state is not None:
                # the mirror of _complete_resize's fence guard: two
                # concurrent membership rounds over one roster would
                # finalize in either order and silently resurrect
                # stale entries
                cancel = "a grow round is in flight"
            elif kind == "evict" and (
                    victim not in live or len(live) < 2
                    or self._slots[victim].dead
                    or self._slots[victim].quiet):
                cancel = f"rank {victim} is no longer an evictable " \
                         "member"
            elif kind == "evict" \
                    and self._next_spare_locked() is None:
                cancel = "the warm-spare pool drained"
            elif now - f["since"] > self._fence_secs:
                missing = sorted(live - set(f["acks"]))
                cancel = (f"rank(s) {missing} did not reach a "
                          f"collective boundary within "
                          f"{self._fence_secs:.1f}s")
            else:
                idle = set(f["acks"])
                for ranks in self._barrier_waiting.values():
                    idle.update(ranks)
                for ranks in self._resize_waiting.values():
                    idle.update(ranks)
                # starvation rule (ISSUE 13): a rank parked at an
                # ordinal BEHIND a peer's position starves every
                # in-flight batch that still needs it — advance the
                # laggards to the global max ordinal (acked positions
                # plus the un-acked ranks' heartbeat in-flight seqs)
                # and only complete the fence when every parked rank
                # sits at the SAME boundary
                seqs = set(f["acks"].values())
                hb_max = max(
                    (int(self._telemetry[r]["seq"])
                     for r in live - set(f["acks"])
                     if r in self._telemetry), default=0)
                # the goal never decreases, but an ack BELOW an
                # already-set goal must still be advanced (a rank
                # acking late at a low seq would otherwise stall the
                # fence to its deadline: goal>f["goal"] is false yet
                # the seqs can never equalize)
                goal = max([hb_max, f["goal"],
                            *f["acks"].values()], default=0)
                laggards = [r for r, s in f["acks"].items()
                            if s < goal]
                if kind == "tuner":
                    # the tuner update needs every live rank PARKED at
                    # one boundary via an explicit ack. Starvation
                    # rule, sharpened for hot blocking jobs: a rank
                    # BLOCKED INSIDE ordinal K reports the same
                    # entered-seq as a rank PARKED AT K's entry (the
                    # wrapper bumps before the park), so equal seqs
                    # can still hide a deadlock — the parked ranks
                    # starve the blocked ones. Whenever an unacked
                    # rank's heartbeat position is at or past a parked
                    # rank's, advance the parked ranks PAST that
                    # ordinal (goal = max + 1 — strictly above their
                    # entered seq, which is what wakes the slave-side
                    # park); they run it, everyone converges on the
                    # next boundary and re-acks.
                    acked = set(f["acks"])
                    hb_unacked = [
                        int(self._telemetry[r]["seq"])
                        for r in live - acked if r in self._telemetry]
                    goal = None
                    if live <= acked and len(seqs) <= 1:
                        self._evict_fence = None
                        push = (f["token"], dict(f["payload"]),
                                sorted(live))
                    elif live <= acked:
                        # every rank parked, at UNEQUAL boundaries
                        # (rooted/partial collectives let a rank
                        # complete ordinals a peer never touched):
                        # advance the behind ranks to the front
                        # rank's position — max(seqs) exceeds their
                        # entered seq, so the slave-side park wakes
                        goal = max(max(seqs), f["goal"])
                    elif (f["acks"] and hb_unacked
                          and max(hb_unacked)
                          >= min(f["acks"].values())):
                        goal = max(max(hb_unacked) + 1, f["goal"])
                    if goal is not None:
                        laggards = [r for r, s in f["acks"].items()
                                    if s < goal]
                        if laggards:
                            f["goal"] = goal
                            for r in laggards:
                                del f["acks"][r]
                            advance = (f["token"], goal, laggards)
                elif laggards:
                    f["goal"] = goal
                    for r in laggards:
                        del f["acks"][r]
                    advance = (f["token"], goal, laggards)
                elif live <= idle and len(seqs) <= 1:
                    self._evict_fence = None
                    self._open_round_locked(self._abort_epoch + 1)
                    self._round_kind = "evict"
                    self._round_why = (f"planned eviction of rank "
                                       f"{victim}: {f['why']}")
                    self._round_evict = {victim: f["why"]}
                    self._round_evict_slots = {
                        victim: self._slots[victim]}
                    donor = min(live - {victim})
                    self._round_manifest_from = donor
                    start = (self._abort_epoch, donor, sorted(live))
            if cancel is not None:
                token = f["token"]
                self._evict_fence = None
                if kind == "evict":
                    self._membership.note_evict_cancel(
                        victim, token, cancel)
        if cancel is not None:
            self._log("M", "WARN",
                      f"{'tuner' if kind == 'tuner' else 'eviction'} "
                      f"fence canceled ({cancel}); releasing "
                      "the parked ranks untouched")
            for r in sorted(self._live_ranks()):
                self._send_to(r, ("fence_release", token))
            return
        if advance is not None:
            token, goal, laggards = advance
            self._log("M", "WARN",
                      f"eviction fence: advancing rank(s) {laggards} "
                      f"to ordinal {goal} (a peer's in-flight batch "
                      "still needs them)")
            for r in laggards:
                self._send_to(r, ("fence_advance", token, goal))
            return
        if push is not None:
            # tuner fence complete (ISSUE 15): every live rank is
            # parked at the SAME collective boundary — push the
            # leader overrides (applied on each rank's ctl thread),
            # THEN release the fence: the master channel is ordered,
            # so every rank applies before its collective thread
            # resumes. Atomic topology switch, wire untouched.
            token, overrides, targets = push
            with self._tuner_lock:
                ctl = self._tuner_ctl
                if ctl is not None:
                    ctl["overrides"] = dict(overrides)
                    ctl["version"] += 1
                    ctl["demotions"] += 1
            self._log("M", "WARN",
                      f"tuner fence complete: applying leader "
                      f"overrides {overrides} at a job-wide "
                      "collective boundary")
            for r in targets:
                self._send_to(r, ("tuner_leaders", overrides))
                self._send_to(r, ("fence_release", token))
            self._tuner_event(
                "demote", f"leader overrides {overrides} applied "
                f"(fence token {token})")
            return
        if start is None:
            return
        target, donor, targets = start
        self._log("M", "WARN",
                  f"eviction fence complete: every rank at a "
                  f"boundary; abort round -> epoch {target}")
        for r in targets:
            self._send_to(r, ("abort", target))
        self._send_to(donor, ("manifest_req", target))
        self._try_advance_round()

    def _finalize_replace_locked(self, epoch: int, live: set[int]):
        """All survivors acked, every casualty's spare acked its
        adoption: swap the roster, resurrect the replaced ranks and
        compose the go message (caller holds the lock and fans out).
        Planned evictions (ISSUE 13) finalize through the same path —
        the difference is the victim is ALIVE: its pre-adoption slot
        goes ``quiet`` here (inbound dropped, channel kept) and the
        composed ``evict_notify`` pushes the clean ``("evicted",)``
        release before the epoch go."""
        repl = {r: rec.entry for r, rec in self._round_adopted.items()}
        self._roster = membership_mod.swap_roster(self._roster, repl)
        self._roster_gen += 1
        joiners = sorted(self._round_adopted)
        extra_lines: list[str] = []
        evict_notify: list[tuple[_Slot, int, str]] = []
        for r in joiners:
            rec = self._round_adopted[r]
            self._departed.pop(r, None)
            self._exit_codes.pop(r, None)
            if r in self._round_evict:
                why = self._round_evict.get(r, "")
                self._membership.note_evict(r, epoch, rec.idx, why)
                old = self._round_evict_slots.get(r)
                if old is not None:
                    old.quiet = True
                    evict_notify.append((old, r, (
                        f"rank {r} evicted by the autoscaler and "
                        f"replaced from warm spare #{rec.idx} @ epoch "
                        f"{epoch}: {why}")))
            else:
                self._membership.note_replace(
                    r, epoch, rec.idx, self._round_dead.get(r, ""))
            extra_lines.extend(
                self._auditor.note_replacement(
                    r, self._round_seq or 0))
            if self._health is not None:
                # the joiner starts HEALTHY with fresh baselines; the
                # reset alert is informational (the DEAD alert already
                # reached the durable sinks)
                extra_lines.extend(
                    "health: " + health_mod.format_alert(ev)
                    for ev in self._health.note_replacement(r))
        info = {"replaced": joiners, "roster": self._roster,
                "epoch": epoch}
        targets = sorted(live)
        self._abort_since = None
        self._round_kind = None
        self._round_dead = {}
        self._round_adoptions = {}
        self._round_adopted = {}
        self._round_evict = {}
        self._round_evict_slots = {}
        self._round_manifest = None
        self._round_manifest_from = None
        self._round_seq = None
        return ("replace", epoch, info, targets, extra_lines, (),
                evict_notify)

    def _finalize_shrink_locked(self, epoch: int):
        """All survivors acked a shrink round: renumber them
        contiguously, rebuild every rank-keyed table under the new
        numbering, and compose the go message (caller holds the lock
        and fans out)."""
        dead = set(self._departed)
        mapping = membership_mod.shrink_mapping(self.slave_num, dead)
        new_roster = membership_mod.shrink_roster(self._roster, mapping)
        dead_list = sorted(dead)
        new_slots: list = [None] * len(mapping)
        for old, new in mapping.items():
            slot = self._slots[old]
            slot.rank = new
            new_slots[new] = slot
        self._slots = new_slots
        self._roster = new_roster
        self._roster_gen += 1
        self.slave_num = len(mapping)
        self._rank_width = max(1, len(str(max(self.slave_num - 1, 0))))
        self._exit_codes = {mapping[r]: c for r, c
                            in self._exit_codes.items() if r in mapping}
        self._telemetry = {mapping[r]: t for r, t
                           in self._telemetry.items() if r in mapping}
        self._rank_windows = {mapping[r]: w for r, w
                              in self._rank_windows.items()
                              if r in mapping}
        self._rank_totals = {mapping[r]: t for r, t
                             in self._rank_totals.items() if r in mapping}
        self._departed = {}
        self._abort_progress = {}
        self._auditor.note_shrink(self.slave_num, mapping)
        if self._health is not None:
            self._health.note_shrink(self.slave_num, mapping)
        self._membership.note_shrink(dead_list, mapping, epoch,
                                     self._round_why)
        # pending resize generations renumber like barriers; a
        # generation completed by the shrink is picked up by the
        # _check_resize_complete scan after the release fan-out
        self._resize_waiting = {
            gen: [mapping[r] for r in ranks if r in mapping]
            for gen, ranks in self._resize_waiting.items()}
        # pending barriers renumber too; one now-complete generation
        # (every survivor already arrived, only the dead were missing)
        # releases on the way out
        release_gens = []
        for gen, ranks in list(self._barrier_waiting.items()):
            self._barrier_waiting[gen] = [
                mapping[r] for r in ranks if r in mapping]
            if len(self._barrier_waiting[gen]) == self.slave_num:
                release_gens.append(gen)
                self._barrier_max_released = max(
                    self._barrier_max_released, gen)
                del self._barrier_waiting[gen]
                self._barrier_since.pop(gen, None)
        info = {"shrink": {"roster": new_roster, "ranks": mapping,
                           "departed": dead_list, "epoch": epoch}}
        targets = sorted(mapping.values())
        self._abort_since = None
        self._round_kind = None
        self._round_dead = {}
        self._round_manifest = None
        self._round_manifest_from = None
        self._round_seq = None
        return ("shrink", epoch, info, targets, [], release_gens, ())

    # -- warm spares (ISSUE 10) -----------------------------------------
    def _register_spare(self, ch: Channel, entry: tuple) -> None:
        """Park a warm-spare registration: ack it, pool it, and start
        its serve thread (pings until adopted)."""
        with self._lock:
            idx = self._spare_seq
            self._spare_seq += 1
            rec = membership_mod.SpareRecord(idx, ch, entry)
            self._spare_pool.append(rec)
            # the registration EVENT is what a pending provision
            # action resolves on — a waiting round may claim this
            # spare before any status snapshot shows the pool > 0
            self._membership.note_spare(idx)
        try:
            ch.send_obj({"spare": idx, "job": self.job_id})
        except (Mp4jError, OSError):
            self._spare_gone(rec, "died during registration")
            return
        t = threading.Thread(target=self._serve_spare, args=(rec,),
                             daemon=True, name=f"master-spare{idx}")
        with self._lock:
            self._spare_threads.append(t)
        t.start()
        self._log("M", "INFO",
                  f"warm spare #{idx} registered "
                  f"({entry[0]}:{entry[1]})")
        # a membership round waiting out an exhausted pool (ISSUE 13:
        # an evict round that cannot safely abandon) resumes the
        # moment a fresh spare registers
        self._try_advance_round()

    def _spare_accept_loop(self) -> None:
        """Post-rendezvous listener: only spare registrations are
        accepted mid-job (a late non-spare dial-in has no rank to
        claim)."""
        while not self._stop.is_set():
            try:
                sock, addr = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return          # listener closed with serve()
            ch = TcpChannel(sock)
            try:
                ch.set_timeout(self.handshake_timeout)
                kind, payload = ch.recv()
                ok = (kind == REGISTER and isinstance(payload, dict)
                      and bool(payload.get("spare")))
                entry = ((str(payload.get("host") or addr[0]),
                          int(payload["listen_port"]),
                          str(payload.get("fp") or ""))
                         if ok else None)
            except Exception:
                ok = False
            if not ok:
                ch.close()
                continue
            ch.set_timeout(None)
            self._register_spare(ch, entry)

    def _serve_spare(self, rec) -> None:
        """Read one spare's control channel: liveness pings until an
        adoption completes — then this THREAD becomes the adopted
        rank's serve thread (the channel is the same object; only its
        role changes)."""
        slot = None
        try:
            while True:
                kind, payload = rec.ch.recv()
                if kind == SPARE_PING:
                    rec.last_ping = time.monotonic()
                elif kind == ADOPT_ACK:
                    slot = self._finish_adoption(rec)
                    if slot is not None:
                        break
                elif kind == LOG:
                    self._log(f"s{rec.idx}", payload["level"],
                              payload["msg"])
                elif kind == CLOSE:
                    # a spare shutting down cleanly before adoption
                    try:
                        rec.ch.send_obj("closed")
                    except (Mp4jError, OSError):
                        pass
                    rec.ch.close()
                    self._spare_gone(rec, "closed")
                    return
                # anything else from an unadopted spare is noise
        except Exception as e:
            self._spare_gone(rec, f"connection lost ({e!r})")
            return
        self._serve_slave(slot)

    def _finish_adoption(self, rec):
        """An adopted spare acked: install its channel as the rank's
        slot and hand the round machinery the news. Returns the slot
        (the caller's thread continues as the rank's serve thread), or
        None when the ack is stale."""
        with self._lock:
            r = rec.adopting_rank
            if r is None or self._fatal_msg is not None:
                return None
            rec.adopt_since = None
            slot = _Slot(r, rec.ch)
            if rec.grow:
                # grow adoption (ISSUE 13): a NEW rank id — the slot
                # list extends (acks may land out of rank order; the
                # padding slots fill as their own acks arrive, and the
                # roster/slave_num only advance at grow finalize)
                gs = self._grow_state
                if gs is None or gs["pending"].get(r) is not rec:
                    return None     # grow aborted meanwhile
                del gs["pending"][r]
                gs["adopted"][r] = rec
                while len(self._slots) <= r:
                    self._slots.append(None)
                self._slots[r] = slot
            else:
                self._slots[r] = slot
                self._round_adopted[r] = rec
            if rec in self._spare_pool:
                self._spare_pool.remove(rec)
            # the dead occupant's telemetry must not pollute the
            # joiner's: fresh windows, fresh deltas (cluster TOTALS
            # keep the dead rank's history — it really happened)
            self._telemetry.pop(r, None)
            self._rank_windows.pop(r, None)
            self._rank_totals.pop(r, None)
            self._serve_threads.append(threading.current_thread())
        self._log("M", "WARN",
                  f"spare #{rec.idx} adopted as rank {r}"
                  + (" (grow)" if rec.grow else ""))
        if rec.grow:
            self._try_advance_grow()
        else:
            self._try_advance_round()
        return slot

    def _send_spare(self, rec, obj) -> None:
        try:
            rec.ch.send_obj(obj)
        except (Mp4jError, OSError):
            self._spare_gone(rec, "unreachable on adopt push")

    def _spare_gone(self, rec, why: str) -> None:
        """A spare died (pre- or mid-adoption): drop it from the pool,
        un-assign any in-flight adoption and re-drive the round — the
        next spare is tried, or the round goes terminal through the
        no-spare path."""
        retry = False
        retry_evict = False
        retry_grow = False
        with self._lock:
            rec.alive = False
            if rec in self._spare_pool:
                self._spare_pool.remove(rec)
            r = rec.adopting_rank
            rec.adopting_rank = None
            rec.adopt_since = None
            if r is not None and self._round_adoptions.get(r) is rec:
                del self._round_adoptions[r]
                # a planned-eviction round retries (or abandons)
                # through its own branch — _begin_membership would
                # misread the round as a death (ISSUE 13)
                retry_evict = self._round_kind == "evict"
                retry = not retry_evict
            gs = self._grow_state
            if (rec.grow and gs is not None
                    and gs["pending"].get(r) is rec):
                del gs["pending"][r]
                retry_grow = True
            round_why = self._round_why
        self._log("M", "WARN", f"warm spare #{rec.idx} lost: {why}")
        try:
            rec.ch.close()
        except OSError:
            pass
        if retry:
            # re-enter through _begin_membership so the no-spare path
            # produces the same clean fatal as never having had one
            self._begin_membership({}, round_why or
                                   f"spare #{rec.idx} died mid-adoption")
            self._try_advance_round()
        elif retry_evict:
            # the evict branch assigns the next spare, or abandons the
            # eviction and releases a plain abort (never fatal)
            self._try_advance_round()
        if retry_grow:
            self._retry_grow_adoption(
                r, f"spare #{rec.idx} died mid-grow-adoption")

    def _release_spares(self, reason: str) -> None:
        with self._lock:
            pool = list(self._spare_pool)
            self._spare_pool = []
            threads = list(self._spare_threads)
        for rec in pool:
            try:
                rec.ch.send_obj(("release", reason))
            except (Mp4jError, OSError):
                pass
            try:
                rec.ch.close()
            except OSError:
                pass
        me = threading.current_thread()
        for t in threads:
            # the fatal path can be DRIVEN from a spare's own serve
            # thread (last spare dies mid-adoption -> no-spare fatal);
            # joining it would raise "cannot join current thread"
            if t is not me:
                t.join(2.0)

    @staticmethod
    def _mixed_progress(progress: dict) -> str | None:
        """Recovery is PER-COLLECTIVE: a round may only be released
        when every in-flight rank is retrying the SAME collective
        ordinal m, and every idle rank sits exactly one behind (it
        will enter m fresh). Any other shape means the fault spans a
        collective boundary — a rank that already completed m cannot
        re-serve its contribution (its input snapshot is gone), so
        retrying would deadlock or, worse, pair mismatched exchanges
        into silently wrong results. Returns the terminal message, or
        None when consistent."""
        inflight = {r: s for r, (s, f) in progress.items() if f}
        if not inflight:
            return None
        m = max(inflight.values())
        bad = {r: s for r, (s, f) in progress.items()
               if (f and s != m) or (not f and s != m - 1)}
        if not bad:
            return None
        detail = ", ".join(
            f"rank {r} at collective #{s}"
            f"{' (in flight)' if progress[r][1] else ' (completed)'}"
            for r, s in sorted(bad.items()))
        return (f"cannot recover: the fault spans a collective "
                f"boundary — ranks retrying collective #{m} but "
                f"{detail}; recovery is per-collective (align the "
                "schedule, e.g. with a barrier, to make this fault "
                "window recoverable)")

    def _fatal_abort(self, msg: str) -> None:
        """Fan the terminal abort out to every live rank, once. The
        message is composed HERE so all ranks raise identically."""
        with self._lock:
            if self._fatal_msg is not None:
                return
            self._fatal_msg = msg
            self._abort_since = None
        self._log("M", "ERROR", f"terminal abort: {msg}")
        for line in self.diagnose():
            self._log("M", "WARN", line)
        # flight recorder: write the manifest NOW (survivors may be
        # about to exit); serve() refreshes it once the slaves' final
        # fatal-path telemetry flushes have landed
        self._write_postmortem_manifest()
        for r in sorted(self._live_ranks()):
            self._send_to(r, ("abort_fatal", msg))
        # idle spares raise Mp4jSpareReleased instead of outliving
        # the job they were provisioned for (ISSUE 10)
        self._release_spares(msg)

    def _log(self, rank, level: str, msg: str):
        """Centralized log sink: ISO-8601 timestamps and a fixed-width
        ``[rank/size LEVEL]`` prefix so interleaved multi-rank logs are
        sortable and greppable; lines below ``MP4J_LOG_LEVEL`` are
        dropped. ``rank`` may be the string ``"M"`` for master-origin
        lines (watchdog, rendezvous)."""
        if tuning.LOG_LEVELS.get(level, tuning.LOG_LEVELS["INFO"]) \
                < self._min_level:
            return
        now = time.time()
        ts = (time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(now))
              + f".{int(now % 1 * 1000):03d}")
        who = f"{rank!s:>{self._rank_width}}"
        print(f"{ts} [{who}/{self.slave_num} {level:<5}] {msg}",
              file=self.log_stream, flush=True)

    # -- telemetry ------------------------------------------------------
    def _record_telemetry(self, rank: int, payload: dict) -> None:
        """Fold one heartbeat into the rolling cluster time-series.

        Since ISSUE 6 the beat carries DELTAS (``stats_delta`` /
        ``metrics_delta``) folded onto the rank's cumulative view;
        a full ``stats`` snapshot (older senders, external tools)
        replaces it instead. Each fold also advances the rank's and
        the cluster's rate rings, so windowed GB/s / collectives/s /
        keys/s stay derivable without a second pass."""
        progress = payload.get("progress") or {}
        now = time.monotonic()
        audit_lines: list[str] = []
        health_alerts: list[dict] = []
        with self._lock:
            live = set(range(self.slave_num)) - set(self._departed)
            new_divergences: list[dict] = []
            if "audit_delta" in payload:
                # verification happens as records complete — a flagged
                # divergence is logged within one heartbeat of the last
                # rank's record arriving; log lines emitted OUTSIDE the
                # lock below
                before_div = self._auditor.divergence_total
                audit_lines = self._auditor.fold(
                    rank, payload.get("audit_delta"), live)
                grew = self._auditor.divergence_total - before_div
                if grew:
                    new_divergences = list(
                        self._auditor.divergences)[-grew:]
            if self._health is not None:
                # the health plane folds the SAME beat: baselines,
                # detectors, the online dominator over the shipped
                # cells, and audit-divergence escalation — alert
                # dispatch (log + push to the subject rank) happens
                # outside the lock below
                health_alerts = self._health.fold(
                    rank, payload, now, live)
                if new_divergences:
                    health_alerts.extend(self._health.note_audit(
                        new_divergences, live))
            prev = self._telemetry.get(rank)
            if "stats_delta" in payload:
                stats = stats_mod.merge_snapshots(
                    prev["stats"] if prev else {},
                    payload.get("stats_delta") or {})
            else:
                stats = (payload.get("stats")
                         or (prev["stats"] if prev else {}))
            delta = payload.get("metrics_delta") or {}
            metrics = metrics_mod.fold_snapshot(
                (prev or {}).get("metrics") or {}, delta)
            self._cluster_metrics = metrics_mod.fold_snapshot(
                self._cluster_metrics, delta)
            self._telemetry[rank] = {
                "seq": int(progress.get("seq", 0)),
                "current": progress.get("current"),
                "last": progress.get("last"),
                "phase": progress.get("phase"),
                "current_secs": float(progress.get("current_secs", 0.0)),
                # per-rank recovery epoch (ISSUE 10): `mp4j-scope
                # live` renders it next to the roster badges
                "epoch": int(progress.get("epoch", 0)),
                "stats": stats,
                "metrics": metrics,
                "mono": now,
                # per-rank tuner document (ISSUE 15): decisions
                # applied/would-apply, trip state — `mp4j-scope tuner`
                "tuner": payload.get("tuner"),
            }
            win = self._rank_windows.get(rank)
            if win is None:
                win = self._rank_windows[rank] = metrics_mod.RateWindow(
                    self._metrics_window)
            totals = self._stats_totals(stats)
            win.note(now, totals)
            # running cluster totals: add this rank's movement since
            # its last fold — O(1 rank) per beat, not a re-sum of every
            # rank's whole stats table under the master lock
            before = self._rank_totals.get(rank, {})
            for k, v in totals.items():
                self._cluster_totals[k] = (self._cluster_totals.get(k, 0)
                                           + v - before.get(k, 0))
            self._rank_totals[rank] = totals
            self._cluster_window.note(now, self._cluster_totals)
        for line in audit_lines:
            self._log("M", "ERROR", line)
        self._dispatch_health_alerts(health_alerts)
        self._tuner_tick(new_divergences, rank=rank,
                         tuner_doc=payload.get("tuner"))

    def _dispatch_health_alerts(self, alerts: list[dict]) -> None:
        """Emit freshly minted health alerts: one master log line
        each, plus a control-plane push to the SUBJECT rank (its
        recovery log and durable sink make the verdict durable). A
        dead/missing subject's alert lands on the lowest live rank
        instead — the evidence must outlive the patient."""
        if not alerts:
            return
        live = self._live_ranks()
        for ev in alerts:
            level = ("ERROR" if ev.get("to") in (
                "SUSPECT", "EVICT_RECOMMENDED", "DEAD") else "WARN")
            self._log("M", level,
                      "health: " + health_mod.format_alert(ev))
            target = ev.get("rank")
            if ev.get("to") == "DEAD" or target not in live:
                # never push a DEAD verdict at its own subject — the
                # channel is the thing that just died, and the failed
                # push would re-enter the death path as "unreachable
                # on push"; the evidence lands on the lowest OTHER
                # live rank instead
                target = next((r for r in sorted(live)
                               if r != ev.get("rank")), None)
            if target is not None and 0 <= target < len(self._slots):
                self._send_to(target, ("health_alert", ev))

    def _autoscale_event(self, ev: dict, level: str = "WARN") -> None:
        """Land one structured autoscaler event everywhere at once
        (ISSUE 13, the repo precedent): master log line, plus the
        health-alert control push to the lowest live rank — whose
        recovery log and durable sink make the action history outlive
        the master, and whose ``alerts`` records interleave actions
        with verdict transitions in every ``mp4j-scope health``
        timeline. Called by the autoscaler WITHOUT the master lock
        held (the push takes per-slot locks only)."""
        self._log("M", level,
                  "autoscale: " + health_mod.format_alert(ev))
        target = next(iter(sorted(self._live_ranks())), None)
        if target is not None and 0 <= target < len(self._slots):
            self._send_to(target, ("health_alert", ev))

    def autoscale_status(self) -> dict | None:
        """The autoscaler document (ISSUE 13): mode, per-action
        counters, observed (would-be) actions, budget, circuit-breaker
        state, the in-flight action and the bounded event history
        (schema: resilience.autoscaler.Autoscaler.status). None when
        ``MP4J_AUTOSCALE=off``."""
        return (self._autoscaler.status()
                if self._autoscaler is not None else None)

    # -- self-tuning data plane, master half (ISSUE 15) ----------------
    def _tuner_event(self, kind: str, msg: str,
                     rank: int | None = None,
                     level: str = "WARN") -> dict:
        """Mint + dispatch one structured tuner event through the
        health-alert pipe (the autoscaler precedent): master log line
        plus a control push to the lowest live rank, whose recovery
        log and durable sink make the history outlive the master.
        Ids are negative in a range disjoint from the autoscaler's
        (-1e6 - seq) so timeline dedup can never collide. Called
        WITHOUT the master or tuner lock held."""
        with self._tuner_lock:
            ctl = self._tuner_ctl
            if ctl is None:
                # operator-driven request_tuner_leaders with the
                # controller off: still log + dispatch, nothing to
                # record
                ctl = {"event_seq": int(time.monotonic() * 1000) % 1000,
                       "mode": "off", "events": []}
            ctl["event_seq"] += 1
            ev = {"id": -(1_000_000 + ctl["event_seq"]),
                  "wall": time.time(), "kind": "tuner", "event": kind,
                  "rank": rank, "mode": ctl["mode"], "msg": msg}
            ctl["events"] = (ctl["events"] + [ev])[-32:]
        self._log("M", level, "tuner: " + health_mod.format_alert(ev))
        target = next(iter(sorted(self._live_ranks())), None)
        if target is not None and 0 <= target < len(self._slots):
            self._send_to(target, ("health_alert", ev))
        return ev

    def _tuner_tick(self, new_divergences: list[dict],
                    rank: int | None = None,
                    tuner_doc: dict | None = None) -> None:
        """One controller evaluation, run after every telemetry fold:
        (1) the AUDIT RAIL — any fresh cross-rank digest divergence
        trips every rank's tuner back to static defaults, latched for
        the job (re-pushed to any rank whose heartbeat shows an
        untripped tuner — a replacement/grow joiner constructs fresh
        and must inherit the latch); (2) the DOMINATOR watch — feed
        the health engine's cause-aware rows to the pure
        leader-demotion policy and, in act mode, actuate through a
        fenced topology update. ``rank``/``tuner_doc`` describe the
        heartbeat that triggered this tick."""
        ctl = self._tuner_ctl
        if ctl is None:
            return
        trip_why = None
        proposal = None
        relatch = None
        revert_overrides = False
        with self._tuner_lock:
            if new_divergences and ctl["tripped"] is None:
                d = new_divergences[0]
                trip_why = (f"cross-rank audit divergence at "
                            f"collective #{d.get('seq')}: "
                            f"{str(d.get('err'))[:160]}")
                ctl["tripped"] = trip_why
                revert_overrides = bool(ctl["overrides"])
            elif ctl["tripped"] is not None:
                # latched: maintenance only — re-latch late joiners
                # whose fresh tuner reports untripped, and keep
                # retrying the fenced revert of any leader overrides
                # still live ("back to static defaults" covers the
                # topology too; the fence may have been busy)
                if (tuner_doc is not None
                        and not tuner_doc.get("tripped")
                        and rank is not None):
                    relatch = (rank, ctl["tripped"])
                revert_overrides = bool(ctl["overrides"])
            elif (self._health is not None
                  and time.monotonic() - ctl["last_action"]
                  >= self._tuner_cooldown):
                rows = self._health.dominator_rows()
                with self._lock:
                    roster = list(self._roster)
                groups = tuner_mod.host_groups(roster)
                proposal = tuner_mod.decide_leaders(
                    rows, groups, ctl["overrides"])
                if proposal is not None:
                    ctl["last_action"] = time.monotonic()
        if relatch is not None:
            self._send_to(relatch[0], ("tuner_trip", relatch[1]))
        if trip_why is not None:
            for r in sorted(self._live_ranks()):
                self._send_to(r, ("tuner_trip", trip_why))
            self._tuner_event("trip", trip_why, level="ERROR")
        if revert_overrides:
            # fenced topology revert; a busy fence/round returns
            # False and the next tick retries
            self.request_tuner_leaders({})
            return
        if trip_why is not None or proposal is None:
            return
        if ctl["mode"] != "act":
            self._tuner_event(
                "would_demote",
                f"would demote leader(s) to {proposal} "
                "(observe mode — no action)")
            return
        if not self.request_tuner_leaders(proposal):
            self._tuner_event(
                "demote_skipped",
                f"leader demotion to {proposal} could not start "
                "(round/fence in flight?) — retrying after cooldown")

    def request_tuner_leaders(self, overrides: dict[int, int]) -> bool:
        """Apply a tuner leader-override map job-wide through a FENCE
        (callable by an operator too): park every live rank at the
        same outermost-collective boundary, push ``tuner_leaders``,
        release. Unlike the eviction fence nothing is torn down and
        no spare is needed — a fence that cannot complete cancels
        with zero disruption and the controller retries after its
        cooldown. Returns False when the request cannot start (a
        round or fence already open, rendezvous incomplete)."""
        with self._lock:
            ok = (self._fatal_msg is None
                  and self._abort_since is None
                  and self._grow_state is None
                  and self._evict_fence is None
                  and len(self._slots) >= self.slave_num)
            if not ok:
                return False
            self._fence_seq += 1
            token = self._fence_seq
            live = set(range(self.slave_num)) - set(self._departed)
            self._evict_fence = {
                "token": token, "kind": "tuner", "rank": None,
                "payload": {int(k): int(v)
                            for k, v in (overrides or {}).items()},
                "why": "tuner leader update", "acks": {}, "goal": 0,
                "since": time.monotonic()}
        self._log("M", "WARN",
                  f"tuner: fencing the job at the next collective "
                  f"boundary to apply leader overrides {overrides}")
        for r in sorted(live):
            self._send_to(r, ("fence", token))
        self._check_fence()
        return True

    def tuner_status(self) -> dict | None:
        """The self-tuning data plane's master document (ISSUE 15;
        None with ``MP4J_TUNER=off``): mode, live leader overrides,
        demotion count, trip state, recent controller events, and the
        per-rank tuner summaries from the heartbeats."""
        ctl = self._tuner_ctl
        if ctl is None:
            return None
        with self._tuner_lock:
            doc = {k: (dict(v) if isinstance(v, dict) else
                       list(v) if isinstance(v, list) else v)
                   for k, v in ctl.items() if k != "event_seq"}
        with self._lock:
            doc["ranks"] = {r: t.get("tuner")
                            for r, t in self._telemetry.items()
                            if t.get("tuner") is not None}
        return doc

    def _handle_diagnose(self, rank: int, payload: dict) -> None:
        """A slave's bounded collective wait expired: refresh its table
        entry from the report itself (fresher than its last heartbeat),
        then log the cluster-wide diagnosis — ONCE per incident. When
        one rank stalls, every other rank's bounded wait expires in the
        same window; without the debounce (keyed on the cluster's max
        sequence number) a 256-rank job would bury the one useful
        report under ~N full per-rank dumps."""
        self._record_telemetry(rank, payload)
        self._log(rank, "ERROR",
                  f"collective '{payload.get('collective')}' failed: "
                  f"{payload.get('error')}")
        with self._lock:
            incident = max((t["seq"] for t in self._telemetry.values()),
                           default=0)
            repeat = incident == self._diag_incident_seq
            self._diag_incident_seq = incident
        if repeat:
            self._log("M", "WARN",
                      f"rank {rank} reports the same incident (max seq "
                      f"{incident}) — full diagnosis already logged above")
            return
        for line in self.diagnose():
            self._log("M", "WARN", line)

    def _snapshot_table(self) -> dict[int, dict]:
        """One heartbeat-table snapshot (progress fields + age) —
        the shared shape behind the diagnosis, the metrics document
        and the postmortem manifest. Caller must NOT hold the lock."""
        now = time.monotonic()
        with self._lock:
            return {r: {**{k: t.get(k) for k in
                           ("seq", "current", "last", "phase",
                            "current_secs", "epoch")},
                        "age": now - t["mono"]}
                    for r, t in self._telemetry.items()}

    def diagnose(self) -> list[str]:
        """Render the hang/straggler diagnosis from the heartbeat
        table (obs.telemetry.render_diagnosis)."""
        return telemetry_mod.render_diagnosis(self._snapshot_table(),
                                              self.slave_num)

    def cluster_stats(self) -> dict[str, dict]:
        """Cross-rank skew per collective family from the latest
        heartbeat stats snapshots (schema:
        obs.telemetry.cluster_skew)."""
        with self._lock:
            per_rank = {r: t["stats"] for r, t in self._telemetry.items()
                        if t.get("stats")}
        return telemetry_mod.cluster_skew(per_rank)

    def format_cluster_stats(self) -> str:
        """The ``mp4j-scope report`` table, live from the master."""
        return telemetry_mod.format_skew(self.cluster_stats())

    # -- live metrics plane (ISSUE 6) -----------------------------------
    def _start_metrics_server(self, host: str, port: int) -> None:
        """Bind the control-plane HTTP metrics endpoint. Loopback by
        default (host "" would mean every interface for the DATA
        master socket too, but metrics add nothing a peer needs — an
        operator scrapes where the master runs, or passes an explicit
        host)."""
        master = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):         # noqa: N802
                if self.path in ("/metrics", "/metrics/"):
                    body = metrics_mod.to_prometheus(
                        master.metrics_doc()).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path in ("/metrics.json", "/json"):
                    body = json.dumps(master.metrics_doc()).encode()
                    ctype = "application/json"
                elif self.path in ("/health.json", "/health"):
                    # the verdict document over HTTP (ISSUE 13
                    # satellite): external orchestrators — a k8s
                    # operator, a cron — read evict recommendations
                    # without being in-process. Stamped with the job
                    # identity (ISSUE 18) so a fleet scraper can
                    # correlate it with /metrics.json and detect a
                    # master restart; the health keys stay `enabled:
                    # false` (not JSON null) under MP4J_HEALTH=0 so
                    # the stamp always has a document to ride
                    hdoc = master.health_status() or {"enabled": False}
                    body = json.dumps(
                        {**hdoc, **master.job_doc()}).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                # control-plane responses are point-in-time telemetry:
                # any intermediary cache would hand a fleet scraper a
                # stale document that looks fresh (ISSUE 18 satellite)
                self.send_header("Cache-Control", "no-store")
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # scrapes are not log lines
                pass

        srv = http.server.ThreadingHTTPServer(
            (host or "127.0.0.1", port), Handler)
        srv.daemon_threads = True
        self._metrics_server = srv
        self.metrics_port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mp4j-metrics-http").start()

    def _stop_metrics_server(self) -> None:
        srv, self._metrics_server = self._metrics_server, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()

    @staticmethod
    def _stats_totals(stats: dict) -> dict[str, float]:
        """Cumulative totals the rate windows differentiate."""
        return {
            "bytes": sum(e.get("bytes_sent", 0) + e.get("bytes_recv", 0)
                         for e in stats.values()),
            "collectives": sum(e.get("calls", 0)
                               for e in stats.values()),
            "keys": sum(e.get("keys", 0) for e in stats.values()),
        }

    def job_doc(self) -> dict:
        """The job-identity stamp (ISSUE 18) both control-plane
        endpoints carry at top level: ``job_id`` (fresh per master —
        a changed id at the same URL IS a restart), the master's
        start wall time and the roster generation (bumped at every
        roster publication). Everything a fleet scraper needs to
        correlate the two documents and detect restarts without
        heuristics."""
        with self._lock:
            return {"job_id": self.job_id,
                    "started_wall": self.started_wall,
                    "roster_gen": self._roster_gen}

    def metrics_doc(self) -> dict:
        """The metrics document both endpoint formats serve: per-rank
        progress/stats/rates plus the cluster aggregate (summed stats,
        folded histograms, windowed rates). Plain JSON-ready dicts —
        ``obs.metrics.to_prometheus`` renders the text form."""
        now = time.monotonic()
        # controller status sampled OUTSIDE the master lock (lock
        # discipline: the controller never holds its own lock while
        # calling master methods, and this order — controller lock
        # only, then master lock — can never cycle)
        autoscale_status = (self._autoscaler.status()
                            if self._autoscaler is not None else None)
        tuner_status = self.tuner_status()
        with self._lock:
            roster_gen = self._roster_gen
            roster = self._roster
            ranks: dict[str, dict] = {}
            for r in sorted(self._telemetry):
                t = self._telemetry[r]
                win = self._rank_windows.get(r)
                # snapshots/aggregates are handed out by REFERENCE:
                # every fold/merge builds a NEW object (the previous
                # one is never mutated), so readers outside the lock
                # see a consistent frozen view — no per-scrape deep
                # copy of the whole fleet's stats under the lock
                ranks[str(r)] = {
                    "progress": {k: t.get(k) for k in
                                 ("seq", "current", "last", "phase",
                                  "current_secs", "epoch")},
                    "age": now - t["mono"],
                    "stats": t["stats"],
                    "rates": win.rates() if win is not None else {},
                    "histograms": (t.get("metrics") or {}).get(
                        "histograms", {}),
                    # registry counters/gauges ride the doc since
                    # ISSUE 9 — the sink series (sink/bytes,
                    # sink/dropped_records, sink/lag_secs) render per
                    # rank in Prometheus and in `mp4j-scope live`
                    "counters": (t.get("metrics") or {}).get(
                        "counters", {}),
                    "gauges": (t.get("metrics") or {}).get(
                        "gauges", {}),
                    # roster host fingerprint (ISSUE 18): the key the
                    # fleet poller folds co-residency on — two jobs'
                    # ranks with EQUAL non-empty fingerprints share a
                    # host; "" means the rank opted out (MP4J_SHM=0)
                    "host_fp": (str(roster[r][2])
                                if 0 <= r < len(roster) else ""),
                }
            cluster_rates = self._cluster_window.rates()
            cluster_metrics = self._cluster_metrics
            audit_status = self._auditor.status()
            membership_status = self._membership_status_locked()
            health_status = (self._health.status()
                             if self._health is not None else None)
        cluster_stats = stats_mod.merge_snapshots(
            *(info["stats"] for info in ranks.values()))
        for r, info in ranks.items():
            info["audit_seq"] = int(
                audit_status["rank_seq"].get(r, 0))
        serve_status = _serve_section(ranks,
                                      cluster_metrics["histograms"])
        return {
            # job identity at top level (ISSUE 18): same fields as
            # job_doc(), sampled under the SAME lock hold as the rank
            # table so a scraper never sees a roster_gen from one
            # roster paired with ranks from another
            "job_id": self.job_id,
            "started_wall": self.started_wall,
            "roster_gen": roster_gen,
            "slave_num": self.slave_num,
            "window_secs": self._metrics_window,
            # heartbeat period (ISSUE 12 satellite): the live view
            # needs it to annotate a stale rank's derived rate columns
            "hb_secs": self._hb_secs,
            "ranks": ranks,
            "cluster": {
                "stats": cluster_stats,
                "rates": cluster_rates,
                "histograms": cluster_metrics["histograms"],
                "audit": audit_status,
                "membership": membership_status,
                "health": health_status,
                "autoscale": autoscale_status,
                "tuner": tuner_status,
                "serve": serve_status,
            },
        }

    def serve_status(self) -> dict | None:
        """The master's serve-roster surface (ISSUE 19): the folded
        serve section of :meth:`metrics_doc` — QPS, latency
        quantiles, cache hit rate, degraded-batch count — or ``None``
        when no rank has reported serve traffic (a pure training
        job). The autoscaler's load-following policy and
        ``mp4j-scope live/fleet`` read exactly this."""
        return self.metrics_doc()["cluster"]["serve"]

    def _membership_status_locked(self) -> dict:
        """ONE definition of the membership snapshot (availability
        predicate included) for every surface that renders it — the
        metrics doc, :meth:`membership_status` and the postmortem
        manifest must never disagree. Caller holds the lock."""
        return self._membership.status(
            spares_available=sum(
                1 for s in self._spare_pool
                if s.alive and s.adopting_rank is None),
            spares_total=self._spare_seq)

    def membership_status(self) -> dict:
        """The elastic-membership document (ISSUE 10): mode, counters,
        spare availability, per-rank badges and the bounded event
        history (schema: resilience.membership.MembershipLog.status)."""
        with self._lock:
            return self._membership_status_locked()

    def audit_status(self) -> dict:
        """The cluster audit document (ISSUE 8): last cross-rank-
        verified collective ordinal, divergence count, recent
        divergence details (schema: obs.audit.ClusterAuditor.status).
        All zeros unless slaves run ``MP4J_AUDIT=verify|capture``."""
        with self._lock:
            return self._auditor.status()

    def health_status(self) -> dict | None:
        """The health plane's verdict document (ISSUE 12) — THE
        operator hook the future elastic autoscaler calls: per-rank
        state (``HEALTHY``/``DEGRADED``/``SUSPECT``/
        ``EVICT_RECOMMENDED``/``DEAD``) with detector-pressure
        evidence, the ``evict_recommended`` list, dominator window
        shares/streak, onset count and the recent alert tail (schema:
        obs.health.HealthEngine.status). This plane only ever
        RECOMMENDS — acting on a verdict (replacing a SUSPECT rank
        from a spare, shrinking around an EVICT_RECOMMENDED one) is
        the caller's decision. None when ``MP4J_HEALTH=0``."""
        with self._lock:
            return (self._health.status()
                    if self._health is not None else None)

    def _write_postmortem_manifest(self) -> None:
        """Flight-recorder manifest (once per write site, idempotent
        overwrite): only on a terminal abort — a clean job leaves no
        postmortem."""
        autoscale_status = (self._autoscaler.status()
                            if self._autoscaler is not None else None)
        with self._lock:
            reason = self._fatal_msg
            departed = dict(self._departed)
            audit_status = self._auditor.status()
            membership_status = self._membership_status_locked()
            health_status = (self._health.status()
                             if self._health is not None else None)
        if not self._postmortem_dir or reason is None:
            return
        # ONE table snapshot feeds both fields, so the manifest's
        # diagnosis and table describe the same instant
        table = self._snapshot_table()
        try:
            postmortem_mod.write_master_manifest(
                self._postmortem_dir, slave_num=self.slave_num,
                reason=reason, table=table, departed=departed,
                diagnosis=telemetry_mod.render_diagnosis(
                    table, self.slave_num),
                audit=audit_status,
                sink_dir=self._sink_dir or None,
                membership=membership_status,
                health=health_status,
                autoscale=autoscale_status)
        except OSError:
            pass  # best-effort: the job is already terminal

    def _watchdog_loop(self):
        """Diagnose stalled barriers, then ACT on them (ISSUE 5).

        A generation some ranks reached ``stall_timeout`` seconds ago
        while others never arrived is the mismatched-schedule deadlock
        signature — log the diagnosis once per generation (the PR-3
        behavior). A generation (or an open abort round) still
        incomplete after ``dead_rank_secs`` escalates to the terminal
        abort fan-out: the whole cluster raises one clean error instead
        of each rank relying on its local timeout — the watchdog is no
        longer log-only. ``stall_timeout=None`` disables the diagnosis
        only; ``dead_rank_secs=inf`` disables the escalation only."""
        bounds = [t for t in (self.stall_timeout, self.dead_rank_secs)
                  if t is not None and t != float("inf")]
        tick = min(1.0, max(0.05, min(bounds) / 4)) if bounds else 1.0
        while not self._stop.wait(tick):
            now = time.monotonic()
            stalled, fatal = [], None
            escalate: dict[int, str] = {}   # rank -> why (elastic)
            lost_spares = []
            with self._lock:
                round_open = self._abort_since is not None
                for gen, since in self._barrier_since.items():
                    if gen not in self._barrier_waiting:
                        continue
                    age = now - since
                    if (age > self.dead_rank_secs
                            and self._fatal_msg is None
                            # a barrier waiting out a membership round
                            # (the joiner has not re-arrived yet) is
                            # the round's business, not a new death
                            and not (self.elastic != "off"
                                     and round_open)):
                        missing = sorted(
                            set(range(self.slave_num))
                            - set(self._barrier_waiting[gen]))
                        fatal = (f"barrier gen {gen} stalled for "
                                 f"{age:.1f}s waiting on ranks "
                                 f"{missing}; aborting the job")
                        if self.elastic != "off":
                            for r in missing:
                                escalate.setdefault(
                                    r, f"barrier gen {gen} stalled "
                                    f"{age:.1f}s without it")
                    elif (self.stall_timeout is not None
                            and age > self.stall_timeout
                            and gen not in self._diagnosed_gens):
                        self._diagnosed_gens.add(gen)
                        stalled.append(
                            (gen, list(self._barrier_waiting[gen]), age))
                if (fatal is None and round_open
                        and now - self._abort_since > self.dead_rank_secs):
                    missing = sorted(set(range(self.slave_num))
                                     - set(self._departed)
                                     - self._abort_acks)
                    if missing:
                        fatal = (f"abort round -> epoch "
                                 f"{self._abort_epoch} stalled: no "
                                 f"teardown ack from ranks "
                                 f"{missing}; aborting the job")
                        if self.elastic != "off":
                            for r in missing:
                                escalate.setdefault(
                                    r, "no teardown ack within "
                                    f"{self.dead_rank_secs:.1f}s")
                    elif self._round_kind in ("replace", "shrink",
                                              "evict"):
                        # acks complete but the membership half never
                        # finished (manifest or adoption wedged past
                        # every narrower deadline): terminal
                        fatal = (f"membership round -> epoch "
                                 f"{self._abort_epoch} stalled for "
                                 f"{now - self._abort_since:.1f}s; "
                                 "aborting the job")
                # spare-adoption deadline (ISSUE 10): a spare that
                # never acks its adoption burns one deadline, not the
                # whole recovery budget — the next spare is tried
                for r, rec in list(self._round_adoptions.items()):
                    if (rec.adopt_since is not None
                            and now - rec.adopt_since > self._adopt_secs):
                        lost_spares.append(rec)
                # grow adoptions share the deadline (ISSUE 13)
                if self._grow_state is not None:
                    for r, rec in list(
                            self._grow_state["pending"].items()):
                        if (rec.adopt_since is not None
                                and now - rec.adopt_since
                                > self._adopt_secs):
                            lost_spares.append(rec)
                # a resize generation stalled past the dead-rank
                # threshold means a rank never reached the boundary —
                # same escalation as a stalled barrier (ISSUE 13)
                for gen, since in list(self._resize_since.items()):
                    if gen not in self._resize_waiting:
                        continue
                    age = now - since
                    if (age > self.dead_rank_secs
                            and self._fatal_msg is None
                            and fatal is None
                            and not (self.elastic != "off"
                                     and round_open)):
                        missing = sorted(
                            set(range(self.slave_num))
                            - set(self._resize_waiting[gen]))
                        fatal = (f"resize gen {gen} stalled for "
                                 f"{age:.1f}s waiting on ranks "
                                 f"{missing}; aborting the job")
                        if self.elastic != "off":
                            for r in missing:
                                escalate.setdefault(
                                    r, f"resize gen {gen} stalled "
                                    f"{age:.1f}s without it")
            for gen, ranks, age in stalled:
                missing = sorted(set(range(self.slave_num)) - set(ranks))
                self._log("M", "WARN",
                          f"barrier gen {gen} stalled for {age:.1f}s: "
                          f"ranks {sorted(ranks)} waiting on ranks "
                          f"{missing}")
                for line in self.diagnose():
                    self._log("M", "WARN", line)
            for rec in lost_spares:
                self._spare_gone(
                    rec, f"adoption not acked within "
                    f"{self._adopt_secs:.1f}s")
            # the eviction fence's deadline + liveness re-checks ride
            # the same tick (ISSUE 13)
            self._check_fence()
            if fatal is not None:
                if self.elastic != "off" and escalate:
                    for r, why in escalate.items():
                        self._on_rank_dead(r, why, fatal)
                else:
                    self._fatal_abort(fatal)

    def _barrier(self, slot: _Slot, gen: int):
        release = False
        stale = False
        with self._lock:
            rank = slot.rank
            fatal = self._fatal_msg
            if fatal is None:
                if gen <= self._barrier_max_released:
                    stale = True    # see _barrier_max_released
                else:
                    waiting = self._barrier_waiting.setdefault(gen, [])
                    self._barrier_since.setdefault(gen,
                                                   time.monotonic())
                    waiting.append(rank)
                    if len(waiting) == self.slave_num:
                        release = True
                        self._barrier_max_released = max(
                            self._barrier_max_released, gen)
        if stale:
            self._send_to(rank, ("barrier_release", gen))
            return
        if fatal is not None:
            # the job is terminally aborted: never release a barrier
            # into it — a straggler arriving after the fan-out must
            # raise the fatal, not "complete" a dead job (re-push the
            # message in case the original fan-out raced its dial-in)
            self._send_to(rank, ("abort_fatal", fatal))
            return
        if release:
            # release everyone waiting on this generation
            for r in range(self.slave_num):
                self._send_to(r, ("barrier_release", gen))
            with self._lock:
                del self._barrier_waiting[gen]
                self._barrier_since.pop(gen, None)
        # a barrier arrival can complete an armed eviction fence (a
        # rank idling in a barrier IS at a boundary — ISSUE 13)
        self._check_fence()


def _serve_section(ranks: dict, cluster_hists: dict) -> dict | None:
    """Fold the per-rank serve counters/gauges into the cluster serve
    section (ISSUE 19): ``None`` for a job that never served a
    request (no zero-noise in docs or Prometheus), else QPS (the
    frontend's sliding-window gauge), p50/p99 request latency from
    the folded ``latency/serve_request`` histogram, cache hit rate
    and the degraded-batch count. Pure function of the already-built
    doc pieces — called outside the master lock."""
    counters: dict[str, float] = {}
    qps = 0.0
    for info in ranks.values():
        for k, v in (info.get("counters") or {}).items():
            if k.startswith("serve/"):
                counters[k] = counters.get(k, 0) + v
        g = (info.get("gauges") or {}).get("serve/qps")
        if g is not None:
            # one frontend owns the gauge; max() tolerates a stale
            # zero from a rank that briefly fronted earlier
            qps = max(qps, float(g))
    if not counters:
        return None
    h = cluster_hists.get("latency/serve_request")
    p50 = metrics_mod.hist_quantile(h, 0.50) if h else 0.0
    p99 = metrics_mod.hist_quantile(h, 0.99) if h else 0.0
    if h:
        # overflow-bucket quantiles come back +Inf; clamp to the
        # histogram's top finite edge so the doc stays strict JSON
        top = h["lo"] * 2.0 ** h["n"]
        p50 = min(p50, top)
        p99 = min(p99, top)
    hits = counters.get("serve/cache_hits", 0)
    misses = counters.get("serve/cache_misses", 0)
    return {
        "active": True,
        "qps": qps,
        "requests": int(counters.get("serve/requests", 0)),
        "batches": int(counters.get("serve/batches", 0)),
        "batch_deadline": int(counters.get("serve/batch_deadline", 0)),
        "batch_full": int(counters.get("serve/batch_full", 0)),
        "p50_ms": p50 * 1e3,
        "p99_ms": p99 * 1e3,
        "hit_rate": (hits / (hits + misses)
                     if (hits + misses) else None),
        "stale_rows": int(counters.get("serve/cache_stale", 0)),
        "degraded_batches": int(
            counters.get("serve/degraded_batches", 0)),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="ytk-mp4j-tpu rendezvous master")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--slaves", type=int, required=True)
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args(argv)
    m = Master(args.slaves, port=args.port, timeout=args.timeout)
    print(f"mp4j master listening on port {m.port} for {args.slaves} slaves",
          file=sys.stderr, flush=True)
    return m.serve()


if __name__ == "__main__":
    sys.exit(main())
