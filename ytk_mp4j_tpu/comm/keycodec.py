"""Persistent key<->code vocabularies for the driver-mode map collectives.

The reference's sparse ``Map<K, V>`` path re-serializes whole maps with
Kryo every call (SURVEY.md section 3c). Round 2's TPU packing did the
host half of that work per call too: ``sorted(set().union(*maps))`` over
the full key union plus a per-entry Python pack loop — measured as the
reason the device map path LOST to the socket dict loop at configs[2]
(BASELINE.md round-3 A/B: 122k vs 169k keys/sec). A real sparse-gradient
stream has a near-persistent vocabulary, so none of that work is
per-call: these codecs assign each distinct key a stable int32 code ONCE
(grow-only) and translate whole maps with vectorized numpy.

Two implementations, chosen by key type at first use:

- :class:`IntKeyCodec` — integer feature-id keys (the ytk-learn
  sparse-gradient shape). Keys never touch Python: encode is one
  ``np.fromiter`` + ``np.searchsorted`` against the sorted known-key
  table; growth merges the (pre-sorted) novelty in with one stable
  mergesort.
- :class:`ObjKeyCodec` — strings and other hashables. Encode is one
  C-level ``np.fromiter(map(dict.__getitem__, keys))`` pass; only NEW
  keys take the Python insert path, once ever.

Both cache ``meta.key_partition`` per code (the blake2b digest is by far
the most expensive per-key operation in the scatter family), and both
decode with one vectorized take from the code->key table.

Codes are dense in [0, size) and stay below ``ops.sparse.SENTINEL``.
"""

from __future__ import annotations

from operator import index as _as_index

import numpy as np

from ytk_mp4j_tpu import meta
from ytk_mp4j_tpu.exceptions import Mp4jError
from ytk_mp4j_tpu.ops.sparse import SENTINEL


def kind_of(key) -> str:
    """``"int"`` or ``"obj"`` — the ONE key-kind rule every backend
    shares (bool is NOT an int key: it would collide with 0/1 while
    claiming the fast path)."""
    if isinstance(key, (int, np.integer)) and not isinstance(key, bool):
        return "int"
    return "obj"


def codec_for_kind(kind: str):
    """A fresh codec for a :func:`kind_of` kind."""
    return IntKeyCodec() if kind == "int" else ObjKeyCodec()


def codec_for_key(key):
    """A fresh codec suited to ``key``'s type."""
    return codec_for_kind(kind_of(key))


def pack_values(values, count: int, vshape, dtype) -> np.ndarray:
    """One vectorized map-values -> ``[count, *vshape]`` conversion,
    shared by the driver, multi-host, and socket map planes so their
    accept/reject behavior cannot drift: ragged mixes raise, and scalar
    vs shape-(1,) mixes raise rather than silently flattening.

    Three paths, cheapest first:

    - ``values`` already an ndarray: validated in place — no list()
      round-trip, no copy unless the dtype needs casting;
    - scalar ``vshape``: packed straight from the (re-iterable) values
      view with ``np.fromiter`` — no boxed-pointer list materialized.
      fromiter would silently FLATTEN a stray shape-(1,) array value
      (a NumPy deprecation), so that warning is promoted to the same
      Mp4jError the asarray path raises;
    - array-valued maps: the original list + asarray conversion.
    """
    vshape = tuple(vshape)
    want = (count,) + vshape
    dt = np.dtype(dtype)
    if isinstance(values, np.ndarray):
        if values.shape != want:
            raise Mp4jError(
                f"map values must share a shape; got {values.shape} "
                f"vs expected {want}")
        try:
            return values if values.dtype == dt else values.astype(dt)
        except (TypeError, ValueError) as e:
            raise Mp4jError(
                f"map values must be {dt}-castable: {e}") from None
    if vshape == ():
        import warnings

        try:
            with warnings.catch_warnings():
                warnings.simplefilter("error", DeprecationWarning)
                return np.fromiter(values, dt, count)
        except (TypeError, ValueError, DeprecationWarning) as e:
            raise Mp4jError(
                f"map values must share shape {vshape} and be "
                f"{dt}-castable: {e}") from None
    try:
        v = np.asarray(list(values), dtype=dt)
    except (TypeError, ValueError) as e:
        raise Mp4jError(
            f"map values must share shape {vshape} and be "
            f"{dt}-castable: {e}") from None
    if v.shape != want:
        raise Mp4jError(
            f"map values must share a shape; got {v.shape} vs "
            f"expected {want}")
    return v


def pow2_bucket(x: int) -> int:
    """Smallest power of 2 >= x (x >= 1) — the shared bucket rule that
    bounds map-collective recompiles at O(log max-keys) programs on
    every backend (see ``tpu_comm._encode_maps``)."""
    return 1 << (x - 1).bit_length()


class _Partitions:
    """code -> rank cache, grown alongside the vocabulary. Placement is
    meta.key_partition on the ORIGINAL key (both backends must agree),
    computed once per (key, n). ``tail_keys(old)`` materializes only
    the keys for codes >= old — the cache-hit path does no
    per-vocabulary work at all."""

    def __init__(self):
        self._by_n: dict[int, np.ndarray] = {}

    def lookup(self, codes: np.ndarray, n: int, size: int,
               tail_keys) -> np.ndarray:
        arr = self._by_n.get(n)
        old = 0 if arr is None else arr.size
        if old < size:
            new = np.fromiter(
                (meta.key_partition(k, n) for k in tail_keys(old)),
                np.int32, size - old)
            arr = new if arr is None else np.concatenate([arr, new])
            self._by_n[n] = arr
        return arr[codes]

    def truncate(self, size: int) -> None:
        """Drop cached placements for codes >= ``size`` (vocabulary
        rollback, see the codecs' ``truncate``) — a re-grown code may
        map to a DIFFERENT key, so its cached rank would be wrong."""
        self._by_n = {n: a[:size] for n, a in self._by_n.items()}


class IntKeyCodec:
    """Grow-only int64 key <-> int32 code vocabulary (vectorized)."""

    def __init__(self):
        self._sorted = np.empty(0, np.int64)        # known keys, sorted
        self._sorted_codes = np.empty(0, np.int32)  # their codes
        self._by_code = np.empty(0, np.int64)       # code -> key
        self._partitions = _Partitions()

    @property
    def size(self) -> int:
        return self._by_code.size

    def _lookup(self, ks: np.ndarray) -> np.ndarray:
        """Codes for ``ks``; -1 where unknown."""
        if self._sorted.size == 0:
            return np.full(ks.size, -1, np.int32)
        pos = np.minimum(np.searchsorted(self._sorted, ks),
                         self._sorted.size - 1)
        return np.where(self._sorted[pos] == ks,
                        self._sorted_codes[pos], np.int32(-1))

    def encode(self, keys, count: int) -> np.ndarray:
        """int32 codes for ``keys`` (re-iterable, ``count`` long),
        assigning fresh codes to novel keys."""
        try:
            # operator.index is the exact-integer gate: floats (which
            # np.fromiter(..., int64) would silently TRUNCATE — 2.5
            # becoming key 2) raise TypeError, big ints stay exact
            ks = np.fromiter(map(_as_index, keys), np.int64, count)
        except (TypeError, ValueError, OverflowError) as e:
            raise Mp4jError(
                f"map keys must be homogeneous int64-representable "
                f"integers on this stream: {e}") from None
        codes = self._lookup(ks)
        miss = codes < 0
        if miss.any():
            new = np.unique(ks[miss])
            start = self._by_code.size
            if start + new.size >= int(SENTINEL):
                raise Mp4jError("key vocabulary overflows int32 codes")
            new_codes = np.arange(start, start + new.size, dtype=np.int32)
            self._by_code = np.concatenate([self._by_code, new])
            order = np.argsort(
                np.concatenate([self._sorted, new]), kind="stable")
            allk = np.concatenate([self._sorted, new])
            allc = np.concatenate([self._sorted_codes, new_codes])
            self._sorted, self._sorted_codes = allk[order], allc[order]
            codes = self._lookup(ks)
        return codes

    def decode(self, codes: np.ndarray) -> list:
        """Python-int keys for ``codes`` (one vectorized take)."""
        return self._by_code[codes].tolist()

    def novel(self, keys, count: int) -> list:
        """The subset of ``keys`` not yet in the vocabulary (insertion
        candidates for SPMD vocab synchronization — every member must
        grow its codec with the SAME keys in the same order)."""
        try:
            ks = np.fromiter(map(_as_index, keys), np.int64, count)
        except (TypeError, ValueError, OverflowError) as e:
            raise Mp4jError(
                f"map keys must be homogeneous int64-representable "
                f"integers on this stream: {e}") from None
        return ks[self._lookup(ks) < 0].tolist()

    def partition(self, codes: np.ndarray, n: int) -> np.ndarray:
        # tolist() -> python ints: key_partition hashes repr(key), and
        # repr(np.int64(5)) != repr(5) on numpy >= 2; only the NEW tail
        # is ever materialized (cache hits do no per-vocab work)
        return self._partitions.lookup(
            codes, n, self._by_code.size,
            lambda old: self._by_code[old:].tolist())

    def truncate(self, size: int) -> None:
        """Roll the vocabulary back to its first ``size`` codes — the
        epoch-fenced retry's codec restore (ISSUE 5): a failed map
        collective may have grown the codec on SOME ranks before the
        abort tore the decision broadcast, and re-running ``novel()``
        against the half-grown vocabulary would desynchronize code
        tables job-wide. Restoring every rank to the (identical)
        pre-attempt size re-establishes the invariant the retry's
        sync round then grows from."""
        if size >= self._by_code.size:
            return
        self._by_code = self._by_code[:size]
        keep = self._sorted_codes < size
        self._sorted = self._sorted[keep]
        self._sorted_codes = self._sorted_codes[keep]
        self._partitions.truncate(size)

    def export(self, size: int | None = None) -> list:
        """The first ``size`` keys in CODE order — the rank-replacement
        manifest's vocabulary payload (ISSUE 10). Code order is the
        load-bearing part: the joining spare rebuilds its tables with
        :meth:`import_keys`, and only an identical key->code assignment
        keeps the job-wide columnar invariant."""
        n = self._by_code.size if size is None else min(
            size, self._by_code.size)
        return self._by_code[:n].tolist()

    def import_keys(self, keys) -> None:
        """Rebuild an EMPTY codec from an exported key list, assigning
        code i to ``keys[i]`` — NOT ``encode`` (which orders a novel
        batch by sorted key, the per-call canonical rule, and would
        scramble a vocabulary grown over many calls)."""
        if self._by_code.size:
            raise Mp4jError("import_keys requires an empty codec")
        ks = np.asarray(list(keys), np.int64)
        if ks.size >= int(SENTINEL):
            raise Mp4jError("key vocabulary overflows int32 codes")
        self._by_code = ks
        codes = np.arange(ks.size, dtype=np.int32)
        order = np.argsort(ks, kind="stable")
        self._sorted = ks[order]
        self._sorted_codes = codes[order]


class ObjKeyCodec:
    """Grow-only hashable-key <-> int32 code vocabulary."""

    def __init__(self):
        self._code: dict = {}
        self._by_code: list = []
        self._arr: np.ndarray | None = None   # object array for decode
        self._partitions = _Partitions()

    @property
    def size(self) -> int:
        return len(self._by_code)

    def encode(self, keys, count: int) -> np.ndarray:
        code = self._code
        try:
            return np.fromiter(map(code.__getitem__, keys),
                               np.int32, count)
        except KeyError:
            pass
        except TypeError as e:
            raise Mp4jError(f"map keys must be hashable: {e}") from None
        start = len(self._by_code)
        # count the prospective insertions and raise BEFORE growing
        # (mirrors IntKeyCodec): a post-insert check would leave an
        # oversized vocabulary behind whose sentinel-colliding codes a
        # later all-known encode (the fast path above) happily returns
        try:
            novel = dict.fromkeys(k for k in keys if k not in code)
        except TypeError as e:
            raise Mp4jError(f"map keys must be hashable: {e}") from None
        if start + len(novel) >= int(SENTINEL):
            raise Mp4jError("key vocabulary overflows int32 codes")
        for k in novel:
            code[k] = len(self._by_code)
            self._by_code.append(k)
        if len(self._by_code) > start:
            self._arr = None   # decode table stale
        return np.fromiter(map(code.__getitem__, keys), np.int32, count)

    def decode(self, codes: np.ndarray) -> list:
        if self._arr is None or self._arr.size < len(self._by_code):
            arr = np.empty(len(self._by_code), object)
            arr[:] = self._by_code
            self._arr = arr
        return self._arr[codes].tolist()

    def novel(self, keys, count: int) -> list:
        """See :meth:`IntKeyCodec.novel`."""
        code = self._code
        return [k for k in keys if k not in code]

    def partition(self, codes: np.ndarray, n: int) -> np.ndarray:
        return self._partitions.lookup(
            codes, n, len(self._by_code),
            lambda old: self._by_code[old:])

    def truncate(self, size: int) -> None:
        """See :meth:`IntKeyCodec.truncate`."""
        if size >= len(self._by_code):
            return
        for k in self._by_code[size:]:
            del self._code[k]
        del self._by_code[size:]
        self._arr = None
        self._partitions.truncate(size)

    def export(self, size: int | None = None) -> list:
        """See :meth:`IntKeyCodec.export`."""
        n = len(self._by_code) if size is None else min(
            size, len(self._by_code))
        return list(self._by_code[:n])

    def import_keys(self, keys) -> None:
        """See :meth:`IntKeyCodec.import_keys` (insertion order IS code
        order for this codec)."""
        if self._by_code:
            raise Mp4jError("import_keys requires an empty codec")
        keys = list(keys)
        if len(keys) >= int(SENTINEL):
            raise Mp4jError("key vocabulary overflows int32 codes")
        self._by_code = keys
        self._code = {k: i for i, k in enumerate(keys)}
        self._arr = None
